import glob, gzip, json, collections
path = sorted(glob.glob("/tmp/decode_trace/**/*.trace.json.gz", recursive=True))[-1]
ev = json.loads(gzip.open(path).read())["traceEvents"]
pids = {}
for e in ev:
    if e.get("ph") == "M" and e.get("name") == "process_name":
        pids[e["pid"]] = e["args"].get("name", "")
print("processes:", pids)
tot = collections.Counter(); cnt = collections.Counter()
for e in ev:
    if e.get("ph") == "X" and "dur" in e and "TPU" in pids.get(e.get("pid"), ""):
        tot[e.get("name", "")[:70]] += e["dur"]; cnt[e.get("name", "")[:70]] += 1
for k, v in tot.most_common(25):
    print(f"{v/1e3:9.2f} ms  x{cnt[k]:<5d} {k}")
