"""ZeRO-Infinity capacity probe — largest model that fine-tunes on ONE chip.

BASELINE.md config #3 ("Llama-3-70B ZeRO-Infinity fits and fine-tunes on
v5e-8; max params/chip tracked") needs a measured per-chip datapoint:
binary-search model size with ZeRO-2 + NVMe-offloaded optimizer state
(fp32 masters + Adam moments live in swap files via ``csrc/aio``; the chip
holds bf16 params, grads, and remat'd activations). Each candidate runs in
a SUBPROCESS so an HBM OOM kills only the trial.

The offload data path runs with ``offload.aio.autotune`` (cached
``aio_bench`` sweep per swap device) and the depth-k read/Adam/write
pipeline — the PR 10 overlapped path, NOT the serial path the original
0.81 B/chip figure was measured on; the aio knobs ride along in the result
so a ledger entry says which data path produced it.

Standalone and opt-in (minutes of runtime): prints one JSON line and
appends a ``bench_capacity`` ledger entry keyed per device kind
(``by_device``) — the dev CPU harness and real chips are separate trend
series. ``--ladder dev`` runs the CPU-feasible rung set; ``--ladder full``
(default) is the TPU ladder.
"""

import argparse
import json
import subprocess
import sys
import time

#: the depth-k pipeline + self-tuned IO shape every trial runs with
AIO_CONFIG = {"autotune": True, "prefetch_depth": 2, "upload_overlap": True}

CHILD = r"""
import json, sys, time
import numpy as np
import jax
import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, TransformerConfig

hidden, layers = int(sys.argv[1]), int(sys.argv[2])
cfg = TransformerConfig(vocab_size=32000, hidden_size=hidden,
                        num_layers=layers, num_heads=hidden // 128,
                        num_kv_heads=max(1, hidden // 256),
                        max_seq_len=1024, arch="llama",
                        remat_policy="full")
model = TransformerLM(cfg)
engine, *_ = ds.initialize(model=model, config={
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-5}},
    "zero_optimization": {
        "stage": 2,
        "offload_optimizer": {"device": "nvme",
                              "nvme_path": "/tmp/dstpu_capacity_swap"},
    },
    # the closed tuning loop: the first trial sweeps the swap disk, every
    # later trial (and process) adopts the cached best threads x chunk_mb;
    # prefetch_depth k = the PR 10 read/Adam/write/upload pipeline
    "offload": {"aio": %AIO%},
    "steps_per_print": 10 ** 9,
})
rng = np.random.default_rng(0)
batch = {"input_ids": rng.integers(0, cfg.vocab_size, (1, 1024))
         .astype(np.int32)}

def one_step():
    loss = engine.forward(batch)
    engine.backward(loss)
    engine.step()
    return float(loss)

print("compiling + first step...", file=sys.stderr, flush=True)
l0 = one_step()                      # compile + first step
print(f"first step done loss={l0}", file=sys.stderr, flush=True)
t0 = time.perf_counter()
l1 = one_step()
dt = time.perf_counter() - t0
assert np.isfinite(l1), l1
# offload data-path health for the steady-state step: measured swap
# bandwidth (native per-direction busy-window stats) + how much of the
# host Adam loop sat blocked on IO (the overlap figure of merit)
rep = engine.offload_report()
sw = rep.get("swapper", {})
dev = jax.devices()[0]
print(json.dumps({"params_b": cfg.num_params_estimate() / 1e9,
                  "step_s": round(dt, 2), "loss0": round(l0, 3),
                  "loss1": round(l1, 3),
                  "device": getattr(dev, "device_kind", dev.platform),
                  "swap_read_MBps": sw.get("read_MBps", 0.0),
                  "swap_write_MBps": sw.get("write_MBps", 0.0),
                  "swap_threads": sw.get("threads"),
                  "swap_chunk_mb": sw.get("chunk_mb"),
                  "pipeline_stall_fraction":
                      rep.get("pipeline_stall_fraction", -1.0),
                  "adam_ms": rep.get("last_adam_ms"),
                  "upload_ms": rep.get("last_upload_ms")}))
"""

#: (hidden, layers) rungs with rising param counts; stop at first failure
LADDERS = {
    # TPU ladder: the 0.81 B/chip figure came from its first rungs
    "full": [(2048, 16), (2560, 20), (3072, 24), (3584, 28), (4096, 32),
             (4608, 36)],
    # CPU dev-harness ladder: same data path (NVMe swap, autotuned AIO,
    # depth-k pipeline), host-RAM-sized rungs so a restatement is minutes
    "dev": [(512, 4), (768, 6), (1024, 8)],
}


def try_size(hidden: int, layers: int, timeout: int = 2700):
    """One candidate in a subprocess (an HBM OOM kills only the trial).
    NOTE: on the tunneled dev runtime host<->device transfers run at
    ~100 MB/s, so offload steps on billion-param models take minutes —
    the capacity answer (fits / does not fit) is unaffected."""
    child = CHILD.replace("%AIO%", repr(AIO_CONFIG))  # Python literal, not JSON
    with open(f"/tmp/capacity_trial_{hidden}x{layers}.log", "w") as logf:
        try:
            p = subprocess.run([sys.executable, "-c", child, str(hidden),
                                str(layers)], stdout=subprocess.PIPE,
                               stderr=logf, text=True, timeout=timeout,
                               cwd="/root/repo")
        except subprocess.TimeoutExpired:
            return {"error": f"timeout after {timeout}s"}
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except Exception:
            continue
    return {"error": "no output (see trial log)"}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ladder", choices=sorted(LADDERS), default="full",
                    help="rung set: 'full' (TPU-scale) or 'dev' (CPU "
                         "harness restatement)")
    ap.add_argument("--timeout", type=int, default=2700,
                    help="per-rung subprocess cap (seconds)")
    args = ap.parse_args(argv)
    results = []
    best = None
    for hidden, layers in LADDERS[args.ladder]:
        t0 = time.time()
        r = try_size(hidden, layers, timeout=args.timeout)
        r.update({"hidden": hidden, "layers": layers,
                  "wall_s": round(time.time() - t0, 1)})
        results.append(r)
        print(json.dumps(r), file=sys.stderr)
        if "error" in r:
            break
        best = r
    kind = (best or {}).get("device") or next(
        (r.get("device") for r in results if r.get("device")), "unknown")
    result = {"metric": "zero_infinity_capacity_per_chip",
              "ladder": args.ladder, "device": kind, "aio": AIO_CONFIG,
              "best": best, "trials": results,
              # per-(device kind, ladder) trend series (bench_trend.py
              # by_device.*.*.params_b): dev-harness and TPU restatements
              # — and the dev ladder vs the full ladder on one device —
              # have different achievable maxima and must never be
              # compared against each other
              "by_device": ({kind: {args.ladder: {
                  "params_b": best["params_b"],
                  "step_s": best["step_s"]}}} if best else {})}
    print(json.dumps(result))
    try:  # perf-trend ledger (best-effort; never sinks the bench)
        from bench import _ledger

        _ledger(result, "bench_capacity")
    except Exception:
        pass


if __name__ == "__main__":
    main()
