"""ZeRO-Infinity capacity probe — largest model that fine-tunes on ONE chip.

BASELINE.md config #3 ("Llama-3-70B ZeRO-Infinity fits and fine-tunes on
v5e-8; max params/chip tracked") needs a measured per-chip datapoint:
binary-search model size with ZeRO-2 + NVMe-offloaded optimizer state
(fp32 masters + Adam moments live in swap files via ``csrc/aio``; the chip
holds bf16 params, grads, and remat'd activations). Each candidate runs in
a SUBPROCESS so an HBM OOM kills only the trial.

Standalone and opt-in (minutes of runtime): prints one JSON line; the
measured result is recorded in BASELINE.md and bench.py's extra.offload.
"""

import json
import subprocess
import sys
import time

CHILD = r"""
import json, sys, time
import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, TransformerConfig

hidden, layers = int(sys.argv[1]), int(sys.argv[2])
cfg = TransformerConfig(vocab_size=32000, hidden_size=hidden,
                        num_layers=layers, num_heads=hidden // 128,
                        num_kv_heads=max(1, hidden // 256),
                        max_seq_len=1024, arch="llama",
                        remat_policy="full")
model = TransformerLM(cfg)
engine, *_ = ds.initialize(model=model, config={
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-5}},
    "zero_optimization": {
        "stage": 2,
        "offload_optimizer": {"device": "nvme",
                              "nvme_path": "/tmp/dstpu_capacity_swap"},
    },
    # the closed tuning loop: the first trial sweeps the swap disk, every
    # later trial (and process) adopts the cached best threads x chunk_mb
    "offload": {"aio": {"autotune": True}},
    "steps_per_print": 10 ** 9,
})
rng = np.random.default_rng(0)
batch = {"input_ids": rng.integers(0, cfg.vocab_size, (1, 1024))
         .astype(np.int32)}

def one_step():
    loss = engine.forward(batch)
    engine.backward(loss)
    engine.step()
    return float(loss)

print("compiling + first step...", file=sys.stderr, flush=True)
l0 = one_step()                      # compile + first step
print(f"first step done loss={l0}", file=sys.stderr, flush=True)
t0 = time.perf_counter()
l1 = one_step()
dt = time.perf_counter() - t0
assert np.isfinite(l1), l1
# offload data-path health for the steady-state step: measured swap
# bandwidth (native per-direction busy-window stats) + how much of the
# host Adam loop sat blocked on IO (the overlap figure of merit)
rep = engine.offload_report()
sw = rep.get("swapper", {})
print(json.dumps({"params_b": cfg.num_params_estimate() / 1e9,
                  "step_s": round(dt, 2), "loss0": round(l0, 3),
                  "loss1": round(l1, 3),
                  "swap_read_MBps": sw.get("read_MBps", 0.0),
                  "swap_write_MBps": sw.get("write_MBps", 0.0),
                  "swap_threads": sw.get("threads"),
                  "swap_chunk_mb": sw.get("chunk_mb"),
                  "pipeline_stall_fraction":
                      rep.get("pipeline_stall_fraction", -1.0),
                  "adam_ms": rep.get("last_adam_ms"),
                  "upload_ms": rep.get("last_upload_ms")}))
"""


def try_size(hidden: int, layers: int, timeout: int = 2700):
    """One candidate in a subprocess (an HBM OOM kills only the trial).
    NOTE: on the tunneled dev runtime host<->device transfers run at
    ~100 MB/s, so offload steps on billion-param models take minutes —
    the capacity answer (fits / does not fit) is unaffected."""
    with open(f"/tmp/capacity_trial_{hidden}x{layers}.log", "w") as logf:
        try:
            p = subprocess.run([sys.executable, "-c", CHILD, str(hidden),
                                str(layers)], stdout=subprocess.PIPE,
                               stderr=logf, text=True, timeout=timeout,
                               cwd="/root/repo")
        except subprocess.TimeoutExpired:
            return {"error": f"timeout after {timeout}s"}
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except Exception:
            continue
    return {"error": "no output (see trial log)"}


def main():
    # ladder of (hidden, layers) with rising param counts; stop at first OOM
    ladder = [(2048, 16), (2560, 20), (3072, 24), (3584, 28), (4096, 32),
              (4608, 36)]
    results = []
    best = None
    for hidden, layers in ladder:
        t0 = time.time()
        r = try_size(hidden, layers)
        r.update({"hidden": hidden, "layers": layers,
                  "wall_s": round(time.time() - t0, 1)})
        results.append(r)
        print(json.dumps(r), file=sys.stderr)
        if "error" in r:
            break
        best = r
    result = {"metric": "zero_infinity_capacity_per_chip",
              "best": best, "trials": results}
    print(json.dumps(result))
    try:  # perf-trend ledger (best-effort; never sinks the bench)
        from bench import _ledger

        _ledger(result, "bench_capacity")
    except Exception:
        pass


if __name__ == "__main__":
    main()
