"""Profile one fused decode_batch @occ32 int8kv+int8w: where does the step go?"""
import glob
import gzip
import json
import os

import numpy as np
import jax

from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from deepspeed_tpu.models import TransformerConfig, TransformerLM

cfg = TransformerConfig(vocab_size=32000, hidden_size=1536, num_layers=16,
                        num_heads=12, num_kv_heads=6, max_seq_len=4096)
model = TransformerLM(cfg)
params = jax.jit(model.init)(jax.random.key(0))
eng = InferenceEngineV2(model, params=params, max_sequences=32,
                        max_seq_len=648, block_size=128,
                        kv_dtype="int8", weight_dtype="int8")
rng = np.random.default_rng(0)
uids = list(range(32))
for i in range(0, 32, 16):
    grp = uids[i:i + 16]
    eng.put(grp, [rng.integers(0, 32000, 512) for _ in grp])
toks = [0] * 32
eng.decode_batch(uids, toks, steps=16)      # warmup/compile
with jax.profiler.trace("/tmp/decode_trace"):
    eng.decode_batch(uids, toks, steps=16)

# parse: sum device durations by op name prefix
path = sorted(glob.glob("/tmp/decode_trace/**/*.trace.json.gz",
                        recursive=True))[-1]
ev = json.loads(gzip.open(path).read())["traceEvents"]
tot = {}
for e in ev:
    if e.get("ph") == "X" and "dur" in e:
        name = e.get("name", "")
        pid_name = e.get("pid")
        key = name.split(".")[0].split("(")[0][:46]
        tot[key] = tot.get(key, 0) + e["dur"]
for k, v in sorted(tot.items(), key=lambda kv: -kv[1])[:24]:
    print(f"{v/1e3:9.2f} ms  {k}")
