"""Profile one fused decode_batch: where does the step go?

Usage: python _prof_decode.py [occ] [weight_dtype] [kv_dtype] [steps]
Prints per-op device durations (XLA Ops track) grouped by op name.
"""
import glob
import gzip
import json
import sys

import numpy as np
import jax

from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from deepspeed_tpu.models import TransformerConfig, TransformerLM

occ = int(sys.argv[1]) if len(sys.argv) > 1 else 32
wd = sys.argv[2] if len(sys.argv) > 2 else "int8"
kvd = sys.argv[3] if len(sys.argv) > 3 else "int8"
steps = int(sys.argv[4]) if len(sys.argv) > 4 else 16

cfg = TransformerConfig(vocab_size=32000, hidden_size=1536, num_layers=16,
                        num_heads=12, num_kv_heads=6, max_seq_len=4096)
model = TransformerLM(cfg)
params = jax.jit(model.init)(jax.random.key(0))
kw = {} if wd == "bf16" else {"weight_dtype": wd}
eng = InferenceEngineV2(model, params=params, max_sequences=occ,
                        max_seq_len=648, block_size=128,
                        kv_dtype=kvd, **kw)
rng = np.random.default_rng(0)
uids = list(range(occ))
for i in range(0, occ, 16):
    grp = uids[i:i + 16]
    eng.put(grp, [rng.integers(0, 32000, 512) for _ in grp])
toks = [0] * occ
eng.decode_batch(uids, toks, steps=steps)      # warmup/compile
with jax.profiler.trace("/tmp/decode_trace"):
    eng.decode_batch(uids, toks, steps=steps)

path = sorted(glob.glob("/tmp/decode_trace/**/*.trace.json.gz",
                        recursive=True))[-1]
ev = json.loads(gzip.open(path).read())["traceEvents"]
tids = {}
for e in ev:
    if e.get("ph") == "M" and e.get("name") == "thread_name":
        tids[(e["pid"], e["tid"])] = e["args"].get("name", "")
tot, cnt = {}, {}
for e in ev:
    if (e.get("ph") == "X" and "dur" in e
            and tids.get((e.get("pid"), e.get("tid"))) == "XLA Ops"):
        key = e["name"][:60]
        tot[key] = tot.get(key, 0) + e["dur"]
        cnt[key] = cnt.get(key, 0) + 1
print(f"== occ={occ} w={wd} kv={kvd} steps={steps} "
      f"(per-step us = total/steps)")
for k, v in sorted(tot.items(), key=lambda kv: -kv[1])[:20]:
    print(f"{v/1e3:9.2f} ms {cnt[k]:5d}x  {v/steps:8.1f} us/step  {k}")
