"""PR 6 verification drive: unified observability layer through the PUBLIC API.

User-style script (no internal test harness): JSON config → deepspeed_tpu
.initialize → train with the registry/bridge/profile-trigger live, then the
serving batcher with tracing + HTTP probes, then error probes. Run from
/root/repo (cwd import; never clobber PYTHONPATH).
"""

import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import csv  # noqa: E402
import json  # noqa: E402
import tempfile  # noqa: E402
import urllib.request  # noqa: E402

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402

checks = []


def check(name, cond, detail=""):
    checks.append((name, bool(cond), detail))
    print(f"  [{'ok' if cond else 'FAIL'}] {name} {detail}")


work = tempfile.mkdtemp(prefix="obs_verify_")
cfg_path = os.path.join(work, "ds.json")
prof_dir = os.path.join(work, "profiles")
with open(cfg_path, "w") as f:
    json.dump({
        "train_batch_size": 16,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "steps_per_print": 2,
        "monitor_config": {"csv_monitor": {
            "enabled": True, "output_path": work, "job_name": "obsjob"}},
        "observability": {
            "enabled": True, "http_server": True, "http_port": 0,
            "train_breakdown": True, "monitor_memory": True,
            "flush_interval_steps": 2,
            "profile": {"enabled": True, "output_dir": prof_dir,
                        "capture_steps": 2, "rate_limit_s": 0.0,
                        "warmup_steps": 2}},
    }, f)

print("== training surface (8-dev CPU mesh) ==")
from deepspeed_tpu.models import TransformerLM, get_preset  # noqa: E402

engine, _opt, _dl, _sched = deepspeed_tpu.initialize(
    model=TransformerLM(get_preset("tiny")), config=cfg_path)
check("mesh is 8-device", len(jax.devices()) == 8, str(jax.devices()[0]))

rng = np.random.default_rng(0)


def batch():
    return {"input_ids": rng.integers(0, 250, (16, 16)),
            "labels": rng.integers(0, 250, (16, 16))}


for _ in range(3):
    engine.train_batch(iter([batch()]))
os.makedirs(prof_dir, exist_ok=True)
rep = engine.observability_report()
open(engine._profile_trigger.trigger_file, "w").close()  # arm from outside
for _ in range(4):
    engine.train_batch(iter([batch()]))

check("observability_report enabled+breakdown",
      rep["enabled"] and rep["breakdown"])
check("metrics server url", rep["metrics_url"], rep["metrics_url"])
body = urllib.request.urlopen(rep["metrics_url"] + "/metrics").read().decode()
check("scrape has train_step_ms gauge", "train_step_ms" in body)
check("scrape has train_fwd_ms breakdown", "train_fwd_ms" in body)
check("scrape has resilience help text", "# TYPE" in body)
hz = urllib.request.urlopen(rep["metrics_url"] + "/healthz")
check("engine /healthz 200 (no health source)", hz.status == 200)

from deepspeed_tpu.observability import get_registry  # noqa: E402

snap = get_registry().snapshot()
check("train/step_ms gauge populated",
      snap["train/step_ms"]["series"][0]["value"] > 0,
      f"{snap['train/step_ms']['series'][0]['value']:.2f}ms")
check("train/loss gauge at steps_per_print",
      snap["train/loss"]["series"][0]["value"] > 0)
prof = engine._profile_trigger.report()
check("profile capture fired once", prof["counters"]["captures"] == 1, prof)
arts = [f for r, _d, fs in os.walk(prof_dir) for f in fs]
check("xla trace artifacts on disk", len(arts) > 0, arts[:2])

csv_dir = os.path.join(work, "obsjob")
bridge_files = [f for f in os.listdir(csv_dir) if f.startswith("train_")]
check("bridge->CSV train_* files", len(bridge_files) >= 5,
      sorted(bridge_files)[:6])
with open(os.path.join(csv_dir, "train_step_ms.csv")) as f:
    rows = list(csv.reader(f))
check("train_step_ms.csv header+rows",
      rows[0] == ["step", "value", "time"] and len(rows) >= 2)

ckpt_dir = os.path.join(work, "ckpt")
engine.save_checkpoint(ckpt_dir)
snap = get_registry().snapshot()
check("train/checkpoint_ms set after save",
      snap["train/checkpoint_ms"]["series"][0]["value"] > 0)
engine.shutdown()
try:
    urllib.request.urlopen(rep["metrics_url"] + "/metrics", timeout=2)
    check("metrics server closed on shutdown", False)
except Exception:
    check("metrics server closed on shutdown", True)

print("== serving surface ==")
from deepspeed_tpu.config.config import ServingConfig  # noqa: E402
from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2  # noqa: E402
from deepspeed_tpu.observability import MetricsRegistry  # noqa: E402
from deepspeed_tpu.serving import ContinuousBatcher  # noqa: E402

reg = MetricsRegistry()
eng2 = InferenceEngineV2(TransformerLM(get_preset("tiny")), max_sequences=8,
                         max_seq_len=128, block_size=16)
eng2.enable_metrics(reg)
b = ContinuousBatcher(eng2, ServingConfig(prefill_chunk=32,
                                          default_max_new_tokens=4),
                      registry=reg)
uids = [b.submit(rng.integers(0, 250, 40)) for _ in range(3)]
b.pump(max_steps=60)
span = b.request_trace(uids[0])
check("request span complete",
      span["ttft_ms"] is not None and span["tpot_ms"] is not None
      and span["e2e_ms"] is not None, span)
check("ttft histogram populated", reg.get("serving/ttft_ms")
      .series[()].count == 3)
check("inference/* via enable_metrics incl. whole-prefill fast path",
      reg.counter("inference/tokens").value >= 3 * 40)
srv = b.serve_metrics_http()
ready = urllib.request.urlopen(srv.url + "/readyz")
check("batcher /readyz 200 when READY", ready.status == 200,
      ready.read().decode())
b.begin_drain("verify")
try:
    urllib.request.urlopen(srv.url + "/readyz", timeout=2)
    check("/readyz 503 when DRAINING", False)
except urllib.error.HTTPError as e:
    check("/readyz 503 when DRAINING", e.code == 503)
srv.close()
b.drain(timeout_s=10)

print("== error probes ==")
try:
    deepspeed_tpu.from_config({"train_batch_size": 8,
                               "observability": {"capture_stepz": 1}})
    check("typo'd observability key rejected", False)
except Exception as e:
    check("typo'd observability key rejected", "capture_stepz" in str(e),
          str(e)[:90])
try:
    reg.histogram("bad/bounds", bounds=[3.0, 1.0])
    check("non-monotone histogram bounds rejected", False)
except ValueError as e:
    check("non-monotone histogram bounds rejected", True, str(e)[:60])
try:
    reg.counter("serving/health")          # exists as a gauge
    check("type conflict rejected", False)
except ValueError:
    check("type conflict rejected", True)

fails = [c for c in checks if not c[1]]
print(f"\n{len(checks) - len(fails)}/{len(checks)} checks passed")
raise SystemExit(1 if fails else 0)
