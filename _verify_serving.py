"""User-style verification drive: public serving surface after the decode
kernel rework (gated worklist DMAs, int8 MXU score dot, int4 i32-shift
dequant, engine timing split). Run on real TPU (default) or the 8-device
CPU mesh (DSTPU_VERIFY_CPU=1)."""
import os

if os.environ.get("DSTPU_VERIFY_CPU") == "1":
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from deepspeed_tpu.models import TransformerConfig, TransformerLM

on_tpu = jax.devices()[0].platform != "cpu"
print(f"devices: {jax.devices()}")

# 1. public v1 surface: init_inference with int8 weights + generate
cfg = TransformerConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                        num_heads=4, num_kv_heads=2, max_seq_len=256,
                        arch="llama")
model = TransformerLM(cfg)
eng1 = deepspeed_tpu.init_inference(model, dtype="int8")
prompt = np.array([[3, 1, 4, 1, 5, 9, 2, 6]], np.int32)
toks = eng1.generate(prompt, max_new_tokens=8)
print("v1 int8 generate:", np.asarray(toks)[0, -8:].tolist())

# 2. v2 engine: every kv/weight dtype combo decodes coherently vs bf16
rng = np.random.default_rng(0)
params = jax.jit(model.init)(jax.random.key(0))
prompts = [rng.integers(0, cfg.vocab_size, 48) for _ in range(4)]
ref_logits = None
for wd, kvd in (("bf16", "bf16"), ("bf16", "int8"), ("int8", "int8"),
                ("int4", "int8"), ("bf16", "int4")):
    eng = InferenceEngineV2(model, params=params, max_sequences=8,
                            max_seq_len=256, block_size=128, kv_dtype=kvd,
                            weight_dtype=wd)
    r = eng.put([0, 1, 2, 3], prompts)
    out = eng.decode_batch([0, 1, 2, 3], [int(np.argmax(r[u]))
                                          for u in range(4)], steps=12)
    lg = np.stack([np.asarray(r[u], np.float32) for u in range(4)])
    if ref_logits is None:
        ref_logits = lg
        ref_toks = {u: out[u].copy() for u in out}
    else:
        rel = np.abs(lg - ref_logits).max() / np.abs(ref_logits).max()
        agree = np.mean([np.mean(out[u] == ref_toks[u]) for u in out])
        print(f"w={wd:4s} kv={kvd:4s}: prefill_rel_err={rel:.3f} "
              f"decode_token_agreement={agree:.2f}")
        # int4 on a random-init model carries ~16x int8's quantization
        # error (no outlier structure to exploit); token agreement is not
        # asserted at all — bf16 argmax ties flip on random-init logits
        assert rel < (0.8 if "int4" in (wd, kvd) else 0.25), \
            f"{wd}/{kvd} prefill diverged"
    # timing split exists and host cost is sane
    eng.put([0, 1, 2, 3], [np.array([5])] * 4)
    t = eng.timing
    assert set(t) == {"host_ms", "dispatch_ms", "fetch_ms"}, t
    assert t["host_ms"] < 50, t
    eng.flush([0, 1, 2, 3])
    del eng
print("timing split (last):", {k: round(v, 2) for k, v in t.items()})

# 3. bad-config probes still fail loudly
try:
    InferenceEngineV2(model, params=params, max_sequences=2,
                      max_seq_len=256, kv_dtype="fp7")
    raise SystemExit("kv_dtype probe failed to raise")
except ValueError as e:
    print("kv_dtype probe ok:", e)
try:
    InferenceEngineV2(model, params=params, max_sequences=2,
                      max_seq_len=256, weight_dtype="int2")
    raise SystemExit("weight_dtype probe failed to raise")
except ValueError as e:
    print("weight_dtype probe ok:", e)

print("VERIFY OK")
