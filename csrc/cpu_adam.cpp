// Host-side vectorized Adam/AdamW for offloaded optimizer states.
//
// Parity target: reference csrc/adam/cpu_adam_impl.cpp (AVX2/AVX512 Step_1/4/8
// template loops) + csrc/includes/simd.h. On TPU-VM hosts (x86 or ARM) we let the
// compiler autovectorize a branch-free fused loop (-O3 -march=native emits
// AVX2/AVX512/NEON as available) instead of hand-written intrinsics — same memory
// behavior (single pass over p/g/m/v), portable across host ISAs.
//
// C ABI so Python binds via ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// Fused Adam/AdamW step over a contiguous fp32 shard.
// adamw_mode: 1 = decoupled weight decay (AdamW), 0 = L2-into-grad (Adam).
void ds_adam_step(float* __restrict params,
                  const float* __restrict grads,
                  float* __restrict exp_avg,
                  float* __restrict exp_avg_sq,
                  int64_t n,
                  float lr, float beta1, float beta2, float eps,
                  float weight_decay, int adamw_mode, int step) {
  const float bc1 = 1.0f - std::pow(beta1, (float)step);
  const float bc2 = 1.0f - std::pow(beta2, (float)step);
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const float one_minus_b1 = 1.0f - beta1;
  const float one_minus_b2 = 1.0f - beta2;
  const float decay = weight_decay;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (!adamw_mode && decay != 0.0f) g += decay * p;
    float m = exp_avg[i] = beta1 * exp_avg[i] + one_minus_b1 * g;
    float v = exp_avg_sq[i] = beta2 * exp_avg_sq[i] + one_minus_b2 * g * g;
    float denom = std::sqrt(v) / bc2_sqrt + eps;
    params[i] = p - step_size * (m / denom)
              - (adamw_mode ? lr * decay * p : 0.0f);  // decoupled decay (AdamW)
  }
}

// Fused Adagrad (csrc/adagrad/cpu_adagrad.cpp parity).
void ds_adagrad_step(float* __restrict params,
                     const float* __restrict grads,
                     float* __restrict exp_avg_sq,
                     int64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    if (weight_decay != 0.0f) g += weight_decay * params[i];
    float v = exp_avg_sq[i] += g * g;
    params[i] -= lr * g / (std::sqrt(v) + eps);
  }
}

// Fused Lion (csrc/lion/cpu_lion_impl.cpp parity).
void ds_lion_step(float* __restrict params,
                  const float* __restrict grads,
                  float* __restrict exp_avg,
                  int64_t n, float lr, float beta1, float beta2,
                  float weight_decay) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float m = exp_avg[i];
    float c = beta1 * m + (1.0f - beta1) * g;
    float sign = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
    params[i] -= lr * (sign + weight_decay * params[i]);
    exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
  }
}

// bf16<->fp32 conversion helpers (param upload/download without numpy bf16).
void ds_fp32_to_bf16(const float* __restrict src, uint16_t* __restrict dst,
                     int64_t n) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    std::memcpy(&bits, &src[i], 4);
    uint32_t rounded = bits + 0x7FFF + ((bits >> 16) & 1);  // round-to-nearest-even
    dst[i] = (uint16_t)(rounded >> 16);
  }
}

void ds_bf16_to_fp32(const uint16_t* __restrict src, float* __restrict dst,
                     int64_t n) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits = ((uint32_t)src[i]) << 16;
    std::memcpy(&dst[i], &bits, 4);
  }
}

}  // extern "C"
