// Async tensor file I/O with a worker threadpool (DeepNVMe parity).
//
// Parity target: reference csrc/aio/ — deepspeed_py_io_handle.cpp (handle API:
// async_pread/async_pwrite/wait), deepspeed_aio_thread.cpp (worker threadpool),
// deepspeed_pin_tensor.cpp (pinned buffer pool). The reference rides libaio/io_uring
// for O_DIRECT NVMe queues; this implementation uses a pread/pwrite threadpool —
// on TPU-VM local SSD (and gcsfuse) the page cache + parallel threads saturate the
// device, and the handle semantics (submit N, overlap with compute, wait) are
// identical. O_DIRECT is honored when block-aligned.
//
// C ABI for ctypes. A handle owns a queue + worker threads; ops complete in
// submission order per worker but arbitrary order globally (same as reference).
//
// Two completion surfaces:
//   * ds_aio_wait          — barrier over every submitted op (legacy).
//   * ds_aio_submit_*      — returns an op id; ds_aio_wait_op blocks on ONE op,
//                            so a writeback no longer fences the next prefetch
//                            (the reference's per-handle completion queues).
// ds_aio_stats exposes per-direction bytes and busy-window time (union of
// in-flight intervals), so callers can report measured read/write bandwidth.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Op {
  enum Kind { READ, WRITE } kind;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t file_offset;
  bool o_direct;
  int64_t id;
};

// Per-direction transfer stats: bytes moved + busy-window time. The busy
// window is the union of in-flight intervals (inflight 0->1 opens, ->0
// closes), so overlapped ops are not double-counted and bytes/busy_ns is the
// achieved device bandwidth, not the per-op average.
struct DirStats {
  int64_t bytes = 0;
  int64_t busy_ns = 0;
  int inflight = 0;
  Clock::time_point open_t;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Op> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  std::atomic<int64_t> inflight{0};
  std::atomic<int64_t> errors{0};
  bool stop = false;
  // per-op completion state (all under mu)
  int64_t next_id = 1;
  std::unordered_set<int64_t> live;        // submitted, not yet completed
  std::unordered_map<int64_t, int> done;   // completed, not yet reaped
  DirStats stats[2];                       // [READ, WRITE]

  void worker_loop() {
    for (;;) {
      Op op;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [&] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        op = queue.front();
        queue.pop_front();
      }
      int err = run_op(op);
      if (err != 0) errors.fetch_add(1);
      {
        std::lock_guard<std::mutex> lk(mu);
        live.erase(op.id);
        done[op.id] = err;
        DirStats& d = stats[op.kind];
        if (err == 0) d.bytes += op.nbytes;
        if (--d.inflight == 0)
          d.busy_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
                           Clock::now() - d.open_t).count();
        // decrement under mu: ds_aio_wait's predicate reads inflight under
        // mu, so a notify after this locked section can't fall in the gap
        // between its predicate check and its sleep
        inflight.fetch_sub(1);
      }
      cv_done.notify_all();
    }
  }

  static int run_op(const Op& op) {
    int flags = (op.kind == Op::READ) ? O_RDONLY : (O_WRONLY | O_CREAT);
    if (op.o_direct) flags |= O_DIRECT;
    int fd = ::open(op.path.c_str(), flags, 0644);
    if (fd < 0 && op.o_direct) {  // fs may not support O_DIRECT; retry buffered
      flags &= ~O_DIRECT;
      fd = ::open(op.path.c_str(), flags, 0644);
    }
    if (fd < 0) return -1;
    char* p = (char*)op.buf;
    int64_t remaining = op.nbytes;
    int64_t off = op.file_offset;
    while (remaining > 0) {
      ssize_t n = (op.kind == Op::READ) ? ::pread(fd, p, remaining, off)
                                        : ::pwrite(fd, p, remaining, off);
      if (n <= 0) { ::close(fd); return -1; }
      p += n; off += n; remaining -= n;
    }
    if (op.kind == Op::WRITE) ::fdatasync(fd);
    ::close(fd);
    return 0;
  }
};

int64_t submit(Handle* h, Op op) {
  h->inflight.fetch_add(1);
  int64_t id;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    id = h->next_id++;
    op.id = id;
    h->live.insert(id);
    DirStats& d = h->stats[op.kind];
    if (d.inflight++ == 0) d.open_t = Clock::now();
    h->queue.push_back(std::move(op));
  }
  h->cv_submit.notify_one();
  return id;
}

}  // namespace

extern "C" {

void* ds_aio_handle_create(int num_threads) {
  auto* h = new Handle();
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  return h;
}

void ds_aio_handle_destroy(void* handle) {
  auto* h = (Handle*)handle;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->stop = true;
  }
  h->cv_submit.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

// Ticketed submission (per-op completion): returns the op id for
// ds_aio_wait_op / ds_aio_poll_op. Buffer must stay alive until the op is
// reaped (per-op wait, poll, or a full ds_aio_wait barrier).
int64_t ds_aio_submit_pwrite(void* handle, const char* path, void* buf,
                             int64_t nbytes, int64_t file_offset,
                             int o_direct) {
  return submit((Handle*)handle, Op{Op::WRITE, path, buf, nbytes, file_offset,
                                    o_direct != 0, 0});
}

int64_t ds_aio_submit_pread(void* handle, const char* path, void* buf,
                            int64_t nbytes, int64_t file_offset, int o_direct) {
  return submit((Handle*)handle, Op{Op::READ, path, buf, nbytes, file_offset,
                                    o_direct != 0, 0});
}

// async_pwrite (deepspeed_py_io_handle.cpp parity): buffer must stay alive
// until ds_aio_wait returns 0 pending.
void ds_aio_pwrite(void* handle, const char* path, void* buf, int64_t nbytes,
                   int64_t file_offset, int o_direct) {
  ds_aio_submit_pwrite(handle, path, buf, nbytes, file_offset, o_direct);
}

void ds_aio_pread(void* handle, const char* path, void* buf, int64_t nbytes,
                  int64_t file_offset, int o_direct) {
  ds_aio_submit_pread(handle, path, buf, nbytes, file_offset, o_direct);
}

// Block until op `id` completes. Returns 0 on success, -1 on IO error, 0 if
// the id was already reaped (a ds_aio_wait barrier reaps everything). An
// errored op reaped here is subtracted from the barrier's error count so one
// failure is reported exactly once.
int ds_aio_wait_op(void* handle, int64_t id) {
  auto* h = (Handle*)handle;
  std::unique_lock<std::mutex> lk(h->mu);
  h->cv_done.wait(lk, [&] { return h->done.count(id) || !h->live.count(id); });
  auto it = h->done.find(id);
  if (it == h->done.end()) return 0;  // reaped by a barrier wait
  int err = it->second;
  h->done.erase(it);
  if (err != 0) h->errors.fetch_sub(1);
  return err ? -1 : 0;
}

// Non-blocking completion probe: 1 = done ok (reaped), -1 = done with error
// (reaped), 0 = still pending. Already-reaped ids report 1.
int ds_aio_poll_op(void* handle, int64_t id) {
  auto* h = (Handle*)handle;
  std::lock_guard<std::mutex> lk(h->mu);
  auto it = h->done.find(id);
  if (it != h->done.end()) {
    int err = it->second;
    h->done.erase(it);
    if (err != 0) h->errors.fetch_sub(1);
    return err ? -1 : 1;
  }
  return h->live.count(id) ? 0 : 1;
}

// Block until every submitted op completes; returns the error count since the
// last wait (reference handle.wait() semantics). Reaps all per-op completion
// records — a subsequent ds_aio_wait_op on an already-barriered id returns 0.
int64_t ds_aio_wait(void* handle) {
  auto* h = (Handle*)handle;
  std::unique_lock<std::mutex> lk(h->mu);
  h->cv_done.wait(lk, [&] { return h->inflight.load() == 0; });
  h->done.clear();
  return h->errors.exchange(0);
}

int64_t ds_aio_pending(void* handle) {
  return ((Handle*)handle)->inflight.load();
}

// out[0..3] = read_bytes, read_busy_ns, write_bytes, write_busy_ns.
// Busy windows close only when the direction's inflight count hits zero, so
// call after a wait/barrier for exact figures.
void ds_aio_stats(void* handle, int64_t* out) {
  auto* h = (Handle*)handle;
  std::lock_guard<std::mutex> lk(h->mu);
  for (int k = 0; k < 2; ++k) {
    const DirStats& d = h->stats[k];
    int64_t busy = d.busy_ns;
    if (d.inflight > 0)
      busy += std::chrono::duration_cast<std::chrono::nanoseconds>(
                  Clock::now() - d.open_t).count();
    out[2 * k] = d.bytes;
    out[2 * k + 1] = busy;
  }
}

}  // extern "C"
