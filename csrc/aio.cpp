// Async tensor file I/O with a worker threadpool (DeepNVMe parity).
//
// Parity target: reference csrc/aio/ — deepspeed_py_io_handle.cpp (handle API:
// async_pread/async_pwrite/wait), deepspeed_aio_thread.cpp (worker threadpool),
// deepspeed_pin_tensor.cpp (pinned buffer pool). The reference rides libaio/io_uring
// for O_DIRECT NVMe queues; this implementation uses a pread/pwrite threadpool —
// on TPU-VM local SSD (and gcsfuse) the page cache + parallel threads saturate the
// device, and the handle semantics (submit N, overlap with compute, wait) are
// identical. O_DIRECT is honored when block-aligned.
//
// C ABI for ctypes. A handle owns a queue + worker threads; ops complete in
// submission order per worker but arbitrary order globally (same as reference).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Op {
  enum Kind { READ, WRITE } kind;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t file_offset;
  bool o_direct;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Op> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  std::atomic<int64_t> inflight{0};
  std::atomic<int64_t> errors{0};
  bool stop = false;

  void worker_loop() {
    for (;;) {
      Op op;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [&] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        op = queue.front();
        queue.pop_front();
      }
      if (run_op(op) != 0) errors.fetch_add(1);
      if (inflight.fetch_sub(1) == 1) {
        // lock (then release) mu before notifying so the wake can't fall in the
        // gap between ds_aio_wait's predicate check and its sleep
        { std::lock_guard<std::mutex> lk(mu); }
        cv_done.notify_all();
      }
    }
  }

  static int run_op(const Op& op) {
    int flags = (op.kind == Op::READ) ? O_RDONLY : (O_WRONLY | O_CREAT);
    if (op.o_direct) flags |= O_DIRECT;
    int fd = ::open(op.path.c_str(), flags, 0644);
    if (fd < 0 && op.o_direct) {  // fs may not support O_DIRECT; retry buffered
      flags &= ~O_DIRECT;
      fd = ::open(op.path.c_str(), flags, 0644);
    }
    if (fd < 0) return -1;
    char* p = (char*)op.buf;
    int64_t remaining = op.nbytes;
    int64_t off = op.file_offset;
    while (remaining > 0) {
      ssize_t n = (op.kind == Op::READ) ? ::pread(fd, p, remaining, off)
                                        : ::pwrite(fd, p, remaining, off);
      if (n <= 0) { ::close(fd); return -1; }
      p += n; off += n; remaining -= n;
    }
    if (op.kind == Op::WRITE) ::fdatasync(fd);
    ::close(fd);
    return 0;
  }
};

}  // namespace

extern "C" {

void* ds_aio_handle_create(int num_threads) {
  auto* h = new Handle();
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  return h;
}

void ds_aio_handle_destroy(void* handle) {
  auto* h = (Handle*)handle;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->stop = true;
  }
  h->cv_submit.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

static void submit(Handle* h, Op op) {
  h->inflight.fetch_add(1);
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.push_back(std::move(op));
  }
  h->cv_submit.notify_one();
}

// async_pwrite (deepspeed_py_io_handle.cpp parity): buffer must stay alive
// until ds_aio_wait returns 0 pending.
void ds_aio_pwrite(void* handle, const char* path, void* buf, int64_t nbytes,
                   int64_t file_offset, int o_direct) {
  submit((Handle*)handle, Op{Op::WRITE, path, buf, nbytes, file_offset,
                             o_direct != 0});
}

void ds_aio_pread(void* handle, const char* path, void* buf, int64_t nbytes,
                  int64_t file_offset, int o_direct) {
  submit((Handle*)handle, Op{Op::READ, path, buf, nbytes, file_offset,
                             o_direct != 0});
}

// Block until every submitted op completes; returns the error count since the
// last wait (reference handle.wait() semantics).
int64_t ds_aio_wait(void* handle) {
  auto* h = (Handle*)handle;
  std::unique_lock<std::mutex> lk(h->mu);
  h->cv_done.wait(lk, [&] { return h->inflight.load() == 0; });
  return h->errors.exchange(0);
}

int64_t ds_aio_pending(void* handle) {
  return ((Handle*)handle)->inflight.load();
}

}  // extern "C"
