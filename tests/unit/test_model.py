"""Model-layer tests (pattern: reference ``tests/unit/simple_model.py`` + model zoo
numeric checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import TransformerLM, TransformerConfig, get_preset
from deepspeed_tpu.models.spec import num_params


@pytest.fixture(scope="module")
def tiny_batch():
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 256, (2, 16))}


@pytest.mark.parametrize("arch", ["llama", "gpt2"])
def test_init_and_loss(arch, tiny_batch):
    model = TransformerLM(get_preset("tiny" if arch == "llama" else "tiny-gpt2"))
    params = model.init(jax.random.key(0))
    loss = model.loss_fn(params, tiny_batch)
    # random init → loss ~ ln(vocab)
    assert abs(float(loss) - np.log(256)) < 0.5


def test_param_specs_match_structure():
    model = TransformerLM(get_preset("tiny"))
    params = jax.eval_shape(model.init, jax.random.key(0))
    specs = model.param_specs()
    # same treedef → every param has a spec
    jax.tree_util.tree_map(lambda p, s: None, params, specs,
                           is_leaf=lambda x: x is None)


def test_grad_flows_everywhere(tiny_batch):
    model = TransformerLM(get_preset("tiny"))
    params = model.init(jax.random.key(0))
    grads = jax.grad(model.loss_fn)(params, tiny_batch)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in leaves)
    nonzero = sum(bool(jnp.any(g != 0)) for g in leaves)
    assert nonzero >= len(leaves) - 1  # everything except possibly unused slots


def test_scan_matches_unrolled(tiny_batch):
    import dataclasses

    cfg = get_preset("tiny")
    m_scan = TransformerLM(cfg)
    m_loop = TransformerLM(dataclasses.replace(cfg, scan_layers=False))
    params = m_scan.init(jax.random.key(0))
    l1 = m_scan.loss_fn(params, tiny_batch)
    l2 = m_loop.loss_fn(params, tiny_batch)
    # scan vs unrolled layers fuse in different orders; small fp drift is expected
    np.testing.assert_allclose(float(l1), float(l2), rtol=5e-5)


def test_gqa_heads():
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=1,
                            num_heads=8, num_kv_heads=2, max_seq_len=32)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    assert params["layers"]["attn"]["wk"].shape == (1, 64, 2 * 8)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    assert np.isfinite(float(model.loss_fn(params, batch)))


def test_labels_and_mask():
    model = TransformerLM(get_preset("tiny"))
    params = model.init(jax.random.key(0))
    ids = np.random.default_rng(1).integers(0, 256, (2, 16))
    labels = ids.copy()
    labels[:, :8] = -100  # ignored positions
    l_masked = model.loss_fn(params, {"input_ids": ids, "labels": labels})
    assert np.isfinite(float(l_masked))


def test_num_params_estimate_close():
    cfg = get_preset("tiny")
    model = TransformerLM(cfg)
    actual = num_params(model.init(jax.random.key(0)))
    est = cfg.num_params_estimate()
    assert abs(est - actual) / actual < 0.05


def test_new_family_knobs_train_under_engine(eight_devices):
    """parallel_block + shared norm + qkv/proj biases + partial rotary must
    train under the engine (zero-3 + tp shards the bias params too)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, TransformerConfig

    cfg = TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=64, arch="gpt2",
                            use_rope=True, learned_pos=False, rope_pct=0.5,
                            parallel_block=True, parallel_shared_norm=True,
                            qkv_bias=True, proj_bias=True,
                            activation="gelu_exact")
    eng, *_ = ds.initialize(model=TransformerLM(cfg), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
        "mesh": {"fsdp": 4, "tp": 2}, "steps_per_print": 100})
    assert "ln2" not in eng.params["layers"]          # shared norm
    assert "bq" in eng.params["layers"]["attn"]       # biases exist
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (8, 32))}
    losses = []
    for _ in range(4):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
