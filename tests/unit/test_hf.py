"""HF interop tests — the analog of the reference's AutoTP/checkpoint-loading
unit tests: a tiny HF Llama checkpoint must import with exact logits parity,
Mixtral must import structurally, and AutoTP spec inference must reproduce the
row/col policy on both naming families."""

import json

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_llama_ckpt(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      rms_norm_eps=1e-5, tie_word_embeddings=True)
    model = LlamaForCausalLM(cfg)
    d = str(tmp_path_factory.mktemp("hf_llama"))
    model.save_pretrained(d)
    return d, model


def test_llama_import_logits_parity(tiny_llama_ckpt):
    """Imported weights + our forward == HF forward (fp32, atol 1e-4)."""
    import torch

    from deepspeed_tpu.models.hf import load_hf_checkpoint

    path, hf_model = tiny_llama_ckpt
    model, params = load_hf_checkpoint(path, dtype="float32")
    ids = np.random.default_rng(0).integers(0, 256, (2, 16))
    ours = np.asarray(jax.jit(model.logits)(params, ids))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-3, rtol=1e-3)


def test_llama_import_trains_under_engine(tiny_llama_ckpt, eight_devices):
    """An imported checkpoint plugs straight into ds.initialize (the reference
    user journey: HF model -> deepspeed engine)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.hf import load_hf_checkpoint

    path, _ = tiny_llama_ckpt
    model, params = load_hf_checkpoint(path)
    eng, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
        "mesh": {"fsdp": 4, "tp": 2},
        "steps_per_print": 100})
    eng.params = jax.device_put(params, eng.param_sharding)
    batch = {"input_ids": np.random.default_rng(1).integers(0, 256, (8, 16))}
    losses = []
    for _ in range(3):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_llama3_rope_scaling_parity(tmp_path):
    """Llama-3.1-style rope_scaling must reproduce transformers' frequency
    banding — unscaled frequencies would silently diverge at all positions."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from deepspeed_tpu.models.hf import load_hf_checkpoint

    torch.manual_seed(1)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64,
                      rope_scaling={"rope_type": "llama3", "factor": 8.0,
                                    "low_freq_factor": 1.0,
                                    "high_freq_factor": 4.0,
                                    "original_max_position_embeddings": 32},
                      tie_word_embeddings=False)
    hf_model = LlamaForCausalLM(cfg)
    hf_model.save_pretrained(str(tmp_path))
    model, params = load_hf_checkpoint(str(tmp_path), dtype="float32")
    assert model.cfg.rope_scaling["rope_type"] == "llama3"
    ids = np.random.default_rng(2).integers(0, 128, (1, 48))
    ours = np.asarray(jax.jit(model.logits)(params, ids))
    with torch.no_grad():
        import torch as t

        theirs = hf_model(t.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-3, rtol=1e-3)


def test_mixtral_import_logits_parity(tmp_path):
    """Mixtral imports into the EP layout with the grouped (dropless) dispatch
    — which matches Mixtral's renormalized top-k routing exactly, so logits
    parity against transformers holds."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    from deepspeed_tpu.models.hf import load_hf_checkpoint

    torch.manual_seed(0)
    cfg = MixtralConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, num_local_experts=4,
                        num_experts_per_tok=2, max_position_embeddings=32)
    hf_model = MixtralForCausalLM(cfg)
    hf_model.save_pretrained(str(tmp_path))
    model, params = load_hf_checkpoint(str(tmp_path), dtype="float32")
    assert model.cfg.num_experts == 4 and model.cfg.top_k == 2
    assert model.cfg.moe_dispatch == "grouped"
    assert params["layers"]["mlp"]["w_gate"].shape == (2, 4, 32, 64)
    assert params["layers"]["mlp"]["router"].shape == (2, 32, 4)
    ids = np.random.default_rng(0).integers(0, 128, (2, 8))
    ours = np.asarray(jax.jit(model.logits)(params, ids))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("preset", ["tiny", "tiny-moe"])
def test_infer_tp_specs_matches_hand_policy(preset):
    """Name-pattern inference reproduces the family's hand-written megatron
    policy on the WHOLE tree — dense and stacked-MoE (ep on the expert dim)."""
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.models.hf import infer_tp_specs

    model = TransformerLM(get_preset(preset))
    params = jax.eval_shape(model.init, jax.random.key(0))
    specs = infer_tp_specs(params)
    hand = model.param_specs()

    def norm(tree):
        # compare per-dim entries, padding trailing Nones
        flat = jax.tree_util.tree_flatten_with_path(
            tree, is_leaf=lambda x: x is None or isinstance(x, P))[0]
        return {tuple(str(k) for k in kp): tuple(s or P()) + (None,) * 4
                for kp, s in flat}

    got, want = norm(specs), norm(hand)
    for key in want:
        assert got[key][:4] == want[key][:4], (key, got[key], want[key])
    if preset == "tiny-moe":
        assert specs["layers"]["mlp"]["w_gate"] == P(None, "ep", None, "tp")
        assert specs["layers"]["mlp"]["w_down"] == P(None, "ep", "tp", None)


def test_infer_tp_specs_hf_naming():
    from deepspeed_tpu.models.hf import infer_tp_specs

    tree = {
        "model.layers.0.self_attn.q_proj.weight": np.zeros((64, 32)),
        "model.layers.0.self_attn.o_proj.weight": np.zeros((32, 64)),
        "model.layers.0.mlp.down_proj.weight": np.zeros((32, 128)),
        "model.embed_tokens.weight": np.zeros((256, 32)),
        "model.layers.0.block_sparse_moe.experts.1.w1.weight": np.zeros((128, 32)),
        "model.norm.weight": np.zeros((32,)),
    }
    specs = infer_tp_specs(tree)
    # torch [out, in]: col-parallel shards out (dim -2), row-parallel in (dim -1)
    assert specs["model.layers.0.self_attn.q_proj.weight"] == P("tp", None)
    assert specs["model.layers.0.self_attn.o_proj.weight"] == P(None, "tp")
    assert specs["model.layers.0.mlp.down_proj.weight"] == P(None, "tp")
    assert specs["model.embed_tokens.weight"] == P("tp", None)
    # raw HF expert leaf is 2-D (expert axis = python structure): plain col
    assert specs["model.layers.0.block_sparse_moe.experts.1.w1.weight"] == \
        P("tp", None)
    assert specs["model.norm.weight"] == P(None)


# ---------------------------------------------------------------------------
# Model-family breadth (reference: inference/v2/model_implementations/ covers
# llama/mistral/mixtral/opt/phi3/qwen2/falcon/...): every family imports with
# logits parity against transformers.
# ---------------------------------------------------------------------------

def _tiny_hf(family):
    import torch
    import transformers as tr

    torch.manual_seed(0)
    if family == "mistral":
        # sliding_window=8 < T=16 so the windowed mask path is exercised
        cfg = tr.MistralConfig(vocab_size=128, hidden_size=64,
                               intermediate_size=96, num_hidden_layers=2,
                               num_attention_heads=4, num_key_value_heads=2,
                               max_position_embeddings=64, sliding_window=8,
                               attn_implementation="eager")
        return tr.MistralForCausalLM(cfg)
    if family == "qwen2":
        cfg = tr.Qwen2Config(vocab_size=128, hidden_size=64,
                             intermediate_size=96, num_hidden_layers=2,
                             num_attention_heads=4, num_key_value_heads=2,
                             max_position_embeddings=64)
        return tr.Qwen2ForCausalLM(cfg)
    if family == "phi3":
        cfg = tr.Phi3Config(vocab_size=128, hidden_size=64,
                            intermediate_size=96, num_hidden_layers=2,
                            num_attention_heads=4, num_key_value_heads=2,
                            max_position_embeddings=64, pad_token_id=0)
        return tr.Phi3ForCausalLM(cfg)
    if family == "falcon7b":  # multi-query + parallel attn + shared ln
        cfg = tr.FalconConfig(vocab_size=128, hidden_size=64,
                              num_hidden_layers=2, num_attention_heads=4,
                              ffn_hidden_size=128, multi_query=True,
                              new_decoder_architecture=False,
                              parallel_attn=True, bias=False, alibi=False,
                              max_position_embeddings=64)
        return tr.FalconForCausalLM(cfg)
    if family == "falcon40b":  # GQA + separate ln_attn/ln_mlp + biases
        cfg = tr.FalconConfig(vocab_size=128, hidden_size=64,
                              num_hidden_layers=2, num_attention_heads=4,
                              num_kv_heads=2, ffn_hidden_size=128,
                              new_decoder_architecture=True, bias=True,
                              alibi=False, max_position_embeddings=64)
        return tr.FalconForCausalLM(cfg)
    if family == "gpt_neox":  # partial rotary + parallel residual + biases
        cfg = tr.GPTNeoXConfig(vocab_size=128, hidden_size=64,
                               intermediate_size=128, num_hidden_layers=2,
                               num_attention_heads=4, rotary_pct=0.5,
                               max_position_embeddings=64,
                               attn_implementation="eager")
        return tr.GPTNeoXForCausalLM(cfg)
    if family == "gpt2":
        cfg = tr.GPT2Config(vocab_size=128, n_embd=64, n_layer=2, n_head=4,
                            n_positions=64)
        return tr.GPT2LMHeadModel(cfg)
    if family == "opt":
        cfg = tr.OPTConfig(vocab_size=128, hidden_size=64, ffn_dim=128,
                           num_hidden_layers=2, num_attention_heads=4,
                           max_position_embeddings=64, word_embed_proj_dim=64,
                           do_layer_norm_before=True)
        return tr.OPTForCausalLM(cfg)
    raise ValueError(family)


@pytest.mark.parametrize("family", ["mistral", "qwen2", "phi3", "falcon7b",
                                    "falcon40b", "gpt_neox", "gpt2", "opt"])
def test_family_import_logits_parity(family, tmp_path):
    import torch

    from deepspeed_tpu.models.hf import load_hf_checkpoint

    hf_model = _tiny_hf(family).eval()  # gpt2/opt default dropout > 0
    hf_model.save_pretrained(str(tmp_path))
    model, params = load_hf_checkpoint(str(tmp_path), dtype="float32")
    ids = np.random.default_rng(3).integers(0, 128, (2, 16))
    ours = np.asarray(jax.jit(model.logits)(params, ids))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)


def test_family_config_mapping():
    """The family switchboard: each HF config maps to the right arch knobs."""
    from deepspeed_tpu.models.hf import config_from_hf

    qwen = config_from_hf({"model_type": "qwen2", "vocab_size": 128,
                           "hidden_size": 64, "num_hidden_layers": 2,
                           "num_attention_heads": 4, "num_key_value_heads": 2,
                           "intermediate_size": 96})
    assert qwen.qkv_bias and qwen.sliding_window is None
    neox = config_from_hf({"model_type": "gpt_neox", "vocab_size": 128,
                           "hidden_size": 64, "num_hidden_layers": 2,
                           "num_attention_heads": 4, "intermediate_size": 128,
                           "rotary_pct": 0.25})
    assert neox.parallel_block and neox.rope_pct == 0.25 and neox.use_rope
    assert neox.rope_dim == 4  # head_dim 16 * 0.25
    f7 = config_from_hf({"model_type": "falcon", "vocab_size": 128,
                         "hidden_size": 64, "num_hidden_layers": 2,
                         "num_attention_heads": 4, "multi_query": True,
                         "parallel_attn": True, "bias": False})
    assert f7.parallel_block and f7.parallel_shared_norm
    assert f7.num_kv_heads == 1 and not f7.qkv_bias
    with pytest.raises(ValueError):
        config_from_hf({"model_type": "falcon", "vocab_size": 128,
                        "hidden_size": 64, "num_hidden_layers": 2,
                        "num_attention_heads": 4, "alibi": True})
    with pytest.raises(ValueError):
        config_from_hf({"model_type": "opt", "vocab_size": 128,
                        "hidden_size": 64, "num_hidden_layers": 2,
                        "num_attention_heads": 4, "ffn_dim": 128,
                        "do_layer_norm_before": False})


def test_qwen2_mixed_window_import_parity(tmp_path):
    """HF qwen2 windows only layers i >= max_window_layers (the first layers
    attend fully). The import threads window_start_layer into segmented layer
    scans; logits must match transformers on a T > window sequence through
    the train path AND the serving engines (round-2 ADVICE: the old gate was
    inverted and applied the window globally)."""
    import torch
    import transformers as tr

    from deepspeed_tpu.inference import InferenceEngine, InferenceEngineV2
    from deepspeed_tpu.models.hf import load_hf_checkpoint

    torch.manual_seed(0)
    cfg = tr.Qwen2Config(vocab_size=128, hidden_size=64, intermediate_size=96,
                         num_hidden_layers=4, num_attention_heads=4,
                         num_key_value_heads=2, max_position_embeddings=64,
                         use_sliding_window=True, sliding_window=8,
                         max_window_layers=2, attn_implementation="eager")
    hf = tr.Qwen2ForCausalLM(cfg).eval()
    hf.save_pretrained(str(tmp_path))
    model, params = load_hf_checkpoint(str(tmp_path), dtype="float32")
    assert model.cfg.sliding_window == 8
    assert model.cfg.window_start_layer == 2
    ids = np.random.default_rng(4).integers(0, 128, (2, 16))  # T=16 > win=8
    ours = np.asarray(jax.jit(model.logits)(params, ids))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)

    # serving parity: greedy decode through v1 and one packed v2 step
    e1 = InferenceEngine(model, config={"mesh": {}}, params=params)
    out = np.asarray(e1.generate(ids[:1], max_new_tokens=4))
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids[:1]), max_new_tokens=4,
                          do_sample=False).numpy()
    np.testing.assert_array_equal(out, ref)

    e2 = InferenceEngineV2(model, params=params, max_sequences=2,
                           max_seq_len=32, block_size=8)
    r = e2.put([1], [ids[0]])
    np.testing.assert_allclose(
        np.asarray(r[1], np.float32), np.asarray(ours[0, -1], np.float32),
        atol=3e-2)


def test_qwen2_window_gate_not_inverted():
    """use_sliding_window with max_window_layers >= num_layers means NO layer
    is windowed — the import must clear the window, not apply it globally."""
    from deepspeed_tpu.models.hf import config_from_hf

    base = {"model_type": "qwen2", "vocab_size": 128, "hidden_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "intermediate_size": 96,
            "use_sliding_window": True, "sliding_window": 8}
    assert config_from_hf({**base, "max_window_layers": 2}).sliding_window \
        is None
    allwin = config_from_hf({**base, "max_window_layers": 0})
    assert allwin.sliding_window == 8 and allwin.window_start_layer == 0
