"""Op numeric-parity tests (pattern: reference ``tests/unit/ops/`` — each custom
kernel vs a plain reference implementation). Pallas kernels run in interpret mode on
the CPU mesh; real-TPU parity is exercised by the verify drive."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.ops.flash_attention import flash_attention
from deepspeed_tpu.ops.quantization import (
    dequantize_blockwise, dequantize_fp8, quantize_blockwise, quantize_fp8,
)
from deepspeed_tpu.ops.rms_norm import fused_rms_norm


def _qkv(T=64, S=64, H=4, K=4, d=16, dtype=jnp.float32):
    q = jax.random.normal(jax.random.key(1), (1, T, H, d), dtype)
    k = jax.random.normal(jax.random.key(2), (1, S, K, d), dtype)
    v = jax.random.normal(jax.random.key(3), (1, S, K, d), dtype)
    return q, k, v


class TestFlashAttention:
    def test_forward_parity_causal(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_forward_gqa(self):
        q, k, v = _qkv(H=8, K=2)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_noncausal(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=False, interpret=True)
        ref = xla_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_backward_parity(self):
        q, k, v = _qkv(T=32, S=32)

        def f_flash(q, k, v):
            return flash_attention(q, k, v, causal=True, interpret=True).sum()

        def f_ref(q, k, v):
            return xla_attention(q, k, v, causal=True).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    def test_backward_gqa(self):
        q, k, v = _qkv(T=32, S=32, H=4, K=2)
        g1 = jax.grad(lambda k: flash_attention(q, k, v, interpret=True).sum())(k)
        g2 = jax.grad(lambda k: xla_attention(q, k, v).sum())(k)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-4)

    def test_uneven_block_sizes(self):
        # T=48 not divisible by default blocks → _pick_block must adapt
        q, k, v = _qkv(T=48, S=48)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("window", [1, 8, 24, 1000])
    def test_sliding_window_forward(self, window):
        """Window masking (mistral/qwen2): parity with the masked XLA path,
        incl. window=1 (self-only), window crossing block boundaries (small
        blocks force multi-block), and window > T (plain causal)."""
        q, k, v = _qkv(T=64, S=64)
        out = flash_attention(q, k, v, causal=True, window=window,
                              block_q=16, block_k=16, interpret=True)
        ref = xla_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_sliding_window_backward(self):
        q, k, v = _qkv(T=32, S=32, H=4, K=2)

        def f_flash(q, k, v):
            return flash_attention(q, k, v, causal=True, window=8,
                                   block_q=8, block_k=8,
                                   interpret=True).sum()

        def f_ref(q, k, v):
            return xla_attention(q, k, v, causal=True, window=8).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


class TestRMSNorm:
    def test_parity(self):
        x = jax.random.normal(jax.random.key(4), (4, 32, 64))
        w = jax.random.normal(jax.random.key(5), (64,)) + 1.0
        ref = np.asarray(x) / np.sqrt(
            (np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-5) * np.asarray(w)
        np.testing.assert_allclose(np.asarray(fused_rms_norm(x, w)), ref, atol=2e-5)

    def test_grad_parity(self):
        x = jax.random.normal(jax.random.key(6), (8, 64))
        w = jax.random.normal(jax.random.key(7), (64,)) + 1.0

        def ref_fn(x, w):
            inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-5)
            return (x * inv * w).sum()

        g1 = jax.grad(lambda x, w: fused_rms_norm(x, w).sum(), argnums=(0, 1))(x, w)
        g2 = jax.grad(ref_fn, argnums=(0, 1))(x, w)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


class TestQuantization:
    @pytest.mark.parametrize("bits,tol", [(8, 0.02), (4, 0.35)])
    def test_roundtrip(self, bits, tol):
        x = np.random.default_rng(0).normal(size=(4096,)).astype(np.float32)
        q, s = quantize_blockwise(x, bits=bits, group_size=512)
        d = np.asarray(dequantize_blockwise(q, s, bits=bits, shape=x.shape,
                                            dtype=jnp.float32))
        assert np.abs(d - x).max() < tol
        if bits == 8:
            assert q.dtype == jnp.int8 and q.size == x.size
        else:
            assert q.size == x.size // 2  # packed nibbles

    def test_fp8_roundtrip(self):
        x = np.random.default_rng(1).normal(size=(1024,)).astype(np.float32) * 10
        q, s = quantize_fp8(jnp.asarray(x))
        d = np.asarray(dequantize_fp8(q, s, dtype=jnp.float32))
        rel = np.abs(d - x) / (np.abs(x) + 1e-3)
        assert np.median(rel) < 0.05


def test_op_registry():
    from deepspeed_tpu.ops import ALL_OPS, get_op_builder, op_report

    assert "flash_attn" in ALL_OPS
    fn = get_op_builder("flash_attn").load()
    assert callable(fn)
    assert all(isinstance(ok, bool) for _, ok in op_report())


def test_attention_registry_has_flash():
    from deepspeed_tpu.models.transformer import _ATTENTION_IMPLS

    import deepspeed_tpu  # noqa: F401  (import registers)

    assert "flash" in _ATTENTION_IMPLS


def test_paged_attention_parity():
    """Paged kernel vs dense-gather reference (pattern: reference
    tests/unit/inference/v2/kernels numeric parity)."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.paged_attention import (paged_attention,
                                                   paged_update,
                                                   xla_paged_attention)
    rng = np.random.default_rng(0)
    B, t, H, K, d, bs, nb, nb_max = 3, 4, 8, 4, 64, 16, 24, 4
    kp = jnp.asarray(rng.normal(size=(nb + 1, bs, K, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(nb + 1, bs, K, d)), jnp.float32)
    bt = jnp.asarray(rng.permutation(nb)[:B * nb_max].reshape(B, nb_max), jnp.int32)
    pos = jnp.asarray([0, 11, 37], jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, t, H, d)), jnp.float32)
    o1 = paged_attention(q, kp, vp, bt, pos)
    o2 = xla_paged_attention(q, kp, vp, bt, pos)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-4)

    # update scatter places each valid token at its block/offset
    new = jnp.asarray(rng.normal(size=(B, t, K, d)), jnp.float32)
    valid = jnp.asarray(rng.integers(0, 2, (B, t)), bool)
    kp2 = paged_update(kp, new, bt, pos, valid)
    gpos = np.asarray(pos)[:, None] + np.arange(t)[None]
    for b in range(B):
        for j in range(t):
            pb = int(bt[b, gpos[b, j] // bs]); off = gpos[b, j] % bs
            if valid[b, j]:
                np.testing.assert_allclose(np.asarray(kp2[pb, off]),
                                           np.asarray(new[b, j]))
            else:
                np.testing.assert_allclose(np.asarray(kp2[pb, off]),
                                           np.asarray(kp[pb, off]))


class TestQuantizedMatmul:
    """Fused dequant-GEMM (reference cutlass_ops/mixed_gemm W4A16/W8A16).

    On-chip measurements (v5e, D=4096 F=14336): XLA fuses the blockwise
    dequant into the matmul — int4-base decode throughput measured 0.95-3.9x
    the bf16 GEMM depending on batch — and this Pallas kernel keeps the
    packed weights compressed all the way into VMEM for the cases XLA
    declines to fuse."""

    @pytest.mark.parametrize("bits", [4, 8])
    def test_kernel_matches_dense_reference(self, bits):
        from deepspeed_tpu.ops.quant_matmul import (
            dequantize_matmul_weight, quantize_matmul_weight,
            quantized_matmul)

        rng = np.random.default_rng(0)
        D, F = 512, 768
        w = jnp.asarray(rng.normal(size=(D, F)).astype(np.float32) / 30)
        packed, scales = quantize_matmul_weight(w, bits=bits, group=128)
        wd = dequantize_matmul_weight(packed, scales, bits, D)
        # quantization error bounded by the group scale
        assert float(jnp.abs(wd.astype(jnp.float32) - w).max()) < 0.02
        for B in (8, 64):
            x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32)
                            ).astype(jnp.bfloat16)
            ref = np.asarray(x @ wd, np.float32)
            out = np.asarray(quantized_matmul(x, packed, scales, bits=bits),
                             np.float32)
            np.testing.assert_allclose(out, ref, atol=2e-1, rtol=2e-2)

    def test_off_sweet_spot_falls_back(self):
        from deepspeed_tpu.ops.quant_matmul import (
            quantize_matmul_weight, quantized_matmul)

        rng = np.random.default_rng(1)
        D, F = 192, 160        # not 128-aligned → XLA fallback path
        w = jnp.asarray(rng.normal(size=(D, F)).astype(np.float32) / 30)
        packed, scales = quantize_matmul_weight(w, bits=8, group=96)
        x = jnp.asarray(rng.normal(size=(4, D)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        out = quantized_matmul(x, packed, scales, bits=8)
        assert out.shape == (4, F) and np.isfinite(np.asarray(
            out, np.float32)).all()
