"""Host/NVMe offload tests (pattern: reference ``tests/unit/ops/adam/test_cpu_adam.py``
numeric parity + ``tests/unit/ops/aio`` handle behavior + ZeRO-Offload engine runs)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, CPUAdamBuilder


requires_native = pytest.mark.skipif(
    not CPUAdamBuilder().is_compatible(), reason="g++ toolchain unavailable")


@requires_native
class TestCPUAdam:
    def test_matches_reference_adamw(self):
        """Native fused AdamW vs a numpy reference (test_cpu_adam.py parity)."""
        from deepspeed_tpu.offload import DeepSpeedCPUAdam

        rng = np.random.default_rng(0)
        n = 4097  # non-multiple of simd width
        p = rng.normal(size=n).astype(np.float32)
        g = rng.normal(size=n).astype(np.float32)
        m = np.zeros(n, np.float32)
        v = np.zeros(n, np.float32)
        p_ref, m_ref, v_ref = p.copy(), m.copy(), v.copy()

        lr, b1, b2, eps, wd = 1e-2, 0.9, 0.95, 1e-8, 0.1
        opt = DeepSpeedCPUAdam(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd)
        for step in range(1, 4):
            opt.step(p, g, m, v)
            # numpy AdamW reference
            m_ref = b1 * m_ref + (1 - b1) * g
            v_ref = b2 * v_ref + (1 - b2) * g * g
            bc1, bc2 = 1 - b1 ** step, 1 - b2 ** step
            denom = np.sqrt(v_ref) / np.sqrt(bc2) + eps
            p_ref = p_ref - (lr / bc1) * (m_ref / denom) - lr * wd * p_ref
        np.testing.assert_allclose(p, p_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(v, v_ref, rtol=1e-5, atol=1e-7)


@requires_native
class TestAIO:
    def test_swap_roundtrip(self, tmp_path):
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2)
        arrays = {f"t{i}": np.random.default_rng(i).normal(
            size=(128 + i,)).astype(np.float32) for i in range(4)}
        for name, arr in arrays.items():
            sw.swap_out(name, arr)
        sw.wait()
        for name, arr in arrays.items():
            back = sw.swap_in(name)
            np.testing.assert_array_equal(back, arr)
        sw.close()

    def test_o_direct_roundtrip(self, tmp_path):
        """O_DIRECT path: block-aligned bounce buffers, odd sizes included."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, o_direct=True)
        arrays = {f"t{i}": np.random.default_rng(i).normal(
            size=(1000 + i,)).astype(np.float32) for i in range(3)}
        for name, arr in arrays.items():
            sw.swap_out(name, arr)
        sw.wait()
        for name, arr in arrays.items():
            np.testing.assert_array_equal(sw.swap_in(name), arr)
        sw.close()

    def test_overlapped_reads(self, tmp_path):
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2)
        a = np.arange(1000, dtype=np.float32)
        b = np.arange(2000, dtype=np.float32) * 2
        sw.swap_out("a", a)
        sw.swap_out("b", b)
        sw.wait()
        ra = sw.swap_in_start("a")
        rb = sw.swap_in_start("b")
        np.testing.assert_array_equal(ra.wait(), a)
        np.testing.assert_array_equal(rb.wait(), b)
        ra.release()
        rb.release()
        sw.close()


@requires_native
class TestOffloadEngine:
    def _config(self, device, nvme_path=None):
        off = {"device": device}
        if nvme_path:
            off["nvme_path"] = nvme_path
        return {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2, "offload_optimizer": off},
            "mesh": {"fsdp": 8},
            "steps_per_print": 100,
        }

    def _train(self, eng, steps=4):
        fixed = {"input_ids": np.random.default_rng(0).integers(
            0, 256, (2 * eng.topology.dp_world_size, 16))}
        losses = []
        for _ in range(steps):
            loss = eng.forward(fixed)
            eng.backward(loss)
            eng.step()
            losses.append(float(loss))
        return losses

    def test_cpu_offload_converges(self, eight_devices):
        model = TransformerLM(get_preset("tiny"))
        eng, *_ = ds.initialize(model=model, config=self._config("cpu"))
        losses = self._train(eng)
        assert losses[-1] < losses[0]

    def test_nvme_offload_converges(self, tmp_path, eight_devices):
        model = TransformerLM(get_preset("tiny"))
        eng, *_ = ds.initialize(model=model,
                                config=self._config("nvme", str(tmp_path)))
        losses = self._train(eng)
        assert losses[-1] < losses[0]
        # moments really live on disk
        import os

        swp = os.path.join(str(tmp_path), "opt_states")
        assert any(f.endswith(".swp") for f in os.listdir(swp))

    def test_zenflow_overlap_converges(self, eight_devices):
        """ZenFlow async overlap: host Adam of step N runs during step N+1's
        fwd/bwd; with 1-step bounded staleness the run still converges and
        checkpoint boundaries drain the in-flight step."""
        cfg = self._config("cpu")
        cfg["zero_optimization"]["zenflow"] = {"overlap_step": True}
        model = TransformerLM(get_preset("tiny"))
        eng, *_ = ds.initialize(model=model, config=cfg)
        assert eng._offload.overlap
        fixed = {"input_ids": np.random.default_rng(0).integers(
            0, 256, (2 * eng.topology.dp_world_size, 16))}
        losses = []
        for _ in range(6):
            loss = eng.forward(fixed)
            eng.backward(loss)
            eng.step()
            # an async step is now in flight (collected at the next boundary)
            assert eng._offload._pending is not None
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # checkpoint drains the pending step
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            eng.save_checkpoint(d)
        assert eng._offload._pending is None

    def test_zenflow_tracks_sync_offload(self, eight_devices):
        """Staleness-1 trajectories track the synchronous offload run at small
        lr (ZenFlow's convergence claim, scaled to the test budget)."""
        runs = {}
        for overlap in (False, True):
            cfg = self._config("cpu")
            if overlap:
                cfg["zero_optimization"]["zenflow"] = {"overlap_step": True}
            eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                    config=cfg)
            runs[overlap] = self._train(eng, steps=6)
        # bounded staleness shifts the trajectory by exactly one step: step
        # N's forward runs before update N-1 is applied
        np.testing.assert_allclose(runs[True][1], runs[True][0], rtol=1e-6)
        np.testing.assert_allclose(runs[True][1:], runs[False][:-1], rtol=2e-2)

    def test_offload_matches_jit_adamw(self, eight_devices):
        """Host C++ AdamW must track the jitted optax path closely."""
        losses = {}
        for mode in ("jit", "cpu"):
            model = TransformerLM(get_preset("tiny"))
            cfg = self._config("cpu") if mode == "cpu" else {
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "mesh": {"fsdp": 8}, "steps_per_print": 100,
            }
            eng, *_ = ds.initialize(model=model, config=cfg)
            losses[mode] = self._train(eng, steps=3)
        np.testing.assert_allclose(losses["cpu"], losses["jit"], rtol=5e-3)


class TestZenFlowSelective:
    """Importance-based top-k gradient split (zenflow_stage_1_and_2.py:155):
    selected columns update on device every step, the rest only through the
    host Adam at update boundaries."""

    def test_split_invariants(self):
        """Off-boundary steps change ONLY the selected columns (+ dense
        leaves); the boundary step applies the accumulated host update to the
        unselected columns."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.offload import ZenFlowSelectiveOptimizer

        rng = np.random.default_rng(0)
        params = {"w": jnp.asarray(rng.normal(size=(4, 16)), jnp.float32),
                  "b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
        # constant grads with a clear importance ranking on the last 4 cols
        g = np.ones((4, 16), np.float32) * 0.01
        g[:, 12:] = 1.0
        grads = {"w": jnp.asarray(g), "b": jnp.ones((16,), jnp.float32)}
        opt = ZenFlowSelectiveOptimizer(
            params, topk_ratio=0.25, select_interval=8, update_interval=3,
            lr=1e-2)
        sel = None
        p = params
        for step in range(3):
            prev = jax.tree_util.tree_map(np.asarray, p)
            p, skipped = opt.step(grads, p, step)
            assert not skipped
            if sel is None:
                sel = np.asarray(opt._idx["w"])
                np.testing.assert_array_equal(sel, [12, 13, 14, 15])
            unsel = np.setdiff1d(np.arange(16), sel)
            cur = jax.tree_util.tree_map(np.asarray, p)
            # selected columns and the dense 1-D leaf move EVERY step
            assert np.abs(cur["w"][:, sel] - prev["w"][:, sel]).max() > 0
            assert np.abs(cur["b"] - prev["b"]).max() > 0
            if step < 2:  # off-boundary: unselected columns are frozen
                np.testing.assert_array_equal(cur["w"][:, unsel],
                                              prev["w"][:, unsel])
            else:  # boundary (step+1) % 3 == 0: host update lands
                assert np.abs(cur["w"][:, unsel] - prev["w"][:, unsel]).max() > 0
        # masters mirror device params after the boundary
        np.testing.assert_allclose(opt.master["w#0"], np.asarray(p["w"]),
                                   rtol=1e-6)

    def test_reselection_and_state_dict(self):
        import jax.numpy as jnp

        from deepspeed_tpu.offload import ZenFlowSelectiveOptimizer

        rng = np.random.default_rng(1)
        params = {"w": jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)}
        opt = ZenFlowSelectiveOptimizer(params, topk_ratio=0.25,
                                        select_interval=2, update_interval=2,
                                        lr=1e-3)
        p = params
        for step in range(4):
            g = {"w": jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)}
            p, _ = opt.step(g, p, step)
        sd = opt.state_dict()
        assert any(k.startswith("zf/idx/") for k in sd)
        opt2 = ZenFlowSelectiveOptimizer(params, topk_ratio=0.25,
                                         select_interval=2, update_interval=2,
                                         lr=1e-3)
        opt2.load_state_dict(sd)
        np.testing.assert_array_equal(np.asarray(opt2._idx["w"]),
                                      np.asarray(opt._idx["w"]))
        np.testing.assert_allclose(np.asarray(opt2._msel["w"]),
                                   np.asarray(opt._msel["w"]))

    def test_engine_e2e_converges(self, eight_devices):
        cfg = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {
                "stage": 1,
                "offload_optimizer": {"device": "cpu"},
                "zenflow": {"topk_ratio": 0.2, "update_interval": 2,
                            "select_interval": 4, "full_warm_up_rounds": 1},
            },
            "mesh": {"fsdp": 8},
            "steps_per_print": 100,
        }
        from deepspeed_tpu.offload import ZenFlowSelectiveOptimizer

        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=cfg)
        assert isinstance(eng._offload, ZenFlowSelectiveOptimizer)
        fixed = {"input_ids": np.random.default_rng(0).integers(
            0, 256, (2 * eng.topology.dp_world_size, 16))}
        losses = []
        for _ in range(8):
            loss = eng.forward(fixed)
            eng.backward(loss)
            eng.step()
            losses.append(float(loss))
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_overlap_and_topk_mutually_exclusive(self):
        import pytest

        from deepspeed_tpu.config import from_config

        with pytest.raises(Exception):
            from_config({"train_micro_batch_size_per_gpu": 1,
                         "zero_optimization": {
                             "offload_optimizer": {"device": "cpu"},
                             "zenflow": {"topk_ratio": 0.1,
                                         "overlap_step": True}}})

    def test_nvme_moments_tier(self, tmp_path):
        """topk split composes with the NVMe moments tier: boundary host Adam
        swaps moments in/out instead of reading the (empty) RAM dicts."""
        import os

        import jax.numpy as jnp

        from deepspeed_tpu.offload import ZenFlowSelectiveOptimizer

        rng = np.random.default_rng(2)
        params = {"w": jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)}
        opt = ZenFlowSelectiveOptimizer(
            params, topk_ratio=0.25, select_interval=8, update_interval=2,
            lr=1e-2, nvme_path=str(tmp_path))
        p = params
        g = {"w": jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)}
        for step in range(4):
            p, skipped = opt.step(g, p, step)
            assert not skipped
        swp = os.path.join(str(tmp_path), "opt_states")
        assert any(f.endswith(".swp") for f in os.listdir(swp))
        assert np.isfinite(np.asarray(p["w"])).all()

    def test_nonfinite_grad_skips_cleanly(self):
        """A NaN step must leave every piece of optimizer state untouched."""
        import jax.numpy as jnp

        from deepspeed_tpu.offload import ZenFlowSelectiveOptimizer

        rng = np.random.default_rng(3)
        params = {"w": jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)}
        opt = ZenFlowSelectiveOptimizer(params, topk_ratio=0.25,
                                        select_interval=8, update_interval=4,
                                        lr=1e-2)
        g_ok = {"w": jnp.ones((4, 16), jnp.float32)}
        p, _ = opt.step(g_ok, params, 0)
        m_before = np.asarray(opt._msel["w"]).copy()
        acc_before = np.asarray(opt._acc["w"]).copy()
        g_bad = {"w": jnp.full((4, 16), jnp.nan, jnp.float32)}
        p2, skipped = opt.step(g_bad, p, 1)
        assert skipped
        np.testing.assert_array_equal(np.asarray(opt._msel["w"]), m_before)
        np.testing.assert_array_equal(np.asarray(opt._acc["w"]), acc_before)
        assert np.isfinite(np.asarray(p2["w"])).all()
        # and recovery works
        p3, skipped = opt.step(g_ok, p2, 2)
        assert not skipped and np.isfinite(np.asarray(p3["w"])).all()


@requires_native
class TestShardedHostTier:
    """Round-2 gap #6: the host tier is partitioned by param shard (reference
    stage_1_and_2 cpu_offload partitioning) — per-host RAM and D2H volume
    follow the fsdp shard size, replicas deduplicated."""

    def test_masters_stored_per_fsdp_shard(self, eight_devices):
        model = TransformerLM(get_preset("tiny"))
        eng, *_ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2,
                                  "offload_optimizer": {"device": "cpu"}},
            "mesh": {"fsdp": 4, "dp": 2},
            "steps_per_print": 100})
        b = {"input_ids": np.random.default_rng(0).integers(
            0, 256, (2 * eng.topology.dp_world_size, 16))}
        loss = eng.forward(b); eng.backward(loss); eng.step()
        opt = eng._offload
        # a ZeRO-sharded leaf stores fsdp buffers, each 1/fsdp of the leaf
        sharded = [n for n in opt._layout
                   if len(opt._layout[n]) == 4]
        assert sharded, "no leaf sharded into 4 host buffers"
        name = sharded[0]
        total = int(np.prod(opt._shapes[name]))
        for i in range(4):
            assert opt.master[f"{name}#{i}"].size == total // 4
        # replicated leaves (dp replicas) are stored ONCE, not 8x
        assert all(len(v) <= 4 for v in opt._layout.values())
        host_elems = sum(a.size for a in opt.master.values())
        model_elems = sum(int(np.prod(s)) for s in opt._shapes.values())
        assert host_elems == model_elems  # all shards present, none duplicated

    def test_sharded_tier_checkpoint_roundtrip(self, eight_devices):
        model = TransformerLM(get_preset("tiny"))
        cfgd = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2,
                                  "offload_optimizer": {"device": "cpu"}},
            "mesh": {"fsdp": 4, "dp": 2},
            "steps_per_print": 100}
        eng, *_ = ds.initialize(model=model, config=cfgd)
        b = {"input_ids": np.random.default_rng(1).integers(
            0, 256, (2 * eng.topology.dp_world_size, 16))}
        loss = eng.forward(b); eng.backward(loss); eng.step()
        sd = eng._offload.state_dict()
        # the checkpoint format is full arrays (topology-independent)
        for name, shape in eng._offload._shapes.items():
            assert sd["master/" + name].shape == tuple(shape)
        eng2, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                 config=cfgd)
        eng2.forward(b)  # build grads/opt
        eng2._offload.load_state_dict(sd)
        for name in eng._offload._layout:
            np.testing.assert_allclose(
                eng2._offload._full_leaf("master", name),
                eng._offload._full_leaf("master", name), rtol=1e-7)
            np.testing.assert_allclose(
                eng2._offload._full_leaf("m", name),
                eng._offload._full_leaf("m", name), rtol=1e-7)
