"""Durable cross-replica request migration tests.

Fast tests pin the portable-resume contracts directly: the manifest
protocol in ``deepspeed_tpu/inference/kv_tier.py`` (canonical-JSON sha256
roundtrip, torn/skewed/tampered docs raise :class:`ManifestError`, POSIX
rename claim = exactly one winner, TTL sweep reclaims both manifests and
their durable KV files), and the batcher-level adoption ladder in
``deepspeed_tpu/serving/batcher.py`` + ``inference/engine_v2.py``
(export on donor A -> adopt on sibling B promotes KV that B never
produced, greedy tokens bit-identical in fp32; donor-GC'd / torn / IO-err
paths all unwind to re-prefill from token history, never zero-fill).

The two-replica crash storm lives in ``tools/serve_drill.py``
(``--scenario crash-migrate``); the ``slow``-marked wrapper at the bottom
runs it under pytest the way the slo-storm wrapper does.
"""

import json
import os
import threading

import numpy as np
import pytest

from deepspeed_tpu.config.config import ServingConfig
from deepspeed_tpu.inference.kv_tier import (ManifestError, claim_manifest,
                                             load_manifest, manifest_dir,
                                             sweep_manifests, write_manifest)
from deepspeed_tpu.serving import COMPLETED, ContinuousBatcher, ShedError

pytestmark = [pytest.mark.migrate, pytest.mark.serving]

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


# ---------------------------------------------------------------------------
# manifest protocol (no engine needed)
# ---------------------------------------------------------------------------

def _payload(uid="u1"):
    return {"uid": uid, "seen_tokens": 11,
            "hist": [3, 1, 4, 1, 5], "entries": [
                {"name": f"{uid}-k0", "tier": "nvme", "nbytes": 64}]}


class TestManifestProtocol:
    def test_roundtrip(self, tmp_path):
        shared = str(tmp_path)
        path = write_manifest(shared, _payload())
        assert os.path.dirname(path) == manifest_dir(shared)
        assert os.path.basename(path) == "u1.json"
        assert load_manifest(path) == _payload()
        # committed atomically: no .tmp droppings beside it
        assert not [f for f in os.listdir(manifest_dir(shared))
                    if f.endswith(".tmp")]

    def test_torn_write_raises(self, tmp_path):
        path = write_manifest(str(tmp_path), _payload())
        raw = open(path).read()
        with open(path, "w") as f:
            f.write(raw[:len(raw) // 2])     # torn mid-document
        with pytest.raises(ManifestError):
            load_manifest(path)

    def test_sha_mismatch_raises(self, tmp_path):
        path = write_manifest(str(tmp_path), _payload())
        doc = json.load(open(path))
        doc["payload"]["seen_tokens"] = 999   # tampered after commit
        with open(path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(ManifestError):
            load_manifest(path)

    def test_version_skew_raises(self, tmp_path):
        path = write_manifest(str(tmp_path), _payload())
        doc = json.load(open(path))
        doc["version"] = 999
        with open(path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(ManifestError):
            load_manifest(path)

    def test_missing_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_manifest(str(tmp_path / "manifests" / "nope.json"))

    def test_claim_exactly_one_winner(self, tmp_path):
        path = write_manifest(str(tmp_path), _payload())
        claimed = claim_manifest(path)
        assert claimed == path + ".claimed" and os.path.exists(claimed)
        assert claim_manifest(path) is None   # second claimant loses
        assert load_manifest(claimed) == _payload()

    def test_claim_race_threaded(self, tmp_path):
        """Satellite: two siblings race one manifest — POSIX rename makes
        exactly one the adopter, every time."""
        for round_ in range(8):
            path = write_manifest(str(tmp_path), _payload(f"r{round_}"))
            wins, barrier = [], threading.Barrier(2)

            def race():
                barrier.wait()
                got = claim_manifest(path)
                if got is not None:
                    wins.append(got)

            ts = [threading.Thread(target=race) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert len(wins) == 1
            os.remove(wins[0])

    def test_sweep_reclaims_aged_manifests_and_kv(self, tmp_path):
        shared = str(tmp_path)
        kv = os.path.join(shared, "kv")
        os.makedirs(kv)
        swp = os.path.join(kv, "u1-k0.swp")
        open(swp, "wb").write(b"\0" * 64)
        path = write_manifest(shared, _payload())
        claimed_src = write_manifest(shared, _payload("u2"))
        claim_manifest(claimed_src)           # orphaned claim ages out too
        stray = os.path.join(manifest_dir(shared), "junk.json.tmp")
        open(stray, "w").write("{")
        now = os.path.getmtime(path) + 100.0
        assert sweep_manifests(shared, ttl_s=1e9, now=now) == 0
        assert sweep_manifests(shared, ttl_s=0, now=now) == 0   # disabled
        assert sweep_manifests(shared, ttl_s=50.0, now=now) == 2
        assert not os.path.exists(path)
        assert not os.path.exists(swp)        # entries' KV died with it
        assert not os.path.exists(stray)
        survivors = write_manifest(shared, _payload("u3"))
        assert sweep_manifests(shared, ttl_s=1e6,
                               now=os.path.getmtime(survivors) + 1) == 0
        assert os.path.exists(survivors)      # fresh manifests survive


# ---------------------------------------------------------------------------
# batcher-level A -> B adoption (fp32 engines, bit-identical greedy)
# ---------------------------------------------------------------------------

def _mig_batcher(shared, **serving):
    """fp32 engine + SLO preemption + migration pointed at ``shared``."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    eng = InferenceEngineV2(
        TransformerLM(get_preset("tiny", dtype="float32")),
        max_sequences=8, max_seq_len=128, block_size=16)
    cfg = ServingConfig(**{
        "prefill_chunk": 32, "default_max_new_tokens": 8,
        "slo": {"enabled": True, "preempt": True},
        "migration": {"enabled": True, "shared_nvme_path": shared,
                      "manifest_ttl_s": 300.0}, **serving})
    return ContinuousBatcher(eng, cfg)


def _baseline(shared, prompt, n=8):
    solo = _mig_batcher(shared)
    uid = solo.submit(prompt, max_new_tokens=n, tier="batch")
    solo.pump(max_steps=80)
    base = list(solo.manager.result(uid).generated)
    solo.engine.close()
    assert len(base) == n
    return base


def _pause_mid_decode(b, uid):
    """Step until ``uid`` is genuinely mid-decode, then pause + export."""
    for _ in range(4):
        b.step()
    req = b.manager.active[uid]
    assert 0 < len(req.generated) < req.max_new_tokens
    assert b.engine.pause_request(uid)
    b.manager.pause(req)
    b._export_manifest(req)
    return req


class TestCrossReplicaAdoption:
    def test_durable_migrate_mid_decode_bit_identical(self, tmp_path):
        """Tentpole invariant: pause on A, crash-style export, adopt on B
        through the claimed manifest — B promotes KV it never produced
        through the same ``_flush_promotes`` fence and finishes the exact
        greedy sequence of an unmigrated fp32 run."""
        shared = str(tmp_path)
        prompt = list(np.random.default_rng(7).integers(0, 250, 40))
        base = _baseline(shared, prompt)

        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        req = _pause_mid_decode(a, uid)
        mid = len(req.generated)
        # ownership transfer (what capture_dead does for a dead donor)
        path = a.engine.export_paused(
            uid, f"{a.migration_tag}-{uid}",
            a._mig.shared_nvme_path, keep=False)
        assert path is not None and os.path.exists(path)
        assert a.counters["pause_exports"] == 1

        b = _mig_batcher(shared)
        claimed = claim_manifest(path)
        assert claimed is not None
        payload = load_manifest(claimed)
        assert payload["seen_tokens"] > 0 and payload["entries"]
        new = b.adopt_inflight(req, payload, claimed, migrated_from="a")
        assert new.migrated_from == "a"    # fresh uid in B's own namespace
        assert list(new.generated) == list(req.generated)
        assert b.engine.is_paused(new.uid)
        b.pump(max_steps=80)
        res = b.manager.result(new.uid)
        assert b.manager.resolve(new.uid) == COMPLETED
        assert list(res.generated) == base        # bit-identical greedy
        assert len(res.generated) > mid           # B actually decoded
        assert b.manager.counters["adopted"] == 1
        assert b.counters["reprefill_fallbacks"] == 0
        # sibling-side discard reclaimed the donor's durable files
        assert b.engine._tier_store.entries() == 0
        alloc = b.engine.state.allocator
        assert alloc.free_blocks == alloc.num_blocks
        assert not os.path.exists(claimed)
        a.engine.close()
        b.engine.close()

    def test_adopt_after_donor_gc_falls_back_to_reprefill(self, tmp_path):
        """Satellite: a manifest whose tier entries were swept (donor GC /
        cap eviction) adopts as a clean re-prefill — recompute from token
        history, never zero-fill — and still matches the baseline."""
        shared = str(tmp_path)
        prompt = list(np.random.default_rng(11).integers(0, 250, 40))
        base = _baseline(shared, prompt)

        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        req = _pause_mid_decode(a, uid)
        path = a.engine.export_paused(
            uid, f"{a.migration_tag}-{uid}",
            a._mig.shared_nvme_path, keep=False)
        kv_dir = os.path.join(shared, "kv")
        for f in os.listdir(kv_dir):          # donor-side GC swept the KV
            os.remove(os.path.join(kv_dir, f))

        b = _mig_batcher(shared)
        claimed = claim_manifest(path)
        payload = load_manifest(claimed)
        with pytest.raises(Exception):
            b.adopt_inflight(req, payload, claimed, migrated_from="a")
        # the failed adopt unwound: the fresh uid was never exposed
        assert not b.manager.active and not b.manager.queue
        new = b.adopt_inflight(req, None, None, migrated_from="a")
        assert new.replay is not None          # re-prefill armed
        b.pump(max_steps=120)
        assert b.manager.resolve(new.uid) == COMPLETED
        assert list(b.manager.result(new.uid).generated) == base
        a.engine.close()
        b.engine.close()

    def test_reprefill_mid_chunked_prefill_bit_identical(self, tmp_path):
        """Satellite: a request severed MID-chunked-prefill (no generated
        tokens yet, partial KV lost with the donor) re-prefills from its
        prompt on the sibling and matches the baseline."""
        shared = str(tmp_path)
        prompt = list(np.random.default_rng(13).integers(0, 250, 96))
        base = _baseline(shared, prompt)

        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        a.step()                               # one 32-token chunk
        req = a.manager.active[uid]
        assert 0 < req.prefilled < len(prompt)   # genuinely mid-prefill

        b = _mig_batcher(shared)
        new = b.adopt_inflight(req, None, None, migrated_from="a")
        b.pump(max_steps=120)
        assert b.manager.resolve(new.uid) == COMPLETED
        assert list(b.manager.result(new.uid).generated) == base
        a.engine.close()
        b.engine.close()

    def test_double_adopt_guard_exactly_one_wins(self, tmp_path):
        """Satellite: two siblings race one exported manifest; the rename
        claim lets exactly one adopt durable KV, the loser re-prefills."""
        shared = str(tmp_path)
        prompt = list(np.random.default_rng(17).integers(0, 250, 40))
        base = _baseline(shared, prompt)

        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        req = _pause_mid_decode(a, uid)
        path = a.engine.export_paused(
            uid, f"{a.migration_tag}-{uid}",
            a._mig.shared_nvme_path, keep=False)
        c1, c2 = claim_manifest(path), claim_manifest(path)
        assert (c1 is None) != (c2 is None)    # exactly one winner
        winner = c1 or c2

        b1, b2 = _mig_batcher(shared), _mig_batcher(shared)
        w = b1.adopt_inflight(req, load_manifest(winner), winner,
                              migrated_from="a")
        loser = b2.adopt_inflight(req, None, None, migrated_from="a")
        b1.pump(max_steps=80)
        b2.pump(max_steps=120)
        assert list(b1.manager.result(w.uid).generated) == base
        assert list(b2.manager.result(loser.uid).generated) == base
        a.engine.close()
        b1.engine.close()
        b2.engine.close()


# ---------------------------------------------------------------------------
# failure ladder: injected faults at every migration seam
# ---------------------------------------------------------------------------

class TestMigrationFaults:
    def test_crash_during_pause_export_leaves_no_debris(self, tmp_path):
        """``crash_during_pause_export`` dies between the KV demote and
        the manifest commit: no manifest, no orphaned durable KV, and the
        request is still locally resumable (pause intact)."""
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     set_injector)

        shared = str(tmp_path)
        prompt = list(np.random.default_rng(19).integers(0, 250, 40))
        base = _baseline(shared, prompt)
        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        for _ in range(4):
            a.step()
        req = a.manager.active[uid]
        assert a.engine.pause_request(uid)
        a.manager.pause(req)
        try:
            set_injector(FaultInjector(
                [{"kind": "crash_during_pause_export"}]))
            a._export_manifest(req)            # swallowed + logged
        finally:
            set_injector(None)
        assert a.counters["pause_exports"] == 0
        mdir = manifest_dir(shared)
        assert not os.path.exists(os.path.join(
            mdir, f"{a.migration_tag}-{uid}.json"))
        kv_dir = os.path.join(shared, "kv")
        assert not (os.path.isdir(kv_dir) and os.listdir(kv_dir))
        a.pump(max_steps=80)                   # pause itself survived
        assert a.manager.resolve(uid) == COMPLETED
        assert list(a.manager.result(uid).generated) == base
        a.engine.close()

    def test_manifest_torn_detected_on_load(self, tmp_path):
        """``manifest_torn`` truncates a just-committed manifest; the
        sibling's load detects it (sha/json) instead of adopting garbage."""
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     set_injector)

        shared = str(tmp_path)
        a = _mig_batcher(shared)
        prompt = list(np.random.default_rng(23).integers(0, 250, 40))
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        for _ in range(4):
            a.step()
        req = a.manager.active[uid]
        assert a.engine.pause_request(uid)
        a.manager.pause(req)
        try:
            set_injector(FaultInjector([{"kind": "manifest_torn"}]))
            path = a.engine.export_paused(
                uid, f"{a.migration_tag}-{uid}",
                a._mig.shared_nvme_path, keep=False)
        finally:
            set_injector(None)
        assert path is not None
        with pytest.raises(ManifestError):
            load_manifest(path)
        a.engine.close()

    def test_migrate_io_error_unwinds_to_reprefill(self, tmp_path):
        """``migrate_io_error`` fails the adopted tier read mid-promote:
        the resume unwinds (cancel, not zero-fill), the batcher requeues
        the MIGRATED request for re-prefill, and it still completes
        bit-identical with ``reprefill_fallbacks`` counted."""
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     set_injector)

        shared = str(tmp_path)
        prompt = list(np.random.default_rng(29).integers(0, 250, 40))
        base = _baseline(shared, prompt)
        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        req = _pause_mid_decode(a, uid)
        path = a.engine.export_paused(
            uid, f"{a.migration_tag}-{uid}",
            a._mig.shared_nvme_path, keep=False)

        b = _mig_batcher(shared)
        claimed = claim_manifest(path)
        new = b.adopt_inflight(req, load_manifest(claimed), claimed,
                               migrated_from="a")
        try:
            set_injector(FaultInjector([{"kind": "migrate_io_error"}]))
            b.pump(max_steps=10)               # resume attempt fails
        finally:
            set_injector(None)
        b.pump(max_steps=120)                  # re-prefill completes it
        assert b.manager.resolve(new.uid) == COMPLETED
        assert list(b.manager.result(new.uid).generated) == base
        assert b.counters["reprefill_fallbacks"] == 1
        assert b.manager.counters["reprefills"] == 1
        alloc = b.engine.state.allocator
        assert alloc.free_blocks == alloc.num_blocks
        a.engine.close()
        b.engine.close()


# ---------------------------------------------------------------------------
# voluntary rebalance + trace continuity
# ---------------------------------------------------------------------------

class TestRebalanceAndTrace:
    def test_voluntary_rebalance_transfers_paused_work(self, tmp_path):
        """Satellite: A exports a paused batch-tier request with ownership
        transferred — resolved locally as ``rebalanced`` with its HBM and
        slot already free — and B resumes it bit-identical."""
        shared = str(tmp_path)
        prompt = list(np.random.default_rng(31).integers(0, 250, 40))
        base = _baseline(shared, prompt)

        a = _mig_batcher(shared)
        uid = a.submit(prompt, max_new_tokens=8, tier="batch")
        req = _pause_mid_decode(a, uid)
        # no step in between: on an otherwise-idle pool the resume pump
        # would bring the pause straight back before the export ran
        out = a.export_paused_for_rebalance()
        assert len(out) == 1 and out[0][0].uid == uid
        req, path = out[0]
        assert a.manager.resolve(uid) == "shed"
        assert a.manager.result(uid).finish_reason == "rebalanced"
        assert a.manager.counters["rebalanced"] == 1
        # donor side fully released BEFORE the sibling touches anything
        assert not a.engine.is_paused(uid)
        assert uid not in a.engine.state.sequences
        alloc = a.engine.state.allocator
        assert alloc.free_blocks == alloc.num_blocks

        b = _mig_batcher(shared)
        claimed = claim_manifest(path)
        new = b.adopt_inflight(req, load_manifest(claimed), claimed,
                               migrated_from="a")
        b.pump(max_steps=80)
        assert list(b.manager.result(new.uid).generated) == base
        a.engine.close()
        b.engine.close()

    def test_trace_id_spans_donor_to_sibling(self, tmp_path):
        """Satellite: the adopted request re-opens the DONOR's trace id,
        so one ``/v1/trace`` chain shows export -> adopt -> resumed
        tokens."""
        from deepspeed_tpu.observability import configure_tracing, get_bus

        shared = str(tmp_path)
        bus = configure_tracing(enabled=True)
        bus.clear()
        try:
            a = _mig_batcher(shared)
            prompt = list(np.random.default_rng(37).integers(0, 250, 40))
            uid = a.submit(prompt, max_new_tokens=8, tier="batch")
            req = _pause_mid_decode(a, uid)
            donor_trace = req.trace_id
            assert donor_trace is not None
            path = a.engine.export_paused(
                uid, f"{a.migration_tag}-{uid}",
                a._mig.shared_nvme_path, keep=False)

            b = _mig_batcher(shared)
            claimed = claim_manifest(path)
            new = b.adopt_inflight(req, load_manifest(claimed), claimed,
                                   migrated_from="a")
            assert new.trace_id == donor_trace   # one chain, two replicas
            b.pump(max_steps=80)
            evs = [e for e in get_bus().events()
                   if e.trace_id == donor_trace]
            whats = [(e.args or {}).get("what") for e in evs]
            assert "pause" in whats              # donor side
            assert "adopt" in whats              # sibling side
            assert "resume" in whats             # first resumed step
            a.engine.close()
            b.engine.close()
        finally:
            configure_tracing(enabled=False)
            bus.clear()


# ---------------------------------------------------------------------------
# slow wrapper: the two-replica crash-migrate storm
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_drill_crash_migrate(tmp_path, monkeypatch):
    """Tier-1 (slow) wrapper for ``serve_drill --scenario crash-migrate``:
    storm two replicas sharing an NVMe namespace, kill one mid-decode;
    the sibling resumes >= 1 request from its durable manifest and
    recovers the manifest-less rest by re-prefill — zero lost uids,
    tokens bit-identical to an uncrashed replay, every pool block, tier
    entry and manifest reclaimed."""
    import sys

    monkeypatch.setenv("DSTPU_BENCH_LEDGER", "0")
    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    verdict = run_scenario("crash-migrate", workdir=str(tmp_path))
    assert verdict["ok"], verdict
