"""Collective façade tests over the virtual 8-device mesh
(parity model: tests/unit/comm/test_dist.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def topo():
    return build_mesh(axis_sizes={"dp": 8})


def _run(topo, fn, x, in_spec, out_spec):
    shard = jax.shard_map(fn, mesh=topo.mesh, in_specs=in_spec, out_specs=out_spec)
    return jax.jit(shard)(x)


def test_all_reduce_sum(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.all_reduce(v, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_reduce_ops(topo, eight_devices):
    x = jnp.arange(1.0, 9.0)
    for op, expect in [(comm.MAX, 8.0), (comm.MIN, 1.0), (comm.AVG, 4.5)]:
        out = _run(topo, lambda v, op=op: comm.all_reduce(v, op=op, axis="dp"),
                   x, P("dp"), P("dp"))
        np.testing.assert_allclose(np.asarray(out)[0], expect)


def test_reduce_scatter(topo, eight_devices):
    # each rank holds the full vector; after reduce_scatter each holds its summed shard
    x = jnp.tile(jnp.arange(8.0), (8, 1))  # [8 ranks, 8 elems] sharded on dim 0
    out = _run(topo, lambda v: comm.reduce_scatter(v[0], axis="dp", scatter_dim=0),
               x, P("dp", None), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)


def test_all_gather(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.all_gather(v, axis="dp", gather_dim=0),
               x, P("dp"), P("dp"))
    # every rank reconstructs the full vector; stacked global result tiles it 8x
    assert out.shape == (64,)
    np.testing.assert_allclose(np.asarray(out), np.tile(np.arange(8.0), 8))


def test_all_to_all(topo, eight_devices):
    # tiled all_to_all redistributes: row-sharded -> column-sharded, content unchanged
    x = jnp.arange(64.0).reshape(8, 8)  # rank i holds row i
    out = _run(topo, lambda v: comm.all_to_all(v, axis="dp", split_dim=1, concat_dim=0),
               x, P("dp", None), P(None, "dp"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    # each device now holds one column
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(8, 1)}


def test_broadcast(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.broadcast(v, src=3, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_ppermute_ring(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.send_recv_next(v, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))
    out = _run(topo, lambda v: comm.send_recv_prev(v, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), -1))


def test_comms_logger_records():
    from deepspeed_tpu.comm.logger import CommsLogger

    lg = CommsLogger(enabled=True)
    lg.append("all_reduce", 1024, 0.001)
    lg.append("all_reduce", 2048)
    assert lg.counts["all_reduce"] == 2
    assert lg.bytes["all_reduce"] == 3072
    summary = lg.log_summary()
    assert "all_reduce" in summary


def test_host_collectives_single_process():
    out = comm.all_reduce_host(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0])
    comm.assert_same_across_processes(3, "three")
