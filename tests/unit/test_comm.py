"""Collective façade tests over the virtual 8-device mesh
(parity model: tests/unit/comm/test_dist.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def topo():
    return build_mesh(axis_sizes={"dp": 8})


def _run(topo, fn, x, in_spec, out_spec):
    shard = jax.shard_map(fn, mesh=topo.mesh, in_specs=in_spec, out_specs=out_spec)
    return jax.jit(shard)(x)


def test_all_reduce_sum(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.all_reduce(v, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_reduce_ops(topo, eight_devices):
    x = jnp.arange(1.0, 9.0)
    for op, expect in [(comm.MAX, 8.0), (comm.MIN, 1.0), (comm.AVG, 4.5)]:
        out = _run(topo, lambda v, op=op: comm.all_reduce(v, op=op, axis="dp"),
                   x, P("dp"), P("dp"))
        np.testing.assert_allclose(np.asarray(out)[0], expect)


def test_reduce_scatter(topo, eight_devices):
    # each rank holds the full vector; after reduce_scatter each holds its summed shard
    x = jnp.tile(jnp.arange(8.0), (8, 1))  # [8 ranks, 8 elems] sharded on dim 0
    out = _run(topo, lambda v: comm.reduce_scatter(v[0], axis="dp", scatter_dim=0),
               x, P("dp", None), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)


def test_all_gather(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.all_gather(v, axis="dp", gather_dim=0),
               x, P("dp"), P("dp"))
    # every rank reconstructs the full vector; stacked global result tiles it 8x
    assert out.shape == (64,)
    np.testing.assert_allclose(np.asarray(out), np.tile(np.arange(8.0), 8))


def test_all_to_all(topo, eight_devices):
    # tiled all_to_all redistributes: row-sharded -> column-sharded, content unchanged
    x = jnp.arange(64.0).reshape(8, 8)  # rank i holds row i
    out = _run(topo, lambda v: comm.all_to_all(v, axis="dp", split_dim=1, concat_dim=0),
               x, P("dp", None), P(None, "dp"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    # each device now holds one column
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(8, 1)}


def test_broadcast(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.broadcast(v, src=3, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_ppermute_ring(topo, eight_devices):
    x = jnp.arange(8.0)
    out = _run(topo, lambda v: comm.send_recv_next(v, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))
    out = _run(topo, lambda v: comm.send_recv_prev(v, axis="dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), -1))


# ---------------------------------------------------------------------------
# byte accounting: comm/<op>_bytes must match the ANALYTIC wire payload —
# these counters are the ZeRO++ acceptance instrument (tools/comm_drill.py
# gates the >=3x volume reduction on them), so they are pinned here for
# dense bf16 AND quantized int8/int4 collectives.
# ---------------------------------------------------------------------------

@pytest.fixture
def comm_log():
    from deepspeed_tpu.comm.logger import comms_logger

    was = comms_logger.enabled
    comms_logger.enabled = True
    yield comms_logger
    comms_logger.enabled = was


def _traced_bytes(topo, lg, fn, x, in_spec, out_spec):
    """Trace (never execute) one shard_map'd collective; return the per-op
    byte deltas the trace logged — trace-time logging IS the accounting."""
    before = dict(lg.bytes)
    jax.make_jaxpr(jax.shard_map(fn, mesh=topo.mesh, in_specs=in_spec,
                                 out_specs=out_spec, check_vma=False))(x)
    return {k: v - before.get(k, 0) for k, v in lg.bytes.items()
            if v != before.get(k, 0)}


N, BS = 2048, 256   # per-device elements, quant block size


def test_bytes_all_gather_bf16(topo, eight_devices, comm_log):
    x = jnp.zeros((8 * N,), jnp.bfloat16)
    d = _traced_bytes(topo, comm_log,
                      lambda v: comm.all_gather(v, axis="dp"), x,
                      P("dp"), P("dp"))
    assert d == {"all_gather": N * 2}


def test_bytes_reduce_scatter_fp32(topo, eight_devices, comm_log):
    x = jnp.zeros((8 * N,), jnp.float32)
    d = _traced_bytes(topo, comm_log,
                      lambda v: comm.reduce_scatter(v, axis="dp"), x,
                      P(None), P("dp"))
    assert d == {"reduce_scatter": 8 * N * 4}


def test_bytes_broadcast_bf16(topo, eight_devices, comm_log):
    x = jnp.zeros((8 * N,), jnp.bfloat16)
    d = _traced_bytes(topo, comm_log,
                      lambda v: comm.broadcast(v, src=0, axis="dp"), x,
                      P("dp"), P("dp"))
    assert d == {"broadcast": N * 2}


@pytest.mark.parametrize("bits", [8, 4])
def test_bytes_quantized_ops(topo, eight_devices, comm_log, bits):
    from deepspeed_tpu.comm import quantized as cq

    want = cq.wire_bytes(N, bits, BS)
    # analytic sanity of the helper itself: packed payload + fp32 scales
    payload = N // 2 if bits == 4 else N
    assert want == payload + (N // BS) * 4

    xb = jnp.zeros((8 * N,), jnp.bfloat16)
    d = _traced_bytes(topo, comm_log,
                      lambda v: cq.all_gather_q(v, "dp", bits=bits,
                                                block_size=BS),
                      xb, P("dp"), P("dp"))
    assert d == {"all_gather": want}

    xf = jnp.zeros((8 * N,), jnp.float32)
    d = _traced_bytes(topo, comm_log,
                      lambda v: cq.reduce_scatter_q(v, "dp", bits=bits,
                                                    block_size=BS),
                      xf, P(None), P("dp"))
    # 8 per-destination chunks of N elements, each blockwise-quantized
    assert d == {"reduce_scatter": 8 * cq.wire_bytes(N, bits, BS)}

    d = _traced_bytes(topo, comm_log,
                      lambda v: cq.broadcast_q(v, 0, "dp", bits=bits,
                                               block_size=BS),
                      xb, P("dp"), P("dp"))
    assert d == {"broadcast": want}


def test_bytes_two_hop_split_op_names(topo, eight_devices, comm_log):
    """Two-hop qgZ logs its ICI hop under reduce_scatter_intra (full bf16
    payload) and its DCN hop under reduce_scatter (quantized 1/slice
    piece) — the convention the drill's >=3x gate relies on."""
    from deepspeed_tpu.comm import quantized as cq

    x = jnp.zeros((8 * N,), jnp.bfloat16)
    d = _traced_bytes(
        topo, comm_log,
        lambda v: cq.two_hop_reduce_scatter(v, "dp", 2, bits=8,
                                            block_size=BS),
        x, P(None), P("dp"))
    assert d["reduce_scatter_intra"] == 8 * N * 2
    # after the 2-wide intra hop each device holds 4N elements, moved as
    # 4 per-destination chunks of N across the strided slice peers
    assert d["reduce_scatter"] == 4 * cq.wire_bytes(N, 8, BS)


def test_two_hop_all_gather_natural_order_and_bytes(topo, eight_devices,
                                                    comm_log):
    """qwZ cross_slice_only gather: quantized DCN hop + dense ICI hop,
    and the un-permute restores the NATURAL shard order (a wrong order
    would silently train on scrambled params)."""
    from deepspeed_tpu.comm import quantized as cq

    x = jnp.linspace(-1.0, 1.0, 8 * 64, dtype=jnp.float32)
    before = dict(comm_log.bytes)
    out = jax.jit(jax.shard_map(
        lambda v: cq.two_hop_all_gather(v, "dp", 2, bits=8, block_size=64),
        mesh=topo.mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(out[:8 * 64]), np.asarray(x),
                               atol=1.5e-2)
    d = {k: v - before.get(k, 0) for k, v in comm_log.bytes.items()
         if v != before.get(k, 0)}
    # cross (DCN) hop: own 64-elem shard quantized; intra (ICI) hop: the
    # gathered 4-slice chunk (256 elems) moves dense fp32
    assert d["all_gather"] == cq.wire_bytes(64, 8, 64)
    assert d["all_gather_intra"] == 4 * 64 * 4


def test_quantized_collectives_roundtrip_values(topo, eight_devices,
                                                comm_log):
    """Numerical sanity riding the same mesh: gather/broadcast round-trip
    within blockwise-int8 tolerance, reduce-scatter sums correctly."""
    from deepspeed_tpu.comm import quantized as cq

    x = jnp.linspace(-1.0, 1.0, 8 * 64, dtype=jnp.float32)
    out = jax.jit(jax.shard_map(
        lambda v: cq.all_gather_q(v, "dp", bits=8, block_size=64),
        mesh=topo.mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(out[:8 * 64]), np.asarray(x),
                               atol=1.5e-2)
    out = jax.jit(jax.shard_map(
        lambda v: cq.broadcast_q(v, 3, "dp", bits=8, block_size=64),
        mesh=topo.mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))(x)
    want = np.tile(np.asarray(x[3 * 64:4 * 64]), 8)
    np.testing.assert_allclose(np.asarray(out), want, atol=1.5e-2)
    # reduce-scatter of identical replicas == 8 * x on each shard
    out = jax.jit(jax.shard_map(
        lambda v: cq.reduce_scatter_q(v, "dp", bits=8, block_size=64),
        mesh=topo.mesh, in_specs=P(None), out_specs=P("dp"),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               atol=0.2)


def test_comms_logger_records():
    from deepspeed_tpu.comm.logger import CommsLogger

    lg = CommsLogger(enabled=True)
    lg.append("all_reduce", 1024, 0.001)
    lg.append("all_reduce", 2048)
    assert lg.counts["all_reduce"] == 2
    assert lg.bytes["all_reduce"] == 3072
    summary = lg.log_summary()
    assert "all_reduce" in summary


def test_host_collectives_single_process():
    out = comm.all_reduce_host(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0])
    comm.assert_same_across_processes(3, "three")
