"""Elastic agent tests — the analog of the reference's elasticity unit tests
plus the elastic_agent restart semantics: a simulated host loss must resume at
a smaller chip count from the latest checkpoint with the global batch constant."""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.elasticity import ElasticAgent, compute_elastic_config
from deepspeed_tpu.models import TransformerLM, get_preset

ECFG = {"max_train_batch_size": 32, "micro_batch_sizes": [1, 2, 4],
        "min_gpus": 1, "max_gpus": 8, "prefer_larger_batch": True}


def test_agent_restart_sequence():
    """Failures walk down admissible world sizes; batch constant, micro adapts."""
    agent = ElasticAgent(ECFG, max_restarts=3)
    calls = []

    def spawn(chips, micro, idx):
        calls.append((chips, micro, idx))
        return 0 if len(calls) >= 3 else 1  # two failures, then success

    res = agent.run(spawn, chips=8, lost_per_failure=1)
    assert res.succeeded and res.restarts == 2
    worlds = [h.chips for h in res.history]
    assert worlds[0] == 8 and worlds == sorted(worlds, reverse=True)
    assert len({h.global_batch for h in res.history}) == 1
    # micro * some_ga * chips == global batch at every incarnation
    for h in res.history:
        assert h.global_batch % (h.chips * h.micro_batch) == 0


def test_prefer_smaller_batch_tiebreak():
    """prefer_larger_batch=False picks the smallest batch among equally
    compatible candidates (was a silent no-op)."""
    # 48 and 24 tie at 6 compatible counts ({1,2,3,4,6,8}) with micro=[1]
    cfg = {"max_train_batch_size": 48, "micro_batch_sizes": [1],
           "min_gpus": 1, "max_gpus": 8}
    big, chips_b, _ = compute_elastic_config({**cfg, "prefer_larger_batch": True})
    small, chips_s, _ = compute_elastic_config({**cfg, "prefer_larger_batch": False})
    assert len(chips_b) == len(chips_s)
    assert small < big


def test_agent_gives_up_below_min():
    agent = ElasticAgent({**ECFG, "min_gpus": 7}, max_restarts=5)
    res = agent.run(lambda c, m, i: 1, chips=8)
    assert not res.succeeded
    assert res.history[-1].chips == 8  # nothing admissible below → stop


class TestCohortSupervisor:
    """Agent-side heartbeat supervision: a cohort wedged so hard its
    in-process watchdog cannot run must be killed from OUTSIDE off its
    stale heartbeat files."""

    # child: writes one heartbeat, then wedges (no further writes — the
    # simulated state where every in-process thread is stuck)
    STALLED = textwrap.dedent("""
        import json, os, sys, time
        hb = sys.argv[1]
        os.makedirs(hb, exist_ok=True)
        with open(os.path.join(hb, "heartbeat_0.json"), "w") as f:
            json.dump({"rank": 0, "pid": os.getpid(), "step": 1}, f)
        time.sleep(120)
    """)

    def test_stalled_child_killed_and_respawn_path_taken(self, tmp_path):
        from deepspeed_tpu.elasticity import CohortSupervisor

        hb = tmp_path / "heartbeats"
        script = tmp_path / "stalled.py"
        script.write_text(self.STALLED)
        sup = CohortSupervisor(str(hb), deadline_s=0.6, poll_s=0.1,
                               grace_s=2.0)
        proc = subprocess.Popen([sys.executable, str(script), str(hb)])
        rc = sup.watch(proc)
        assert rc != 0                       # killed, not clean exit
        assert sup.kills == 1
        assert "stale cohort heartbeats" in sup.last_cause
        # the agent treats the nonzero exit as an ordinary host loss
        agent = ElasticAgent(ECFG, max_restarts=1)
        calls = []

        def spawn(chips, micro, idx):
            if not calls:
                calls.append("wedged")
                p = subprocess.Popen([sys.executable, str(script), str(hb)])
                return sup.watch(p)
            calls.append("healthy")
            return 0

        res = agent.run(spawn, chips=8)
        assert res.succeeded and res.restarts == 1
        assert sup.kills == 2

    def test_respawn_not_killed_off_previous_cohorts_stale_beats(
            self, tmp_path):
        """After a hang-kill the dead cohort's heartbeat files are (by
        construction) already past the deadline; a respawned cohort must
        not be killed off them before it writes its own first beat."""
        from deepspeed_tpu.elasticity import CohortSupervisor

        hb = tmp_path / "heartbeats"
        hb.mkdir()
        stale = hb / "heartbeat_0.json"
        stale.write_text("{}")
        past = time.time() - 3600.0
        os.utime(stale, (past, past))           # the previous incarnation
        script = tmp_path / "late.py"
        script.write_text(textwrap.dedent("""
            import json, os, sys, time
            time.sleep(1.0)                     # "startup compile"
            with open(os.path.join(sys.argv[1],
                                   "heartbeat_0.json"), "w") as f:
                json.dump({"rank": 0}, f)
        """))
        sup = CohortSupervisor(str(hb), deadline_s=0.4, poll_s=0.1)
        proc = subprocess.Popen([sys.executable, str(script), str(hb)])
        assert sup.watch(proc) == 0             # survived its slow startup
        assert sup.kills == 0

    def test_healthy_child_not_killed(self, tmp_path):
        """A cohort that keeps beating (or exits cleanly) is left alone."""
        from deepspeed_tpu.elasticity import CohortSupervisor

        hb = tmp_path / "heartbeats"
        script = tmp_path / "healthy.py"
        script.write_text(textwrap.dedent("""
            import json, os, sys, time
            hb = sys.argv[1]
            os.makedirs(hb, exist_ok=True)
            for _ in range(6):
                with open(os.path.join(hb, "heartbeat_0.json"), "w") as f:
                    json.dump({"rank": 0, "pid": os.getpid()}, f)
                time.sleep(0.1)
        """))
        sup = CohortSupervisor(str(hb), deadline_s=0.5, poll_s=0.1)
        proc = subprocess.Popen([sys.executable, str(script), str(hb)])
        assert sup.watch(proc) == 0
        assert sup.kills == 0

    def test_supervised_spawn_wires_env_and_heartbeat_dir(self, tmp_path):
        from deepspeed_tpu.elasticity import supervised_subprocess_spawn

        script = tmp_path / "trainer.py"
        script.write_text(textwrap.dedent("""
            import json, os, sys
            out = {k: os.environ[k] for k in
                   ("DSTPU_ELASTIC_CHIPS", "DSTPU_ELASTIC_MICRO",
                    "DSTPU_RESTART_COUNT", "DSTPU_CHECKPOINT_DIR")}
            with open(sys.argv[1], "w") as f:
                json.dump(out, f)
        """))
        sink = tmp_path / "env.json"
        spawn, sup = supervised_subprocess_spawn(
            str(script), [str(sink)], dict(os.environ), str(tmp_path),
            deadline_s=30.0)
        assert spawn(4, 2, 1) == 0
        env = json.loads(sink.read_text())
        assert env["DSTPU_ELASTIC_CHIPS"] == "4"
        assert env["DSTPU_RESTART_COUNT"] == "1"
        assert sup.hb_dir == os.path.join(str(tmp_path), "heartbeats")
        assert sup.kills == 0


def test_elastic_engine_batch_resolution(eight_devices):
    """elasticity.enabled drives the batch triple from the world size."""
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "elasticity": {"enabled": True, **ECFG},
        "mesh": {"dp": 8}, "steps_per_print": 100})
    batch, _, micro_map = compute_elastic_config(ECFG, target_chips=8)
    assert eng.train_batch_size() == batch
    assert eng.train_micro_batch_size_per_gpu() == micro_map[8]

    with pytest.raises(ValueError, match="ignore_non_elastic_batch_info"):
        ds.initialize(model=TransformerLM(get_preset("tiny")), config={
            "train_batch_size": 64,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "elasticity": {"enabled": True, **ECFG},
            "mesh": {"dp": 8}})


TRAINER = textwrap.dedent("""
    import json, os, sys
    chips = int(os.environ["DSTPU_ELASTIC_CHIPS"])
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={chips}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    ckpt = os.environ["DSTPU_CHECKPOINT_DIR"]
    restart = int(os.environ["DSTPU_RESTART_COUNT"])
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "elasticity": {"enabled": True, "max_train_batch_size": 32,
                       "micro_batch_sizes": [1, 2, 4],
                       "min_gpus": 1, "max_gpus": 8},
        "mesh": {"fsdp": chips}, "steps_per_print": 100})
    if os.path.exists(os.path.join(ckpt, "latest")):
        eng.load_checkpoint(ckpt)
    rec = {"chips": chips, "global_batch": eng.train_batch_size(),
           "micro": eng.train_micro_batch_size_per_gpu(),
           "start_step": eng.global_steps}
    rng = np.random.default_rng(0)
    B = eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size
    while eng.global_steps < 6:
        for _ in range(eng.gradient_accumulation_steps()):
            loss = eng.forward({"input_ids": rng.integers(0, 256, (B, 16))})
            eng.backward(loss)
        eng.step()
        eng.save_checkpoint(ckpt)
        if restart == 0 and eng.global_steps >= 3:
            os._exit(13)  # simulated host loss mid-run
    rec["end_step"] = eng.global_steps
    rec["loss"] = float(loss)
    json.dump(rec, open(os.path.join(ckpt, f"run{restart}.json"), "w"))
""")


def test_host_loss_resumes_smaller_world(tmp_path):
    """End-to-end: cohort dies at step 3 (rc=13) on 8 chips; the agent restarts
    at the next admissible world size; training resumes from the step-3
    checkpoint (ZeRO-2 reshard-on-load) and finishes at step 6 with the SAME
    global batch."""
    from deepspeed_tpu.elasticity import subprocess_spawn

    script = tmp_path / "trainer.py"
    script.write_text(TRAINER)
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
        + os.pathsep + env.get("PYTHONPATH", ""))
    agent = ElasticAgent(ECFG, max_restarts=2)
    res = agent.run(subprocess_spawn(str(script), [], env, ckpt), chips=8,
                    lost_per_failure=4)  # lose half the pod
    assert res.succeeded, [h.exit_code for h in res.history]
    assert res.restarts == 1
    assert res.history[0].exit_code == 13 and res.history[1].exit_code == 0
    assert res.history[0].chips == 8 and res.history[1].chips == 4
    rec = json.load(open(os.path.join(ckpt, "run1.json")))
    assert rec["chips"] == 4
    assert rec["start_step"] == 3, "did not resume from the step-3 checkpoint"
    assert rec["end_step"] == 6
    # the elastic guarantee: same global batch at both world sizes
    assert rec["global_batch"] == res.history[0].global_batch
