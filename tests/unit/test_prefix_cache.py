"""Prefix-cache KV reuse + n-gram speculative decoding tests.

Cache-exactness is the contract under test: the same prompt served cold vs
prefix-cached, and greedy decode with speculation on vs off, must produce
IDENTICAL tokens — sharing/drafting may only change how much work it takes
to produce them. Exactness tests run the tiny model in float32: in bf16 a
random-init model's near-tied logits can flip argmax between the (all
numerically-equivalent) attention kernel variants, which is a test-model
artifact, not a property of the mechanism (a trained model's logit margins
dwarf kernel rounding).

Also here: the refcounted-allocator satellite (double-free raises), the
duplicate-uid ``can_schedule_batch`` satellite, LRU eviction under pool
pressure, and refcount-leak-free pool restoration. The end-to-end
``prefix-storm`` drill lives in ``tools/serve_drill.py``; its slow wrapper
is at the bottom under the ``perf`` marker.
"""

import os

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import (BlockedAllocator, InferenceEngineV2,
                                     PrefixCache, SequenceManager,
                                     ngram_draft)
from deepspeed_tpu.models import TransformerLM, get_preset

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


# ---------------------------------------------------------------------------
# refcounted allocator (satellite: double-free must raise)
# ---------------------------------------------------------------------------

class TestRefcountedAllocator:
    def test_double_free_raises(self):
        alloc = BlockedAllocator(num_blocks=4, block_size=8)
        a = alloc.allocate(2)
        alloc.free(a)
        assert alloc.free_blocks == 4
        with pytest.raises(RuntimeError, match="double free"):
            alloc.free(a)              # second free of the same blocks
        # the failed free must not have corrupted the free list
        assert alloc.free_blocks == 4
        with pytest.raises(RuntimeError, match="double free"):
            alloc.free([0])            # never-reallocated block

    def test_shared_block_needs_one_free_per_owner(self):
        alloc = BlockedAllocator(num_blocks=2, block_size=8)
        [b] = alloc.allocate(1)
        alloc.incref([b])              # second owner (e.g. the prefix tree)
        alloc.free([b])                # first owner releases
        assert alloc.free_blocks == 1  # still held by the second owner
        assert alloc.refcount(b) == 1
        alloc.free([b])
        assert alloc.free_blocks == 2
        with pytest.raises(RuntimeError, match="double free"):
            alloc.free([b])

    def test_incref_of_free_block_raises(self):
        alloc = BlockedAllocator(num_blocks=2, block_size=8)
        with pytest.raises(RuntimeError, match="unallocated"):
            alloc.incref([0])


# ---------------------------------------------------------------------------
# duplicate-uid joint schedulability (satellite)
# ---------------------------------------------------------------------------

class TestDuplicateUidBatch:
    def test_duplicate_uid_blocks_costed_cumulatively(self):
        """A uid listed twice must be costed against its PROJECTED state
        after the first occurrence — the old per-occurrence check read the
        original ``seen_tokens`` twice and undercounted block demand."""
        sm = SequenceManager(max_sequences=2, max_seq_len=64, block_size=8,
                             num_blocks=8)
        sm.schedule(1, 4)
        sm.commit(1)                   # seen=4, holds 1 block (4/8 used)
        taken = sm.allocator.allocate(sm.allocator.free_blocks)  # drain pool
        # two 4-token chunks: cumulative 4+8=12 tokens -> needs a 2nd block;
        # per-occurrence math said ceil(8/8)-1 = 0 twice -> "schedulable"
        assert not sm.can_schedule_batch([1, 1], [4, 4])
        sm.allocator.free(taken)
        assert sm.can_schedule_batch([1, 1], [4, 4])

    def test_duplicate_uid_seq_len_costed_cumulatively(self):
        sm = SequenceManager(max_sequences=2, max_seq_len=32, block_size=8)
        sm.schedule(1, 30)
        sm.commit(1)
        # each occurrence alone fits (30+2 <= 32); jointly 34 > 32
        assert sm.can_schedule_batch([1], [2])
        assert not sm.can_schedule_batch([1, 1], [2, 2])

    def test_duplicate_new_uid_counts_one_slot(self):
        sm = SequenceManager(max_sequences=1, max_seq_len=32, block_size=8)
        assert sm.can_schedule_batch([7, 7], [4, 4])   # one slot, not two


# ---------------------------------------------------------------------------
# PrefixCache state machine (no engine)
# ---------------------------------------------------------------------------

class TestPrefixCacheState:
    def _cache(self, num_blocks=8, bs=4, **kw):
        alloc = BlockedAllocator(num_blocks, bs)
        return alloc, PrefixCache(alloc, **kw)

    def test_full_block_granularity_and_roundtrip(self):
        alloc, pc = self._cache()
        toks = np.arange(10, dtype=np.int32)          # 2 full blocks + tail 2
        blocks = alloc.allocate(3)
        assert pc.insert(toks, blocks) == 2           # tail block not cached
        got, n = pc.peek(toks)
        assert n == 8 and got == blocks[:2]
        # a diverging second block matches only the first
        other = np.concatenate([toks[:4], toks[4:8] + 1])
        _, n2 = pc.peek(other)
        assert n2 == 4
        # acquire takes a reference per matched block
        acq, n3 = pc.acquire(toks)
        assert n3 == 8
        assert alloc.refcount(blocks[0]) == 3         # owner + tree + acquire
        assert pc.counters["hits"] == 1 and pc.counters["hit_tokens"] == 8

    def test_max_tokens_caps_at_full_blocks(self):
        alloc, pc = self._cache()
        toks = np.arange(8, dtype=np.int32)
        pc.insert(toks, alloc.allocate(2))
        # cap 7 (len-1): only 1 full block may match — the tail block is
        # recomputed, never shared (copy-on-write by recompute)
        _, n = pc.peek(toks, max_tokens=7)
        assert n == 4

    def test_lru_eviction_spares_referenced_blocks(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        a = alloc.allocate(1)
        b = alloc.allocate(1)
        pc.insert(np.arange(4), a)
        pc.insert(np.arange(100, 104), b)
        alloc.free(a)                  # tree is now block a's only owner
        alloc.free(b)
        pc.acquire(np.arange(100, 104))   # pin b via a live reference, bump LRU
        assert pc.evictable_blocks() == 1
        assert pc.evict(2) == 1        # only a can go; b is pinned
        assert pc.peek(np.arange(4))[1] == 0
        assert pc.peek(np.arange(100, 104))[1] == 4

    def test_lru_order(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        a, b = alloc.allocate(1), alloc.allocate(1)
        pc.insert(np.arange(4), a)
        pc.insert(np.arange(100, 104), b)
        alloc.free(a)
        alloc.free(b)
        got, _ = pc.acquire(np.arange(4))   # refresh a: b is now LRU
        alloc.free(got)
        assert pc.evict(1) == 1
        assert pc.peek(np.arange(4))[1] == 4          # a survived
        assert pc.peek(np.arange(100, 104))[1] == 0   # b evicted

    def test_interior_nodes_evict_only_after_leaves(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        blocks = alloc.allocate(2)
        pc.insert(np.arange(8), blocks)    # chain: parent -> child
        alloc.free(blocks)
        assert pc.evict(1) == 1            # must take the LEAF (child)
        assert pc.peek(np.arange(8))[1] == 4   # parent still matches
        assert pc.evict(1) == 1
        assert alloc.free_blocks == 4

    def test_max_blocks_cap(self):
        alloc, pc = self._cache(num_blocks=8, bs=4, max_blocks=2)
        a = alloc.allocate(3)
        pc.insert(np.arange(12), a)
        assert pc._nodes == 2              # third block refused at the cap
        alloc.free(a)                      # tree keeps refs on the first two
        b = alloc.allocate(1)
        pc.insert(np.arange(100, 104), b)  # evicts LRU to stay at cap
        assert pc._nodes == 2
        assert pc.counters["evicted_blocks"] == 1

    def test_max_blocks_insert_never_orphans_descent_path(self):
        """At the cap, insert must NOT evict a node on the prefix it is
        descending — the new node would attach to a detached parent, an
        unreachable subtree whose cache references could never be released
        (review regression)."""
        alloc, pc = self._cache(num_blocks=8, bs=4, max_blocks=1)
        a = alloc.allocate(1)
        pc.insert(np.arange(4), a)         # node A fills the cap
        alloc.free(a)                      # A rc1: the sole evictable leaf
        b = alloc.allocate(2)
        pc.insert(np.arange(8), b)         # descends THROUGH A at the cap
        alloc.free(b)
        pc.clear()
        assert alloc.free_blocks == 8
        assert not alloc.leaked_blocks()

    def test_clear_releases_only_tree_refs(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        a = alloc.allocate(1)
        pc.insert(np.arange(4), a)
        assert pc.clear() == 1
        assert alloc.refcount(a[0]) == 1   # the live owner's ref remains
        alloc.free(a)
        assert alloc.free_blocks == 4 and not alloc.leaked_blocks()


# ---------------------------------------------------------------------------
# n-gram drafter
# ---------------------------------------------------------------------------

class TestNgramDraft:
    def test_draft_follows_most_recent_occurrence(self):
        h = [1, 2, 3, 9, 1, 2, 4, 7, 1, 2]
        d = list(ngram_draft(h, ngram=2, max_draft=3))
        assert d == [4, 7, 1]              # continuation of the LATEST [1,2]

    def test_backoff_to_shorter_ngram(self):
        h = [5, 6, 7, 8, 6]                # [8, 6] never repeats; [6] does
        assert list(ngram_draft(h, ngram=2, max_draft=2)) == [7, 8]

    def test_no_repeat_no_draft(self):
        assert ngram_draft([1, 2, 3, 4], ngram=3, max_draft=4).size == 0
        assert ngram_draft([1], ngram=3, max_draft=4).size == 0
        assert ngram_draft([1, 1], ngram=2, max_draft=0).size == 0


# ---------------------------------------------------------------------------
# engine integration (fp32 tiny model: exactness without bf16 tie noise;
# module-scoped SHARED engines — every fresh InferenceEngineV2 re-jits its
# whole step family, so tests reuse engines and reset state between them)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def f32_lm():
    model = TransformerLM(get_preset("tiny", dtype="float32"))
    params = model.init(jax.random.key(0))
    return model, params


_SPEC = {"enabled": True, "ngram": 2, "max_draft": 4, "fallback_steps": 4}


def _engine(model, params, **kw):
    base = dict(max_sequences=8, max_seq_len=128, block_size=16)
    base.update(kw)
    return InferenceEngineV2(model, params=params, **base)


def _reset(eng):
    """Back to a cold engine: flush every sequence, drop the prefix tree,
    zero the feature counters (they are lifetime-cumulative)."""
    eng.flush(list(eng.state.sequences))
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
        for k in eng.prefix_cache.counters:
            eng.prefix_cache.counters[k] = 0
    for k in eng.spec_stats:
        eng.spec_stats[k] = 0
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks, "leak from previous test"
    return eng


@pytest.fixture(scope="module")
def feat_eng(f32_lm):
    model, params = f32_lm
    return _engine(model, params, prefix_cache=True, speculative=_SPEC)


@pytest.fixture(scope="module")
def plain_eng(f32_lm):
    model, params = f32_lm
    return _engine(model, params)


@pytest.fixture(scope="module")
def small_eng(f32_lm):
    """Small pool for eviction-pressure tests."""
    model, params = f32_lm
    return _engine(model, params, prefix_cache=True, num_blocks=12,
                   max_seq_len=64)


def test_warm_prefix_cache_is_token_identical(feat_eng):
    """Same prompt cold vs prefix-cached: identical first token and
    identical greedy continuation, with the warm put skipping the cached
    full blocks (cache-exactness satellite)."""
    eng = _reset(feat_eng)
    rng = np.random.default_rng(0)
    prompt = np.concatenate([rng.integers(0, 250, 48),   # 3 full blocks
                             rng.integers(0, 250, 5)])
    r1 = eng.put([1], [prompt])
    t1 = int(np.argmax(r1[1]))
    cold = [int(x) for x in
            eng.decode_batch([1], [t1], steps=8, speculative=False)[1]]
    eng.flush([1])
    r2 = eng.put([2], [prompt])
    t2 = int(np.argmax(r2[2]))
    assert eng.prefix_cache.counters["hit_tokens"] == 48
    assert eng.state.sequences[2].seen_tokens == len(prompt)
    warm = [int(x) for x in
            eng.decode_batch([2], [t2], steps=8, speculative=False)[2]]
    assert t1 == t2 and cold == warm
    # shared blocks really are shared: the warm sequence holds the cached
    # prefix blocks at refcount >= 2 (sequence + tree)
    seq = eng.state.sequences[2]
    assert all(eng.state.allocator.refcount(b) >= 2 for b in seq.blocks[:3])
    eng.flush([2])
    assert eng.prefix_cache.clear() > 0
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()


def test_partial_prefix_match_prefills_only_suffix(feat_eng, plain_eng):
    eng = _reset(feat_eng)
    rng = np.random.default_rng(1)
    shared = rng.integers(0, 250, 32)                    # 2 full blocks
    p_a = np.concatenate([shared, rng.integers(0, 250, 20)])
    p_b = np.concatenate([shared, rng.integers(0, 250, 24)])
    ra = eng.put([1], [p_a])
    rb = eng.put([2], [p_b])                             # shares 32 tokens
    assert eng.prefix_cache.counters["hit_tokens"] == 32
    # exactness of the shared-prefix serve vs a cold engine
    cold = _reset(plain_eng)
    ca = cold.put([1], [p_a])
    cb = cold.put([2], [p_b])
    cold.flush([1, 2])
    assert int(np.argmax(ra[1])) == int(np.argmax(ca[1]))
    assert int(np.argmax(rb[2])) == int(np.argmax(cb[2]))


def test_fully_cached_prompt_still_computes_last_token(feat_eng):
    """A prompt that is one long cached prefix (length a block multiple)
    must cap the match below the prompt length so the forward still runs
    and yields first-token logits."""
    eng = _reset(feat_eng)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 250, 64)                    # exactly 4 blocks
    r1 = eng.put([1], [prompt])
    eng.flush([1])
    r2 = eng.put([2], [prompt])                          # 100% published
    # matched capped at 48 (< 64): the tail block is recomputed
    assert eng.state.sequences[2].seen_tokens == 64
    assert eng.prefix_cache.counters["hit_tokens"] == 48
    assert int(np.argmax(r1[1])) == int(np.argmax(r2[2]))


def test_speculative_greedy_token_identical(feat_eng):
    """Greedy decode with speculation on vs off is token-identical — on
    repetitive text (where n-gram drafting fires) AND on random text (where
    rounds mostly fall back). Satellite: >1 token emitted per verify round
    on repetitive text."""
    eng = _reset(feat_eng)
    for seed, prompt in ((3, np.tile([5, 6, 7, 8], 8)),
                         (4, np.random.default_rng(4).integers(0, 250, 30))):
        r = eng.put([1], [np.asarray(prompt)])
        t = int(np.argmax(r[1]))
        ref = [int(x) for x in
               eng.decode_batch([1], [t], steps=20, speculative=False)[1]]
        eng.flush([1])
        eng.put([2], [np.asarray(prompt)])
        got = [int(x) for x in
               eng.decode_batch([2], [t], steps=20, speculative=True)[2]]
        assert got == ref, (seed, got, ref)
        eng.flush([2])
    assert eng.spec_stats["rounds"] > 0
    # acceptance win on the repetitive prompt, measured in isolation
    _reset(eng)
    eng.put([1], [np.tile([5, 6, 7, 8], 8)])
    eng.decode_batch([1], [1], steps=24)
    s2 = eng.spec_stats
    assert s2["emitted"] / max(1, s2["rounds"]) > 1.0, s2


def test_spec_partial_accept_leaves_consistent_state(feat_eng, plain_eng):
    """After rounds with rejected drafts (stale KV beyond the frontier),
    continued decode must still match the non-speculative stream — the
    frontier math masks and later overwrites the stale rows."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 250, 20)
    eng = _reset(feat_eng)
    eng.put([1], [prompt])
    a = [int(x) for x in eng.decode_batch([1], [3], steps=10)[1]]
    b = [int(x) for x in eng.decode_batch([1], [a[-1]], steps=10)[1]]
    ref_eng = _reset(plain_eng)
    ref_eng.put([1], [prompt])
    ra = [int(x) for x in ref_eng.decode_batch([1], [3], steps=10)[1]]
    rb = [int(x) for x in ref_eng.decode_batch([1], [ra[-1]], steps=10)[1]]
    assert a == ra and b == rb
    assert eng.state.sequences[1].seen_tokens \
        == ref_eng.state.sequences[1].seen_tokens


def test_prefix_eviction_under_pool_pressure(small_eng):
    """Distinct published prefixes overflow a small pool: scheduling must
    reclaim LRU cache blocks instead of failing, and the pool must restore
    fully afterwards (no refcount leak)."""
    eng = _reset(small_eng)
    rng = np.random.default_rng(6)
    for uid in range(8):                       # 8 x 2 published blocks > 12
        eng.put([uid], [rng.integers(0, 250, 40)])
        eng.flush([uid])
    assert eng.prefix_cache.counters["evicted_blocks"] > 0
    assert len(eng.state.sequences) == 0
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()


def test_shared_blocks_never_evicted_or_double_freed(small_eng):
    """A block a live sequence shares (refcount > 1) must survive cache
    eviction pressure; flushing both owners releases it exactly once."""
    eng = _reset(small_eng)
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 250, 32)          # 2 blocks published
    eng.put([1], [np.concatenate([shared, rng.integers(0, 250, 4)])])
    eng.put([2], [np.concatenate([shared, rng.integers(0, 250, 4)])])
    pinned = eng.state.sequences[2].blocks[:2]
    assert all(eng.state.allocator.refcount(b) >= 3 for b in pinned)
    assert eng.prefix_cache.evict(12) == 0     # everything is pinned
    eng.flush([1, 2])
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks


def test_put_reject_is_side_effect_free_with_warm_cache(small_eng):
    """A fresh-uid put() that raises CapacityError must leave NO state —
    no slot, no cache refs, no seen_tokens — even when the prompt has a
    warm cached prefix, so the caller can free capacity and retry the
    SAME call (review regression: auto-attach used to run before the
    capacity check)."""
    from deepspeed_tpu.inference import CapacityError

    eng = _reset(small_eng)
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, 250, 40)          # 3 blocks, 2 published
    r1 = eng.put([1], [prompt])
    hog = eng.state.allocator.allocate(eng.state.allocator.free_blocks)
    with pytest.raises(CapacityError):
        eng.put([2], [prompt])                 # warm prefix, no room
    assert 2 not in eng.state.sequences        # no slot consumed
    assert eng._hist is not None and 2 not in eng._hist
    eng.state.allocator.free(hog)
    r2 = eng.put([2], [prompt])                # retry: attaches + succeeds
    assert eng.state.sequences[2].seen_tokens == 40
    assert eng.prefix_cache.counters["hit_tokens"] == 32
    assert int(np.argmax(r2[2])) == int(np.argmax(r1[1]))
    eng.flush([1, 2])


def test_config_blocks_reach_engine(f32_lm):
    from deepspeed_tpu.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(train_batch_size=8, inference={
        "prefix_cache": {"enabled": True, "max_blocks": 32},
        "speculative": {"enabled": True, "ngram": 4, "max_draft": 6}})
    assert cfg.inference.prefix_cache.max_blocks == 32
    assert cfg.inference.speculative.max_draft == 6
    model, params = f32_lm
    eng = InferenceEngineV2(model, params=params, max_sequences=2,
                            max_seq_len=64, block_size=16,
                            prefix_cache=cfg.inference.prefix_cache,
                            speculative=cfg.inference.speculative)
    assert eng.prefix_cache is not None and eng.prefix_cache.max_blocks == 32
    assert eng.spec_cfg.max_draft == 6
    with pytest.raises(ValueError, match="max_draft"):
        DeepSpeedTpuConfig(train_batch_size=8, inference={
            "speculative": {"enabled": True, "max_draft": 0}})
    # both features need the packed paged engine
    with pytest.raises(ValueError, match="packed"):
        InferenceEngineV2(model, params=params, max_sequences=2,
                          max_seq_len=64, prefix_cache=True, paged=False)


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

@pytest.mark.serving
def test_serving_prefix_spec_exact_and_metered(feat_eng, plain_eng):
    """The batcher with prefix cache + speculation serves the same token
    streams as the plain batcher, and the ``serving/spec_*`` +
    ``inference/prefix_cache_*`` metrics populate."""
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.observability import MetricsRegistry
    from deepspeed_tpu.serving import ContinuousBatcher

    rng = np.random.default_rng(8)
    system = rng.integers(0, 250, 48)
    prompts = [np.concatenate([system, rng.integers(0, 250, 6)])
               for _ in range(3)]

    def run(eng, registry=None):
        b = ContinuousBatcher(
            eng, ServingConfig(prefill_chunk=32, default_max_new_tokens=6),
            registry=registry)
        outs = []
        for p in prompts:              # sequential: later ones hit the cache
            uid = b.submit(p)
            b.pump(max_steps=100)
            outs.append(list(b.manager.done[uid].generated))
        return b, outs

    _, base = run(_reset(plain_eng))
    reg = MetricsRegistry()
    b, got = run(_reset(feat_eng), registry=reg)
    assert got == base
    rep = b.serving_report()
    assert rep["counters"]["prefix_hit_requests"] == 2
    assert rep["counters"]["prefix_hit_tokens"] == 96
    assert rep["counters"]["spec_rounds"] > 0
    assert rep["prefix_cache"]["hit_tokens"] == 96
    assert rep["speculative"]["rounds"] > 0
    assert reg.get("serving/spec_rounds") is not None
    # prefix-aware admission: a mostly-cached request's projected demand
    # counts only the uncached share
    req = type("R", (), {})()
    req.prompt = prompts[0]
    req.prompt_len = len(prompts[0])
    req.total_token_demand = len(prompts[0]) + 6
    assert b._blocks_needed(req) < b._blocks_for(req.total_token_demand)
    # cache-held blocks are reclaimable capacity, not load
    assert rep["kv"]["reclaimable_blocks"] > 0
    assert rep["kv"]["occupancy"] == 0.0
    b.engine.prefix_cache.clear()
    alloc = b.engine.state.allocator
    assert alloc.free_blocks == alloc.num_blocks


# ---------------------------------------------------------------------------
# drill wrapper (slow; the CLI is the invariant authority)
# ---------------------------------------------------------------------------

@pytest.mark.perf
@pytest.mark.slow
def test_prefix_storm_drill(tmp_path):
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    verdict = run_scenario("prefix-storm", workdir=str(tmp_path))
    assert verdict["ok"], verdict
