"""Prefix-cache KV reuse + n-gram speculative decoding tests.

Cache-exactness is the contract under test: the same prompt served cold vs
prefix-cached, and greedy decode with speculation on vs off, must produce
IDENTICAL tokens — sharing/drafting may only change how much work it takes
to produce them. Exactness tests run the tiny model in float32: in bf16 a
random-init model's near-tied logits can flip argmax between the (all
numerically-equivalent) attention kernel variants, which is a test-model
artifact, not a property of the mechanism (a trained model's logit margins
dwarf kernel rounding).

Also here: the refcounted-allocator satellite (double-free raises), the
duplicate-uid ``can_schedule_batch`` satellite, LRU eviction under pool
pressure, and refcount-leak-free pool restoration. The end-to-end
``prefix-storm`` drill lives in ``tools/serve_drill.py``; its slow wrapper
is at the bottom under the ``perf`` marker.
"""

import os

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import (BlockedAllocator, InferenceEngineV2,
                                     PrefixCache, SequenceManager,
                                     ngram_draft)
from deepspeed_tpu.models import TransformerLM, get_preset

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


# ---------------------------------------------------------------------------
# refcounted allocator (satellite: double-free must raise)
# ---------------------------------------------------------------------------

class TestRefcountedAllocator:
    def test_double_free_raises(self):
        alloc = BlockedAllocator(num_blocks=4, block_size=8)
        a = alloc.allocate(2)
        alloc.free(a)
        assert alloc.free_blocks == 4
        with pytest.raises(RuntimeError, match="double free"):
            alloc.free(a)              # second free of the same blocks
        # the failed free must not have corrupted the free list
        assert alloc.free_blocks == 4
        with pytest.raises(RuntimeError, match="double free"):
            alloc.free([0])            # never-reallocated block

    def test_shared_block_needs_one_free_per_owner(self):
        alloc = BlockedAllocator(num_blocks=2, block_size=8)
        [b] = alloc.allocate(1)
        alloc.incref([b])              # second owner (e.g. the prefix tree)
        alloc.free([b])                # first owner releases
        assert alloc.free_blocks == 1  # still held by the second owner
        assert alloc.refcount(b) == 1
        alloc.free([b])
        assert alloc.free_blocks == 2
        with pytest.raises(RuntimeError, match="double free"):
            alloc.free([b])

    def test_incref_of_free_block_raises(self):
        alloc = BlockedAllocator(num_blocks=2, block_size=8)
        with pytest.raises(RuntimeError, match="unallocated"):
            alloc.incref([0])


# ---------------------------------------------------------------------------
# duplicate-uid joint schedulability (satellite)
# ---------------------------------------------------------------------------

class TestDuplicateUidBatch:
    def test_duplicate_uid_blocks_costed_cumulatively(self):
        """A uid listed twice must be costed against its PROJECTED state
        after the first occurrence — the old per-occurrence check read the
        original ``seen_tokens`` twice and undercounted block demand."""
        sm = SequenceManager(max_sequences=2, max_seq_len=64, block_size=8,
                             num_blocks=8)
        sm.schedule(1, 4)
        sm.commit(1)                   # seen=4, holds 1 block (4/8 used)
        taken = sm.allocator.allocate(sm.allocator.free_blocks)  # drain pool
        # two 4-token chunks: cumulative 4+8=12 tokens -> needs a 2nd block;
        # per-occurrence math said ceil(8/8)-1 = 0 twice -> "schedulable"
        assert not sm.can_schedule_batch([1, 1], [4, 4])
        sm.allocator.free(taken)
        assert sm.can_schedule_batch([1, 1], [4, 4])

    def test_duplicate_uid_seq_len_costed_cumulatively(self):
        sm = SequenceManager(max_sequences=2, max_seq_len=32, block_size=8)
        sm.schedule(1, 30)
        sm.commit(1)
        # each occurrence alone fits (30+2 <= 32); jointly 34 > 32
        assert sm.can_schedule_batch([1], [2])
        assert not sm.can_schedule_batch([1, 1], [2, 2])

    def test_duplicate_new_uid_counts_one_slot(self):
        sm = SequenceManager(max_sequences=1, max_seq_len=32, block_size=8)
        assert sm.can_schedule_batch([7, 7], [4, 4])   # one slot, not two


# ---------------------------------------------------------------------------
# PrefixCache state machine (no engine)
# ---------------------------------------------------------------------------

class TestPrefixCacheState:
    def _cache(self, num_blocks=8, bs=4, **kw):
        alloc = BlockedAllocator(num_blocks, bs)
        return alloc, PrefixCache(alloc, **kw)

    def test_full_block_granularity_and_roundtrip(self):
        alloc, pc = self._cache()
        toks = np.arange(10, dtype=np.int32)          # 2 full blocks + tail 2
        blocks = alloc.allocate(3)
        assert pc.insert(toks, blocks) == 2           # tail block not cached
        got, n = pc.peek(toks)
        assert n == 8 and got == blocks[:2]
        # a diverging second block matches only the first
        other = np.concatenate([toks[:4], toks[4:8] + 1])
        _, n2 = pc.peek(other)
        assert n2 == 4
        # acquire takes a reference per matched block
        acq, n3 = pc.acquire(toks)
        assert n3 == 8
        assert alloc.refcount(blocks[0]) == 3         # owner + tree + acquire
        assert pc.counters["hits"] == 1 and pc.counters["hit_tokens"] == 8

    def test_max_tokens_caps_at_full_blocks(self):
        alloc, pc = self._cache()
        toks = np.arange(8, dtype=np.int32)
        pc.insert(toks, alloc.allocate(2))
        # cap 7 (len-1): only 1 full block may match — the tail block is
        # recomputed, never shared (copy-on-write by recompute)
        _, n = pc.peek(toks, max_tokens=7)
        assert n == 4

    def test_lru_eviction_spares_referenced_blocks(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        a = alloc.allocate(1)
        b = alloc.allocate(1)
        pc.insert(np.arange(4), a)
        pc.insert(np.arange(100, 104), b)
        alloc.free(a)                  # tree is now block a's only owner
        alloc.free(b)
        pc.acquire(np.arange(100, 104))   # pin b via a live reference, bump LRU
        assert pc.evictable_blocks() == 1
        assert pc.evict(2) == 1        # only a can go; b is pinned
        assert pc.peek(np.arange(4))[1] == 0
        assert pc.peek(np.arange(100, 104))[1] == 4

    def test_lru_order(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        a, b = alloc.allocate(1), alloc.allocate(1)
        pc.insert(np.arange(4), a)
        pc.insert(np.arange(100, 104), b)
        alloc.free(a)
        alloc.free(b)
        got, _ = pc.acquire(np.arange(4))   # refresh a: b is now LRU
        alloc.free(got)
        assert pc.evict(1) == 1
        assert pc.peek(np.arange(4))[1] == 4          # a survived
        assert pc.peek(np.arange(100, 104))[1] == 0   # b evicted

    def test_interior_nodes_evict_only_after_leaves(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        blocks = alloc.allocate(2)
        pc.insert(np.arange(8), blocks)    # chain: parent -> child
        alloc.free(blocks)
        assert pc.evict(1) == 1            # must take the LEAF (child)
        assert pc.peek(np.arange(8))[1] == 4   # parent still matches
        assert pc.evict(1) == 1
        assert alloc.free_blocks == 4

    def test_max_blocks_cap(self):
        alloc, pc = self._cache(num_blocks=8, bs=4, max_blocks=2)
        a = alloc.allocate(3)
        pc.insert(np.arange(12), a)
        assert pc._nodes == 2              # third block refused at the cap
        alloc.free(a)                      # tree keeps refs on the first two
        b = alloc.allocate(1)
        pc.insert(np.arange(100, 104), b)  # evicts LRU to stay at cap
        assert pc._nodes == 2
        assert pc.counters["evicted_blocks"] == 1

    def test_max_blocks_insert_never_orphans_descent_path(self):
        """At the cap, insert must NOT evict a node on the prefix it is
        descending — the new node would attach to a detached parent, an
        unreachable subtree whose cache references could never be released
        (review regression)."""
        alloc, pc = self._cache(num_blocks=8, bs=4, max_blocks=1)
        a = alloc.allocate(1)
        pc.insert(np.arange(4), a)         # node A fills the cap
        alloc.free(a)                      # A rc1: the sole evictable leaf
        b = alloc.allocate(2)
        pc.insert(np.arange(8), b)         # descends THROUGH A at the cap
        alloc.free(b)
        pc.clear()
        assert alloc.free_blocks == 8
        assert not alloc.leaked_blocks()

    def test_clear_releases_only_tree_refs(self):
        alloc, pc = self._cache(num_blocks=4, bs=4)
        a = alloc.allocate(1)
        pc.insert(np.arange(4), a)
        assert pc.clear() == 1
        assert alloc.refcount(a[0]) == 1   # the live owner's ref remains
        alloc.free(a)
        assert alloc.free_blocks == 4 and not alloc.leaked_blocks()


# ---------------------------------------------------------------------------
# n-gram drafter
# ---------------------------------------------------------------------------

class TestNgramDraft:
    def test_draft_follows_most_recent_occurrence(self):
        h = [1, 2, 3, 9, 1, 2, 4, 7, 1, 2]
        d = list(ngram_draft(h, ngram=2, max_draft=3))
        assert d == [4, 7, 1]              # continuation of the LATEST [1,2]

    def test_backoff_to_shorter_ngram(self):
        h = [5, 6, 7, 8, 6]                # [8, 6] never repeats; [6] does
        assert list(ngram_draft(h, ngram=2, max_draft=2)) == [7, 8]

    def test_no_repeat_no_draft(self):
        assert ngram_draft([1, 2, 3, 4], ngram=3, max_draft=4).size == 0
        assert ngram_draft([1], ngram=3, max_draft=4).size == 0
        assert ngram_draft([1, 1], ngram=2, max_draft=0).size == 0


# ---------------------------------------------------------------------------
# engine integration (fp32 tiny model: exactness without bf16 tie noise;
# module-scoped SHARED engines — every fresh InferenceEngineV2 re-jits its
# whole step family, so tests reuse engines and reset state between them)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def f32_lm():
    model = TransformerLM(get_preset("tiny", dtype="float32"))
    params = model.init(jax.random.key(0))
    return model, params


_SPEC = {"enabled": True, "ngram": 2, "max_draft": 4, "fallback_steps": 4}


def _engine(model, params, **kw):
    base = dict(max_sequences=8, max_seq_len=128, block_size=16)
    base.update(kw)
    return InferenceEngineV2(model, params=params, **base)


def _reset(eng):
    """Back to a cold engine: flush every sequence, drop the prefix tree,
    zero the feature counters (they are lifetime-cumulative)."""
    eng.flush(list(eng.state.sequences))
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
        for k in eng.prefix_cache.counters:
            eng.prefix_cache.counters[k] = 0
    for k in eng.spec_stats:
        eng.spec_stats[k] = 0
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks, "leak from previous test"
    return eng


@pytest.fixture(scope="module")
def feat_eng(f32_lm):
    model, params = f32_lm
    return _engine(model, params, prefix_cache=True, speculative=_SPEC)


@pytest.fixture(scope="module")
def plain_eng(f32_lm):
    model, params = f32_lm
    return _engine(model, params)


@pytest.fixture(scope="module")
def small_eng(f32_lm):
    """Small pool for eviction-pressure tests."""
    model, params = f32_lm
    return _engine(model, params, prefix_cache=True, num_blocks=12,
                   max_seq_len=64)


def test_warm_prefix_cache_is_token_identical(feat_eng):
    """Same prompt cold vs prefix-cached: identical first token and
    identical greedy continuation, with the warm put skipping the cached
    full blocks (cache-exactness satellite)."""
    eng = _reset(feat_eng)
    rng = np.random.default_rng(0)
    prompt = np.concatenate([rng.integers(0, 250, 48),   # 3 full blocks
                             rng.integers(0, 250, 5)])
    r1 = eng.put([1], [prompt])
    t1 = int(np.argmax(r1[1]))
    cold = [int(x) for x in
            eng.decode_batch([1], [t1], steps=8, speculative=False)[1]]
    eng.flush([1])
    r2 = eng.put([2], [prompt])
    t2 = int(np.argmax(r2[2]))
    assert eng.prefix_cache.counters["hit_tokens"] == 48
    assert eng.state.sequences[2].seen_tokens == len(prompt)
    warm = [int(x) for x in
            eng.decode_batch([2], [t2], steps=8, speculative=False)[2]]
    assert t1 == t2 and cold == warm
    # shared blocks really are shared: the warm sequence holds the cached
    # prefix blocks at refcount >= 2 (sequence + tree)
    seq = eng.state.sequences[2]
    assert all(eng.state.allocator.refcount(b) >= 2 for b in seq.blocks[:3])
    eng.flush([2])
    assert eng.prefix_cache.clear() > 0
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()


def test_partial_prefix_match_prefills_only_suffix(feat_eng, plain_eng):
    eng = _reset(feat_eng)
    rng = np.random.default_rng(1)
    shared = rng.integers(0, 250, 32)                    # 2 full blocks
    p_a = np.concatenate([shared, rng.integers(0, 250, 20)])
    p_b = np.concatenate([shared, rng.integers(0, 250, 24)])
    ra = eng.put([1], [p_a])
    rb = eng.put([2], [p_b])                             # shares 32 tokens
    assert eng.prefix_cache.counters["hit_tokens"] == 32
    # exactness of the shared-prefix serve vs a cold engine
    cold = _reset(plain_eng)
    ca = cold.put([1], [p_a])
    cb = cold.put([2], [p_b])
    cold.flush([1, 2])
    assert int(np.argmax(ra[1])) == int(np.argmax(ca[1]))
    assert int(np.argmax(rb[2])) == int(np.argmax(cb[2]))


def test_fully_cached_prompt_still_computes_last_token(feat_eng):
    """A prompt that is one long cached prefix (length a block multiple)
    must cap the match below the prompt length so the forward still runs
    and yields first-token logits."""
    eng = _reset(feat_eng)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 250, 64)                    # exactly 4 blocks
    r1 = eng.put([1], [prompt])
    eng.flush([1])
    r2 = eng.put([2], [prompt])                          # 100% published
    # matched capped at 48 (< 64): the tail block is recomputed
    assert eng.state.sequences[2].seen_tokens == 64
    assert eng.prefix_cache.counters["hit_tokens"] == 48
    assert int(np.argmax(r1[1])) == int(np.argmax(r2[2]))


def test_speculative_greedy_token_identical(feat_eng):
    """Greedy decode with speculation on vs off is token-identical — on
    repetitive text (where n-gram drafting fires) AND on random text (where
    rounds mostly fall back). Satellite: >1 token emitted per verify round
    on repetitive text."""
    eng = _reset(feat_eng)
    for seed, prompt in ((3, np.tile([5, 6, 7, 8], 8)),
                         (4, np.random.default_rng(4).integers(0, 250, 30))):
        r = eng.put([1], [np.asarray(prompt)])
        t = int(np.argmax(r[1]))
        ref = [int(x) for x in
               eng.decode_batch([1], [t], steps=20, speculative=False)[1]]
        eng.flush([1])
        eng.put([2], [np.asarray(prompt)])
        got = [int(x) for x in
               eng.decode_batch([2], [t], steps=20, speculative=True)[2]]
        assert got == ref, (seed, got, ref)
        eng.flush([2])
    assert eng.spec_stats["rounds"] > 0
    # acceptance win on the repetitive prompt, measured in isolation
    _reset(eng)
    eng.put([1], [np.tile([5, 6, 7, 8], 8)])
    eng.decode_batch([1], [1], steps=24)
    s2 = eng.spec_stats
    assert s2["emitted"] / max(1, s2["rounds"]) > 1.0, s2


def test_spec_partial_accept_leaves_consistent_state(feat_eng, plain_eng):
    """After rounds with rejected drafts (stale KV beyond the frontier),
    continued decode must still match the non-speculative stream — the
    frontier math masks and later overwrites the stale rows."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 250, 20)
    eng = _reset(feat_eng)
    eng.put([1], [prompt])
    a = [int(x) for x in eng.decode_batch([1], [3], steps=10)[1]]
    b = [int(x) for x in eng.decode_batch([1], [a[-1]], steps=10)[1]]
    ref_eng = _reset(plain_eng)
    ref_eng.put([1], [prompt])
    ra = [int(x) for x in ref_eng.decode_batch([1], [3], steps=10)[1]]
    rb = [int(x) for x in ref_eng.decode_batch([1], [ra[-1]], steps=10)[1]]
    assert a == ra and b == rb
    assert eng.state.sequences[1].seen_tokens \
        == ref_eng.state.sequences[1].seen_tokens


def test_prefix_eviction_under_pool_pressure(small_eng):
    """Distinct published prefixes overflow a small pool: scheduling must
    reclaim LRU cache blocks instead of failing, and the pool must restore
    fully afterwards (no refcount leak)."""
    eng = _reset(small_eng)
    rng = np.random.default_rng(6)
    for uid in range(8):                       # 8 x 2 published blocks > 12
        eng.put([uid], [rng.integers(0, 250, 40)])
        eng.flush([uid])
    assert eng.prefix_cache.counters["evicted_blocks"] > 0
    assert len(eng.state.sequences) == 0
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()


def test_shared_blocks_never_evicted_or_double_freed(small_eng):
    """A block a live sequence shares (refcount > 1) must survive cache
    eviction pressure; flushing both owners releases it exactly once."""
    eng = _reset(small_eng)
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 250, 32)          # 2 blocks published
    eng.put([1], [np.concatenate([shared, rng.integers(0, 250, 4)])])
    eng.put([2], [np.concatenate([shared, rng.integers(0, 250, 4)])])
    pinned = eng.state.sequences[2].blocks[:2]
    assert all(eng.state.allocator.refcount(b) >= 3 for b in pinned)
    assert eng.prefix_cache.evict(12) == 0     # everything is pinned
    eng.flush([1, 2])
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks


def test_put_reject_is_side_effect_free_with_warm_cache(small_eng):
    """A fresh-uid put() that raises CapacityError must leave NO state —
    no slot, no cache refs, no seen_tokens — even when the prompt has a
    warm cached prefix, so the caller can free capacity and retry the
    SAME call (review regression: auto-attach used to run before the
    capacity check)."""
    from deepspeed_tpu.inference import CapacityError

    eng = _reset(small_eng)
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, 250, 40)          # 3 blocks, 2 published
    r1 = eng.put([1], [prompt])
    hog = eng.state.allocator.allocate(eng.state.allocator.free_blocks)
    with pytest.raises(CapacityError):
        eng.put([2], [prompt])                 # warm prefix, no room
    assert 2 not in eng.state.sequences        # no slot consumed
    assert eng._hist is not None and 2 not in eng._hist
    eng.state.allocator.free(hog)
    r2 = eng.put([2], [prompt])                # retry: attaches + succeeds
    assert eng.state.sequences[2].seen_tokens == 40
    assert eng.prefix_cache.counters["hit_tokens"] == 32
    assert int(np.argmax(r2[2])) == int(np.argmax(r1[1]))
    eng.flush([1, 2])


def test_config_blocks_reach_engine(f32_lm):
    from deepspeed_tpu.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(train_batch_size=8, inference={
        "prefix_cache": {"enabled": True, "max_blocks": 32},
        "speculative": {"enabled": True, "ngram": 4, "max_draft": 6}})
    assert cfg.inference.prefix_cache.max_blocks == 32
    assert cfg.inference.speculative.max_draft == 6
    model, params = f32_lm
    eng = InferenceEngineV2(model, params=params, max_sequences=2,
                            max_seq_len=64, block_size=16,
                            prefix_cache=cfg.inference.prefix_cache,
                            speculative=cfg.inference.speculative)
    assert eng.prefix_cache is not None and eng.prefix_cache.max_blocks == 32
    assert eng.spec_cfg.max_draft == 6
    with pytest.raises(ValueError, match="max_draft"):
        DeepSpeedTpuConfig(train_batch_size=8, inference={
            "speculative": {"enabled": True, "max_draft": 0}})
    # both features need the packed paged engine
    with pytest.raises(ValueError, match="packed"):
        InferenceEngineV2(model, params=params, max_sequences=2,
                          max_seq_len=64, prefix_cache=True, paged=False)


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------

@pytest.mark.serving
def test_serving_prefix_spec_exact_and_metered(feat_eng, plain_eng):
    """The batcher with prefix cache + speculation serves the same token
    streams as the plain batcher, and the ``serving/spec_*`` +
    ``inference/prefix_cache_*`` metrics populate."""
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.observability import MetricsRegistry
    from deepspeed_tpu.serving import ContinuousBatcher

    rng = np.random.default_rng(8)
    system = rng.integers(0, 250, 48)
    prompts = [np.concatenate([system, rng.integers(0, 250, 6)])
               for _ in range(3)]

    def run(eng, registry=None):
        b = ContinuousBatcher(
            eng, ServingConfig(prefill_chunk=32, default_max_new_tokens=6),
            registry=registry)
        outs = []
        for p in prompts:              # sequential: later ones hit the cache
            uid = b.submit(p)
            b.pump(max_steps=100)
            outs.append(list(b.manager.done[uid].generated))
        return b, outs

    _, base = run(_reset(plain_eng))
    reg = MetricsRegistry()
    b, got = run(_reset(feat_eng), registry=reg)
    assert got == base
    rep = b.serving_report()
    assert rep["counters"]["prefix_hit_requests"] == 2
    assert rep["counters"]["prefix_hit_tokens"] == 96
    assert rep["counters"]["spec_rounds"] > 0
    assert rep["prefix_cache"]["hit_tokens"] == 96
    assert rep["speculative"]["rounds"] > 0
    assert reg.get("serving/spec_rounds") is not None
    # prefix-aware admission: a mostly-cached request's projected demand
    # counts only the uncached share
    req = type("R", (), {})()
    req.prompt = prompts[0]
    req.prompt_len = len(prompts[0])
    req.total_token_demand = len(prompts[0]) + 6
    assert b._blocks_needed(req) < b._blocks_for(req.total_token_demand)
    # cache-held blocks are reclaimable capacity, not load
    assert rep["kv"]["reclaimable_blocks"] > 0
    assert rep["kv"]["occupancy"] == 0.0
    b.engine.prefix_cache.clear()
    alloc = b.engine.state.allocator
    assert alloc.free_blocks == alloc.num_blocks


# ---------------------------------------------------------------------------
# tiered KV spill: KVTierStore semantics (host budget, NVMe spill, loans)
# ---------------------------------------------------------------------------

def _payload(v, shape=(2, 4)):
    return {"k": np.full(shape, v, np.float32),
            "v": np.full(shape, -v, np.float32)}


class TestKVTierStore:
    def test_host_roundtrip_and_discard(self):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=1.0)
        assert store.put(0, _payload(3))
        f = store.fetch_start(0)
        assert f.tier == "host"
        parts = f.wait()
        assert np.array_equal(parts["k"], _payload(3)["k"])
        f.release()
        store.discard(0)
        assert store.entries() == 0
        assert store.pool.report()["outstanding"] == 0

    def test_spill_to_nvme_and_promote(self, tmp_path):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        # budget holds ~1 entry (64 B payloads): older entries must spill
        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path))
        for i in range(3):
            store.put(i, _payload(i))
        rep = store.report()
        assert rep["nvme_entries"] >= 1 and rep["nvme_demotions"] >= 1
        f = store.fetch_start(0)              # oldest: must be on NVMe
        assert f.tier == "nvme"
        assert np.array_equal(f.wait()["k"], _payload(0)["k"])
        f.release()
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_host_budget_without_nvme_drops_via_callback(self):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        dropped = []
        store = KVTierStore(host_mb=100 / 2**20, on_drop=dropped.append)
        for i in range(4):
            store.put(i, _payload(i))
        assert dropped and all(store.tier_of(k) is None for k in dropped)
        assert store.counters["dropped"] == len(dropped)
        # the survivors still fetch
        live = [k for k in range(4) if store.has(k)]
        assert live
        f = store.fetch_start(live[-1])
        f.wait()
        f.release()
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_loaned_entry_never_spilled(self):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        dropped = []
        store = KVTierStore(host_mb=100 / 2**20, on_drop=dropped.append)
        store.put(0, _payload(0))
        f = store.fetch_start(0)              # pins entry 0
        parts = f.wait()
        before = parts["k"].copy()
        for i in range(1, 5):                 # budget pressure on top
            store.put(i, _payload(i))
        # the loaned entry survived and its bytes were never recycled
        assert store.has(0) and 0 not in dropped
        assert np.array_equal(parts["k"], before)
        # a discard mid-loan defers until the fetch releases
        store.discard(0)
        assert store.has(0)
        f.release()
        assert not store.has(0)
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_put_never_drops_its_own_entry_mid_spill(self):
        # host budget below one entry, no NVMe, every older entry pinned
        # by a live fetch: the spill inside put() must not drop the entry
        # being inserted — on_drop would fire before the radix cache has
        # recorded the handle, leaving a demoted node with a dead handle
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=40 / 2**20)   # < one 64-byte entry
        dropped = []
        store.on_drop = dropped.append
        store.put(1, _payload(1))
        f = store.fetch_start(1)                  # pins entry 1
        store.put(2, _payload(2))                 # over budget, 1 pinned
        assert store.has(2) and not dropped       # 2 survives its own put
        f.release()
        store.put(3, _payload(3))                 # older entries now fair game
        assert store.has(3) and set(dropped) == {1, 2}
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_promote_depth_defers_read_submission(self, tmp_path):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=1 / 2**20, nvme_path=str(tmp_path),
                            promote_depth=1)
        for i in range(3):
            store.put(i, _payload(i))
        assert store.report()["nvme_entries"] >= 2
        f0 = store.fetch_start(0)
        f1 = store.fetch_start(1)
        assert f0.submitted and not f1.submitted   # depth 1: second defers
        assert np.array_equal(f0.wait()["k"], _payload(0)["k"])
        assert np.array_equal(f1.wait()["k"], _payload(1)["k"])
        f0.release()
        f1.release()
        store.close()
        assert store.pool.report()["outstanding"] == 0


class TestNvmeBoundsAndBatchedPromotes:
    """PR-12 follow-ups: NVMe entry cap + TTL (tiers.nvme_max_mb /
    tiers.nvme_ttl_s, LRU+TTL enforced in _spill) and one AIO ticket per
    promote chain instead of one per block."""

    def test_nvme_cap_lru_drops_oldest(self, tmp_path):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        dropped = []
        # host holds ~1 entry; NVMe capped at ~2 entries (64 B payloads)
        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path),
                            nvme_max_mb=150 / 2**20,
                            on_drop=dropped.append)
        for i in range(6):
            store.put(i, _payload(i))
        rep = store.report()
        assert rep["nvme_cap_dropped"] >= 1
        assert rep["nvme_bytes"] <= store.nvme_max_bytes
        assert dropped and dropped == sorted(dropped)   # oldest-first LRU
        # survivors still fetch bit-exact
        live = [k for k in range(6) if store.tier_of(k) == "nvme"]
        assert live
        f = store.fetch_start(live[-1])
        assert np.array_equal(f.wait()["k"], _payload(live[-1])["k"])
        f.release()
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_nvme_ttl_drops_idle_entries(self, tmp_path):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        clock = [0.0]
        dropped = []
        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path),
                            nvme_ttl_s=10.0, on_drop=dropped.append)
        store._now = lambda: clock[0]
        store.put(0, _payload(0))
        store.put(1, _payload(1))           # 0 spills to NVMe
        assert store.tier_of(0) == "nvme"
        clock[0] = 5.0
        f = store.fetch_start(0)            # touch refreshes the TTL clock
        f.wait()
        f.release()
        clock[0] = 12.0                     # 0 idle 7s, fresh enough
        store.put(2, _payload(2))           # spill -> bounds sweep
        assert store.has(0)
        clock[0] = 30.0                     # idle 18s > ttl
        store.put(3, _payload(3))
        assert not store.has(0)
        assert store.counters["nvme_ttl_dropped"] >= 1
        assert 0 in dropped
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_chain_batches_reads_into_one_ticket(self, tmp_path):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path))
        for i in range(4):
            store.put(i, _payload(i))
        keys = [k for k in range(4) if store.tier_of(k) == "nvme"]
        assert len(keys) >= 3
        singles, batches = [], []
        orig_one = store.swapper.swap_in_start
        orig_many = store.swapper.swap_in_start_many
        store.swapper.swap_in_start = \
            lambda n: singles.append(n) or orig_one(n)
        store.swapper.swap_in_start_many = \
            lambda ns: batches.append(list(ns)) or orig_many(ns)
        assert store.begin_chain(keys)
        try:
            fetches = [store.fetch_start(k) for k in keys]
            for k, f in zip(keys, fetches):
                assert f.tier == "nvme"
                assert np.array_equal(f.wait()["k"], _payload(k)["k"])
        finally:
            store.end_chain()
        for f in fetches:
            f.release()
        assert len(batches) == 1 and len(batches[0]) == len(keys)
        assert not singles                   # ONE ticket for the chain
        assert store.counters["batched_reads"] == 1
        assert store._reads_inflight == 0
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_chain_lazy_past_promote_depth(self, tmp_path):
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path),
                            promote_depth=1)
        for i in range(4):
            store.put(i, _payload(i))
        keys = [k for k in range(4) if store.tier_of(k) == "nvme"][:2]
        blocker = store.fetch_start(keys[0])     # occupies the one slot
        assert store.begin_chain(keys)           # arms LAZY (depth hit)
        try:
            f = store.fetch_start(keys[1])
            assert f._batch is not None and f._batch.ticket is None
            blocker.wait()
            blocker.release()
            # first wait submits the batch at the fence
            assert np.array_equal(f.wait()["k"], _payload(keys[1])["k"])
        finally:
            store.end_chain()
        f.release()
        assert store._reads_inflight == 0
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_nvme_bounds_survive_reentrant_discard(self, tmp_path):
        """Evicting one NVMe entry fires on_drop -> _drop_subtree, which
        can discard OTHER NVMe entries (demoted descendants) while the
        TTL/cap sweep iterates its key snapshot — the sweep must skip
        the vanished keys, not KeyError on the serving hot path."""
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        clock = [0.0]
        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path),
                            nvme_ttl_s=5.0)
        store._now = lambda: clock[0]
        # dropping either of {0, 1} discards the other (the radix tree
        # dropping a parent's demoted descendant subtree)
        store.on_drop = lambda k: store.discard(1 - k) if k in (0, 1) \
            else None
        for i in range(3):
            store.put(i, _payload(i))
        assert store.tier_of(0) == "nvme" and store.tier_of(1) == "nvme"
        clock[0] = 30.0                       # both expired
        store.put(3, _payload(3))             # sweep runs — must not raise
        assert not store.has(0) and not store.has(1)
        assert store.counters["nvme_ttl_dropped"] >= 1
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_lazy_chain_submits_only_ridden_names(self, tmp_path):
        """A LAZY batch submits at the first rider's fence-time wait —
        by then end_chain has unpinned the chain members nothing rode,
        and those may have been evicted (their _meta gone). The submit
        must cover only the CLAIMED names or one stale member poisons
        every intact rider."""
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path),
                            promote_depth=1)
        for i in range(5):
            store.put(i, _payload(i))
        keys = [k for k in range(5) if store.tier_of(k) == "nvme"]
        assert len(keys) >= 4
        blocker = store.fetch_start(keys[0])   # occupies the one slot
        assert store.begin_chain(keys[1:4])    # arms LAZY
        try:
            f1 = store.fetch_start(keys[1])
            f2 = store.fetch_start(keys[2])    # keys[3] never ridden
        finally:
            store.end_chain()
        store.discard(keys[3])                 # unridden member vanishes
        blocker.wait()
        blocker.release()
        assert np.array_equal(f1.wait()["k"], _payload(keys[1])["k"])
        assert np.array_equal(f2.wait()["k"], _payload(keys[2])["k"])
        f1.release()
        f2.release()
        assert store._reads_inflight == 0
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_begin_chain_survives_failed_demote_write(self, tmp_path):
        """A torn demote write (failed wticket) must degrade to a
        per-block tier miss inside begin_chain — raising would crash the
        whole serving acquire, and the pre-existing single-read paths
        already degrade."""
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path))
        for i in range(5):
            store.put(i, _payload(i))
        keys = [k for k in range(5) if store.tier_of(k) == "nvme"]
        assert len(keys) >= 3

        class BoomTicket:
            def wait(self):
                raise IOError("torn demote write")

        store._nvme[keys[0]].wticket = BoomTicket()
        assert store.begin_chain(keys)        # must not raise
        try:
            assert not store.has(keys[0])     # torn entry -> miss/drop
            assert store.counters["nvme_misses"] >= 1
            f = store.fetch_start(keys[1])    # survivors still serve
            assert np.array_equal(f.wait()["k"], _payload(keys[1])["k"])
        finally:
            store.end_chain()
        f.release()
        assert store._reads_inflight == 0
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_unridden_batch_members_pinned_until_ticket_release(
            self, tmp_path):
        """An EAGER batch submits preads for every chain member; members
        nothing rode must stay pinned past end_chain until the shared
        ticket dies — evicting one would unlink a file a pread still
        targets (AsyncTensorSwapper.discard's documented contract)."""
        from deepspeed_tpu.inference.kv_tier import KVTierStore

        # cap holds the 3-entry chain (64 B each) with no slack for more
        store = KVTierStore(host_mb=100 / 2**20, nvme_path=str(tmp_path),
                            nvme_max_mb=200 / 2**20)
        for i in range(4):
            store.put(i, _payload(i))
        keys = [k for k in range(4) if store.tier_of(k) == "nvme"][:3]
        assert len(keys) == 3
        assert store.begin_chain(keys)
        f = store.fetch_start(keys[0])        # only keys[0] rides
        store.end_chain()
        # cap pressure while the shared ticket is alive: the unridden
        # members' reads are in flight — the sweep must skip them
        store.put(8, _payload(8))
        store.put(9, _payload(9))
        assert store.has(keys[1]) and store.has(keys[2])
        assert np.array_equal(f.wait()["k"], _payload(keys[0])["k"])
        f.release()                           # ticket dies: members unpin
        store.put(10, _payload(10))           # sweep can now enforce cap
        assert store.report()["nvme_bytes"] <= store.nvme_max_bytes
        assert store._reads_inflight == 0
        store.close()
        assert store.pool.report()["outstanding"] == 0

    def test_acquire_pins_chain_before_deficit_eviction(self):
        """acquire's make-room eviction demotes blocks, which can push
        the NVMe tier over its cap — the LRU sweep must not drop the
        very chain entries this acquire is about to promote (they are
        the LRU-oldest). begin_chain pins them FIRST."""
        import tempfile

        with tempfile.TemporaryDirectory() as nvme:
            alloc, pc, store, publish = _tiered_cache(
                num_blocks=4, host_mb=40 / 2**20, nvme_path=nvme,
                nvme_max_mb=150 / 2**20)
            toksA = np.arange(12, dtype=np.int32)
            publish(toksA, 1)
            pc.evict(3)                   # A: 2 entries NVMe + 1 host
            assert store.report()["nvme_entries"] == 2
            publish(np.arange(100, 112, dtype=np.int32), 2)  # B fills pool
            assert alloc.free_blocks == 1
            # acquire A: deficit eviction demotes B -> host spill -> NVMe
            # over cap -> sweep; A's batched entries must survive it
            blocks, n = pc.acquire(toksA)
            assert n >= 8                 # the pinned chain promoted
            recs = pc.drain_promotes()
            for r in recs:
                r.fetch.wait()
                r.fetch.release()
                store.discard(r.key)
            pc.mark_uploaded(recs)
            if blocks:
                alloc.free(blocks)
            pc.clear()
            assert not alloc.leaked_blocks()
            assert store.pool.report()["outstanding"] == 0
            store.close()

    def test_acquire_chain_uses_one_ticket(self):
        """End-to-end through PrefixCache.acquire: a 2-block demoted NVMe
        chain promotes through ONE batched read, promote_ms semantics
        unchanged (each record still carries its own fetch + t_start)."""
        import tempfile

        with tempfile.TemporaryDirectory() as nvme:
            alloc, pc, store, publish = _tiered_cache(
                host_mb=40 / 2**20, nvme_path=nvme)
            toks = np.arange(12, dtype=np.int32)
            publish(toks, 9)
            pc.evict(3)
            # _spill keeps one entry host-resident; the older two hit NVMe
            assert store.report()["nvme_entries"] == 2
            singles = []
            orig_one = store.swapper.swap_in_start
            store.swapper.swap_in_start = \
                lambda n: singles.append(n) or orig_one(n)
            blocks, n = pc.acquire(toks)
            assert n == 12
            recs = pc.drain_promotes()
            assert len(recs) == 3
            assert store.counters["batched_reads"] == 1 and not singles
            for r in recs:
                assert r.fetch.t_start > 0     # promote_ms anchor intact
                assert np.array_equal(r.fetch.wait()["k"],
                                      _payload(9)["k"])
                r.fetch.release()
                store.discard(r.key)
            pc.mark_uploaded(recs)
            alloc.free(blocks)
            pc.clear()
            assert not alloc.leaked_blocks() and store.entries() == 0
            assert store.pool.report()["outstanding"] == 0
            store.close()


# ---------------------------------------------------------------------------
# tiered PrefixCache semantics (fake extract: no device in the loop)
# ---------------------------------------------------------------------------

def _tiered_cache(num_blocks=8, block_size=4, **store_kw):
    from deepspeed_tpu.inference.kv_tier import KVTierStore

    alloc = BlockedAllocator(num_blocks, block_size=block_size)
    pc = PrefixCache(alloc)
    store = KVTierStore(**{"host_mb": 1.0, **store_kw})
    payloads = {}

    def extract(blocks):
        return [dict(payloads[b]) for b in blocks]

    pc.attach_tier_store(store, extract)

    def publish(toks, val):
        blks = alloc.allocate(len(toks) // block_size)
        for b in blks:
            payloads[b] = _payload(val)
        pc.insert(toks, blks)
        alloc.free(blks)
        return blks

    publish.payloads = payloads
    return alloc, pc, store, publish


class TestTieredPrefixCache:
    def test_demote_instead_of_evict_keeps_nodes(self):
        alloc, pc, store, publish = _tiered_cache()
        publish(np.arange(8, dtype=np.int32), 1)
        assert pc.evict(2) == 2                  # HBM blocks freed...
        assert alloc.free_blocks == alloc.num_blocks
        rep = pc.report()
        assert rep["blocks"] == 0 and rep["demoted_nodes"] == 2
        assert rep["demoted_blocks"] == 2 and store.entries() == 2
        # ...but the prefix still matches, as warm-not-resident
        info = pc.peek_tiers(np.arange(8, dtype=np.int32))
        assert info["matched_tokens"] == 8
        assert info["resident_tokens"] == 0 and info["demoted_blocks"] == 2

    def test_acquire_promotes_with_pending_upload(self):
        alloc, pc, store, publish = _tiered_cache()
        toks = np.arange(8, dtype=np.int32)
        publish(toks, 7)
        pc.evict(2)
        blocks, n = pc.acquire(toks)
        assert n == 8 and len(blocks) == 2
        recs = pc.drain_promotes()
        assert len(recs) == 2 and pc.report()["promoted_blocks"] == 2
        for r in recs:
            assert np.array_equal(r.fetch.wait()["k"], _payload(7)["k"])
            r.fetch.release()
            store.discard(r.key)
        # promoted blocks are live (cache + acquirer refs) and pinned
        assert all(alloc.refcount(b) == 2 for b in blocks)
        assert pc.evictable_blocks() == 0
        alloc.free(blocks)
        assert pc.evictable_blocks() == 2
        pc.clear()
        assert alloc.free_blocks == alloc.num_blocks
        assert not alloc.leaked_blocks() and store.entries() == 0

    def test_cancel_promotes_redemotes_and_frees(self):
        alloc, pc, store, publish = _tiered_cache()
        toks = np.arange(8, dtype=np.int32)
        publish(toks, 5)
        pc.evict(2)
        blocks, n = pc.acquire(toks)
        recs = pc.drain_promotes()
        # the acquirer fails before the upload fence: free its refs, then
        # cancel — nodes re-demote onto their still-live store entries
        alloc.free(blocks)
        pc.cancel_promotes(recs)
        assert alloc.free_blocks == alloc.num_blocks
        rep = pc.report()
        assert rep["blocks"] == 0 and rep["demoted_nodes"] == 2
        assert store.entries() == 2
        # and the prefix is still servable afterwards
        blocks2, n2 = pc.acquire(toks)
        assert n2 == 8
        for r in pc.drain_promotes():
            assert np.array_equal(r.fetch.wait()["k"], _payload(5)["k"])
            r.fetch.release()
            store.discard(r.key)
        alloc.free(blocks2)
        pc.clear()
        assert not alloc.leaked_blocks() and store.entries() == 0

    def test_republish_readopts_demoted_nodes(self):
        alloc, pc, store, publish = _tiered_cache()
        toks = np.arange(8, dtype=np.int32)
        publish(toks, 2)
        pc.evict(2)
        assert store.entries() == 2
        # a second sequence publishes identical content: nodes re-adopt its
        # private blocks — no tier fetch, store entries released
        publish(toks, 2)
        rep = pc.report()
        assert rep["readopted_blocks"] == 2 and rep["demoted_nodes"] == 0
        assert store.entries() == 0
        info = pc.peek_tiers(toks)
        assert info["resident_tokens"] == 8

    def test_dropped_tier_entry_detaches_subtree(self):
        # no NVMe + tiny host budget: demotions past the budget drop the
        # oldest entries, and the radix tree must forget those nodes
        alloc, pc, store, publish = _tiered_cache(
            num_blocks=16, host_mb=150 / 2**20)
        for i in range(4):
            publish(np.arange(i * 100, i * 100 + 8, dtype=np.int32), i)
            pc.evict(2)
        assert store.counters["dropped"] >= 1
        assert pc.report()["tier_lost_blocks"] >= 1
        # every remaining match still resolves cleanly (dead prefixes miss)
        total = 0
        for i in range(4):
            toks = np.arange(i * 100, i * 100 + 8, dtype=np.int32)
            blocks, n = pc.acquire(toks)
            for r in pc.drain_promotes():
                r.fetch.wait()
                r.fetch.release()
                store.discard(r.key)
            total += n
            alloc.free(blocks)
        assert 0 < total < 4 * 8
        pc.clear()
        assert alloc.free_blocks == alloc.num_blocks
        assert store.pool.report()["outstanding"] == 0

    def test_deep_chain_demotes_leaf_first_bottom_up(self):
        # demoted children must not pin their parents: a fully-unreferenced
        # chain demotes bottom-up until the whole path is in the store
        alloc, pc, store, publish = _tiered_cache(num_blocks=8)
        toks = np.arange(16, dtype=np.int32)          # 4-block chain
        publish(toks, 9)
        assert pc.evict(4) == 4
        rep = pc.report()
        assert rep["blocks"] == 0 and rep["demoted_nodes"] == 4
        info = pc.peek_tiers(toks)
        assert info["matched_tokens"] == 16 and info["demoted_blocks"] == 4

    def test_pending_upload_blocks_resist_eviction_until_fence(self):
        # an acquirer shed between attach and the engine's fence leaves the
        # cache sole owner of promoted blocks whose payload was NEVER
        # uploaded: demoting one would extract garbage, freeing one would
        # let the deferred scatter overwrite whoever gets the block next
        alloc, pc, store, publish = _tiered_cache()
        toks = np.arange(8, dtype=np.int32)
        publish(toks, 3)
        pc.evict(2)
        blocks, n = pc.acquire(toks)
        recs = pc.drain_promotes()
        alloc.free(blocks)                 # acquirer gone, rc back to 1
        assert pc.evict(2) == 0            # fence pending: untouchable
        assert pc.report()["blocks"] == 2
        for r in recs:                     # the fence: upload + finalize
            assert np.array_equal(r.fetch.wait()["k"], _payload(3)["k"])
            r.fetch.release()
            store.discard(r.key)
            publish.payloads[r.block] = _payload(3)
        pc.mark_uploaded(recs)
        assert pc.evict(2) == 2            # ordinary cache blocks again
        pc.clear()
        assert alloc.free_blocks == alloc.num_blocks
        assert not alloc.leaked_blocks() and store.entries() == 0

    def test_deep_chain_eviction_has_no_recursion_limit(self):
        # candidate gathering must be iterative: one shared system prompt
        # can be a chain far deeper than the interpreter's recursion limit
        alloc, pc, store, publish = _tiered_cache(num_blocks=1300,
                                                  block_size=4,
                                                  host_mb=4.0)
        toks = np.arange(4800, dtype=np.int32)        # 1200-block chain
        publish(toks, 1)
        assert pc.evict(1200) == 1200
        rep = pc.report()
        assert rep["blocks"] == 0 and rep["demoted_nodes"] == 1200
        pc.clear()
        assert store.entries() == 0 and not alloc.leaked_blocks()

    def test_demote_failure_drops_orphaned_demoted_descendants(self):
        # when the store cannot take a victim (copy failure) the fallback
        # is plain eviction — but the victim can carry DEMOTED children,
        # and unlinking just the victim would orphan them: unreachable
        # nodes whose tier entries leak until clear()
        alloc, pc, store, publish = _tiered_cache()
        toks = np.arange(12, dtype=np.int32)          # 3-block chain
        publish(toks, 4)
        assert pc.evict(1) == 1                       # leaf -> demoted
        assert store.entries() == 1

        def broken_put(key, parts):
            raise RuntimeError("pinned copy failed")

        store.put = broken_put
        assert pc.evict(1) == 1                       # plain-evict fallback
        rep = pc.report()
        assert store.entries() == 0                   # child went with it
        assert rep["demoted_nodes"] == 0 and rep["blocks"] == 1
        blocks, n = pc.acquire(toks)
        assert n == 4                                 # only the head serves
        assert not pc.drain_promotes()
        alloc.free(blocks)
        pc.clear()
        assert not alloc.leaked_blocks()


# ---------------------------------------------------------------------------
# tiered KV through the engine (fp32: promote must be bit-exact)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tier_eng(f32_lm, tmp_path_factory):
    model, params = f32_lm
    nvme = tmp_path_factory.mktemp("kv_tier_nvme")
    # host budget ~2 blocks (tiny block = 2*16*64*4*2 bytes) so a few
    # demotions reach NVMe too
    eng = _engine(model, params, num_blocks=24,
                  prefix_cache={"enabled": True,
                                "tiers": {"enabled": True,
                                          "host_mb": 2 * 16384 / 2**20,
                                          "nvme_path": str(nvme),
                                          "promote_depth": 2}})
    yield eng
    eng.close()


def _gen(eng, uid, prompt, steps=6):
    r = eng.put([uid], [prompt])
    out = [int(np.argmax(r[uid]))]
    toks = eng.decode_batch([uid], [out[0]], steps=steps)
    out += [int(t) for t in toks[uid]]
    eng.flush([uid])
    return out


def test_tiered_demote_promote_token_identical(tier_eng, plain_eng):
    """The correctness bar: the SAME prompt served (a) cold on a plain
    engine, (b) publishing, (c) after full demotion to host+NVMe via
    promote — all three token streams identical, pool and store restored."""
    eng = _reset(tier_eng)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 250, 52)
    base = _gen(_reset(plain_eng), 0, prompt)
    first = _gen(eng, 1, prompt)
    assert first == base
    pc = eng.prefix_cache
    assert pc.report()["blocks"] == 3
    pc.evict(10)                       # demote everything (host + NVMe)
    rep = pc.report()
    assert rep["demoted_nodes"] == 3 and rep["blocks"] == 0
    tiers = rep["tiers"]
    assert tiers["host_entries"] + tiers["nvme_entries"] == 3
    promoted = _gen(eng, 2, prompt)
    assert promoted == base
    rep = pc.report()
    assert rep["promoted_blocks"] == 3
    assert rep["tiers"]["host_hits"] + rep["tiers"]["nvme_hits"] == 3
    pc.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()
    assert eng._tier_store.entries() == 0
    assert eng._tier_store.pool.report()["outstanding"] == 0


def test_tier_metrics_render_in_prometheus(tier_eng):
    """Acceptance: inference/prefix_cache_tier_{hits,promote_ms} appear in
    the Prometheus exposition with per-tier labels."""
    from deepspeed_tpu.observability import get_registry

    eng = _reset(tier_eng)
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 250, 52)
    _gen(eng, 10, prompt)
    eng.prefix_cache.evict(10)
    _gen(eng, 11, prompt)              # promote -> hits + promote_ms
    text = get_registry().render_prometheus()
    assert 'inference_prefix_cache_tier_hits_total{tier="host"}' in text \
        or 'inference_prefix_cache_tier_hits_total{tier="nvme"}' in text
    assert 'inference_prefix_cache_tier_demotions_total{tier="host"}' \
        in text
    assert 'inference_prefix_cache_tier_promote_ms_count{tier=' in text
    assert 'inference_prefix_cache_tier_bytes{tier="host"}' in text
    eng.prefix_cache.clear()


def test_tiers_config_reaches_engine(f32_lm, tmp_path):
    from deepspeed_tpu.config.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(**{
        "inference": {"prefix_cache": {
            "enabled": True,
            "tiers": {"enabled": True, "host_mb": 0.5,
                      "nvme_path": str(tmp_path), "promote_depth": 3}}}})
    t = cfg.inference.prefix_cache.tiers
    assert t.enabled and t.host_mb == 0.5 and t.promote_depth == 3
    model, params = f32_lm
    eng = _engine(model, params, prefix_cache=cfg.inference.prefix_cache)
    try:
        assert eng._tier_store is not None
        assert eng._tier_store.host_bytes == int(0.5 * 2**20)
        assert eng._tier_store.promote_depth == 3
        assert eng._tier_store.swapper is not None
        assert eng.prefix_cache.tier_store is eng._tier_store
    finally:
        eng.close()
    assert eng._tier_store is None     # close() is the teardown seam


def test_tiers_config_validation():
    from deepspeed_tpu.config.config import KVTierConfig

    with pytest.raises(ValueError):
        KVTierConfig(host_mb=0)
    with pytest.raises(ValueError):
        KVTierConfig(promote_depth=0)


def test_batcher_projection_counts_demoted_as_block_demand(tier_eng):
    """Admission math: resident cached blocks are free capacity; demoted
    blocks stay in the block projection (a promote allocates a block) but
    the request is still a prefix hit — the promote-latency tax, not cold
    prefill demand."""
    from deepspeed_tpu.serving import ContinuousBatcher

    eng = _reset(tier_eng)
    b = ContinuousBatcher(eng)
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 250, 52)
    _gen(eng, 20, prompt)
    req = type("R", (), {})()
    req.prompt = prompt
    req.prompt_len = len(prompt)
    req.total_token_demand = len(prompt) + 6
    resident_need = b._blocks_needed(req)
    assert resident_need < b._blocks_for(req.total_token_demand)
    eng.prefix_cache.evict(10)         # all demoted now
    demoted_need = b._blocks_needed(req)
    # demoted blocks cost pool blocks again (promotes allocate), so the
    # projected need returns to the full worst case
    assert demoted_need == b._blocks_for(req.total_token_demand)
    eng.prefix_cache.clear()


def test_promote_read_failure_zero_fills_and_restores_loans(tier_eng):
    """A promote fetch failing with a NON-IO error at the fence (the lazy
    NVMe path submits inside wait(): pool.get can raise under host-memory
    pressure) must zero-fill that block and still finalize every other
    record — an escape would strand the whole batch's loans and leave
    garbage blocks attached to live sequences."""
    eng = _reset(tier_eng)
    rng = np.random.default_rng(6)
    prompt = rng.integers(0, 250, 52)
    _gen(eng, 30, prompt)
    eng.prefix_cache.evict(10)
    hit = eng.prefix_attach(31, prompt)
    assert hit > 0 and eng._promote_q

    bad = eng._promote_q[0]

    class _BoomFetch:                      # KVFetch is slotted: wrap it
        def __init__(self, inner):
            self.inner = inner
            self.tier = inner.tier
            self.t_start = inner.t_start

        def wait(self):
            raise RuntimeError("pinned pool exhausted")

        def release(self):
            self.inner.release()

    bad.fetch = _BoomFetch(bad.fetch)
    misses = lambda: (eng._tier_store.counters["host_misses"]
                      + eng._tier_store.counters["nvme_misses"])
    m0 = misses()
    eng._flush_promotes()                  # must not raise
    assert not eng._promote_q
    assert misses() == m0 + 1
    assert not eng.prefix_cache._pending_upload
    # the zero-filled node (and, being the chain head, everything under
    # it) must leave the tree: published, every FUTURE match would read
    # zeros as KV — only the in-flight acquirer computes on zeros
    pc = eng.prefix_cache
    assert pc.counters["tier_lost_blocks"] >= 1
    assert pc.peek_tiers(prompt, max_tokens=len(prompt) - 1)[
        "matched_tokens"] == 0
    eng.flush([31])
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()
    assert eng._tier_store.entries() == 0
    assert eng._tier_store.pool.report()["outstanding"] == 0
    assert eng._tier_store.swapper is None \
        or eng._tier_store.swapper.report()["loaned_read_buffers"] == 0


def test_clear_between_attach_and_fence_discards_stale_promotes(tier_eng):
    """An ops cache flush (clear()) landing between prefix_attach and the
    engine's next dispatch releases the promoted blocks back to the pool —
    the fence must RELEASE the stale records, never scatter their payloads
    over blocks that may belong to another sequence by then."""
    eng = _reset(tier_eng)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, 250, 52)
    ref = _gen(eng, 40, prompt)
    eng.prefix_cache.evict(10)
    hit = eng.prefix_attach(41, prompt)
    assert hit > 0 and eng._promote_q
    eng.prefix_cache.clear()
    eng.flush([41])                        # blocks fully free for reuse

    orig = eng._promote_step

    def must_not_scatter(*a, **kw):
        raise AssertionError("fence scattered a stale promote")

    eng._promote_step = must_not_scatter
    try:
        eng._flush_promotes()
    finally:
        eng._promote_step = orig
    assert not eng._promote_q
    assert eng._tier_store.pool.report()["outstanding"] == 0
    # and the engine serves cleanly on the recycled blocks
    assert _gen(eng, 42, prompt) == ref
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()


def test_close_with_pending_promotes_drops_garbage_nodes(tier_eng):
    """close() before the fence: the queued promotions' blocks were never
    uploaded, and the prefix cache stays usable after a tier-only close —
    the garbage nodes must leave the tree, not get published. (Runs LAST
    among the tier_eng tests: it closes the shared engine.)"""
    eng = _reset(tier_eng)
    rng = np.random.default_rng(8)
    prompt = rng.integers(0, 250, 52)
    _gen(eng, 50, prompt)
    eng.prefix_cache.evict(10)
    hit = eng.prefix_attach(51, prompt)
    assert hit > 0 and eng._promote_q
    pc = eng.prefix_cache
    eng.flush([51])
    eng.close()
    assert pc.peek_tiers(prompt, max_tokens=len(prompt) - 1)[
        "matched_tokens"] == 0
    assert not pc._pending_upload
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks
    assert not alloc.leaked_blocks()


# ---------------------------------------------------------------------------
# drill wrappers (slow; the CLI is the invariant authority)
# ---------------------------------------------------------------------------

@pytest.mark.perf
@pytest.mark.slow
def test_prefix_storm_drill(tmp_path):
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    verdict = run_scenario("prefix-storm", workdir=str(tmp_path))
    assert verdict["ok"], verdict


@pytest.mark.perf
@pytest.mark.slow
def test_kv_tier_drill(tmp_path):
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    verdict = run_scenario("kv-tier", workdir=str(tmp_path))
    assert verdict["ok"], verdict
