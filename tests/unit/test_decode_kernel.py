"""Fused Pallas paged-decode kernel: parity, fallback, and fused-fence
tests (``inference.decode_kernel``).

The contract under test is EXACTNESS plus dispatch accounting: greedy
decode tokens must be bit-identical between ``decode_kernel='pallas'``
(the fused work-list flash-decode kernel, interpret mode on this CPU
suite) and ``decode_kernel='xla'`` (the dense-gather reference twin) in
fp32 — across ragged lengths, block-boundary prompts, an int8 KV pool,
and speculative verify rounds — and a backend with no Pallas lowering
must fall back to the xla path with ONE logged warning and no behavior
change. fp32 for the same reason as ``test_prefix_cache.py``: a
random-init model's near-tied bf16 logits flip argmax between
numerically-equivalent kernels, which is a test-model artifact.

The fused promote-fence prologue rides along: with the pallas kernel
active, pending tier promotions land inside the next step's dispatch
instead of a standalone donated scatter, counted in ``tier_report()``.
``tools/decode_kernel_drill.py`` is the invariant authority for the
hardware claims; its slow wrappers are at the bottom under the
``pallas`` marker.
"""

import logging
import os

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2
from deepspeed_tpu.models import TransformerLM, get_preset

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


@pytest.fixture(scope="module")
def f32_lm():
    model = TransformerLM(get_preset("tiny", dtype="float32"))
    params = model.init(jax.random.key(0))
    return model, params


def _engine(model, params, **kw):
    base = dict(max_sequences=8, max_seq_len=64, block_size=8)
    base.update(kw)
    return InferenceEngineV2(model, params=params, **base)


def _pair(f32_lm, **kw):
    model, params = f32_lm
    return {kern: _engine(model, params, decode_kernel=kern, **kw)
            for kern in ("pallas", "xla")}


# ---------------------------------------------------------------------------
# selector plumbing: config field, ctor validation, backend probe
# ---------------------------------------------------------------------------

class TestKernelSelection:
    def test_inference_config_field(self):
        from deepspeed_tpu.config.config import InferenceConfig

        assert InferenceConfig().decode_kernel == "pallas"
        assert InferenceConfig(decode_kernel="xla").decode_kernel == "xla"
        with pytest.raises(ValueError, match="decode_kernel"):
            InferenceConfig(decode_kernel="cuda")

    def test_engine_rejects_unknown_kernel(self, f32_lm):
        model, params = f32_lm
        with pytest.raises(ValueError, match="decode_kernel"):
            _engine(model, params, decode_kernel="triton")

    def test_support_probe_on_cpu(self):
        from deepspeed_tpu.ops.paged_attention import decode_kernel_support

        mode, reason = decode_kernel_support()
        assert mode == "interpret" and "CPU" in reason

    def test_ops_reject_unknown_kernel(self):
        from deepspeed_tpu.ops.paged_attention import _check_kernel

        assert _check_kernel("xla") is True
        assert _check_kernel("pallas") is False
        with pytest.raises(ValueError, match="kernel"):
            _check_kernel("cuda")

    def test_engine_resolves_interpret_mode(self, f32_lm):
        model, params = f32_lm
        eng = _engine(model, params, decode_kernel="pallas")
        assert eng.decode_kernel == "pallas"
        assert eng.decode_kernel_mode == "interpret"
        assert eng.spec_stats["fused"] == 1
        eng2 = _engine(model, params, decode_kernel="xla")
        assert eng2.decode_kernel == "xla"
        assert eng2.decode_kernel_mode == "xla"
        assert eng2.spec_stats["fused"] == 0


# ---------------------------------------------------------------------------
# fp32 greedy-token parity: pallas (interpret) vs the xla reference twin
# ---------------------------------------------------------------------------

class TestGreedyParity:
    def test_ragged_and_block_boundary_prompts(self, f32_lm):
        """Ragged prompt lengths including exact block multiples (8, 16 at
        block_size=8): identical greedy tokens through prefill + the fused
        decode scan."""
        engines = _pair(f32_lm)
        rng = np.random.default_rng(3)
        lens = [3, 8, 11, 16, 21]
        prompts = [rng.integers(1, 256, n).astype(np.int32) for n in lens]
        toks = {}
        for kern, eng in engines.items():
            uids = list(range(len(prompts)))
            first = eng.put(uids, prompts)
            starts = [int(np.argmax(first[u])) for u in uids]
            out = eng.decode_batch(uids, starts, steps=6)
            toks[kern] = np.stack([out[u] for u in uids])
            eng.flush(uids)
        np.testing.assert_array_equal(toks["pallas"], toks["xla"])

    def test_single_token_put_steps(self, f32_lm):
        """The 1-token-atom packed put path (latency serving mode) stays
        identical too — it reads the pool through the same kernel."""
        engines = _pair(f32_lm)
        rng = np.random.default_rng(4)
        prompt = rng.integers(1, 256, 11).astype(np.int32)
        logits = {}
        for kern, eng in engines.items():
            r = eng.put([0], [prompt])
            cur = int(np.argmax(r[0]))
            seq = []
            for _ in range(5):
                r = eng.put([0], [np.array([cur], np.int32)])
                cur = int(np.argmax(r[0]))
                seq.append(cur)
            logits[kern] = seq
            eng.flush([0])
        assert logits["pallas"] == logits["xla"]

    def test_int8_kv_pool(self, f32_lm):
        engines = _pair(f32_lm, kv_dtype="int8")
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, 256, 11).astype(np.int32),
                   rng.integers(1, 256, 21).astype(np.int32)]
        toks = {}
        for kern, eng in engines.items():
            first = eng.put([0, 1], prompts)
            starts = [int(np.argmax(first[0])), int(np.argmax(first[1]))]
            out = eng.decode_batch([0, 1], starts, steps=6)
            toks[kern] = np.stack([out[0], out[1]])
            eng.flush([0, 1])
        np.testing.assert_array_equal(toks["pallas"], toks["xla"])

    def test_spec_verify_wide_shape(self, f32_lm):
        """Speculative verify (logits gathered at every draft position —
        the wide-decode shape) through the shared packed step: identical
        emitted tokens, and the verify rounds really ran."""
        spec = {"enabled": True, "ngram": 2, "max_draft": 3,
                "fallback_steps": 2}
        engines = _pair(f32_lm, speculative=spec)
        rng = np.random.default_rng(6)
        rep = np.tile(rng.integers(1, 256, 3), 7).astype(np.int32)
        toks = {}
        for kern, eng in engines.items():
            first = eng.put([0], [rep])
            out = eng.decode_batch([0], [int(np.argmax(first[0]))],
                                   steps=8, speculative=True)
            toks[kern] = [int(t) for t in out[0]]
            assert eng.spec_stats["rounds"] > 0
            eng.flush([0])
        assert toks["pallas"] == toks["xla"]


# ---------------------------------------------------------------------------
# fused promote-fence prologue (tiers demote -> promote -> decode)
# ---------------------------------------------------------------------------

class TestFusedPromoteFence:
    TIERS = {"enabled": True,
             "tiers": {"enabled": True, "host_mb": 8.0}}

    def _roundtrip(self, eng, seed=7):
        """Publish a 3-block shared prefix, demote it, re-attach it on a
        fresh uid (promotions pending), then decode — returns the greedy
        tokens that crossed the promote fence."""
        rng = np.random.default_rng(seed)
        shared = rng.integers(1, 256, 24).astype(np.int32)
        sfx = rng.integers(1, 256, 4).astype(np.int32)
        eng.put([0], [np.concatenate([shared, sfx])])
        eng.flush([0])
        pc = eng.prefix_cache
        pc.evict(pc.evictable_blocks())
        first = eng.put([1], [np.concatenate([shared, sfx])])
        out = eng.decode_batch([1], [int(np.argmax(first[1]))], steps=6)
        eng.flush([1])
        return [int(t) for t in out[1]]

    def test_demote_promote_identical_and_dispatches_saved(self, f32_lm):
        model, params = f32_lm
        toks, reports = {}, {}
        for kern in ("pallas", "xla"):
            eng = _engine(model, params, max_sequences=4, max_seq_len=96,
                          decode_kernel=kern, prefix_cache=self.TIERS)
            toks[kern] = self._roundtrip(eng)
            reports[kern] = eng.tier_report()
            eng.close()
        assert toks["pallas"] == toks["xla"]
        # pallas: the promotions rode a step prologue (>= 1 standalone
        # scatter dispatch saved); xla: the standalone fence ran as before
        assert reports["pallas"]["fused_prologue_dispatches_saved"] >= 1
        assert reports["xla"]["fused_prologue_dispatches_saved"] == 0

    def test_fence_leaves_no_pending_state(self, f32_lm):
        model, params = f32_lm
        eng = _engine(model, params, max_sequences=4, max_seq_len=96,
                      decode_kernel="pallas", prefix_cache=self.TIERS)
        self._roundtrip(eng)
        rep = eng.tier_report()
        assert rep["pending_promotes"] == 0
        assert rep["pending_resumes"] == 0
        alloc = eng.state.allocator
        eng.prefix_cache.clear()
        assert alloc.free_blocks == alloc.num_blocks  # no leaked refs
        eng.close()

    def test_pause_resume_through_fused_prologue(self, f32_lm):
        """A PAUSED request resumed while prefix promotions are pending:
        the resume upload flushes standalone (unwind semantics) and the
        prefix promotions still fuse — tokens identical to the xla path."""
        model, params = f32_lm
        toks = {}
        for kern in ("pallas", "xla"):
            eng = _engine(model, params, max_sequences=4, max_seq_len=96,
                          decode_kernel=kern, prefix_cache=self.TIERS)
            rng = np.random.default_rng(11)
            prompt = rng.integers(1, 256, 19).astype(np.int32)
            r = eng.put([5], [prompt])
            cur = int(np.argmax(r[5]))
            assert eng.pause_request(5)
            assert eng.resume_request(5)
            assert eng.flush_resumes() == []
            out = eng.decode_batch([5], [cur], steps=6)
            toks[kern] = [int(t) for t in out[5]]
            eng.flush([5])
            eng.close()
        assert toks["pallas"] == toks["xla"]


# ---------------------------------------------------------------------------
# fallback: Pallas unavailable -> xla path, one warning, same behavior
# ---------------------------------------------------------------------------

class TestFallback:
    def test_unavailable_backend_falls_back_with_one_warning(
            self, f32_lm, monkeypatch):
        from deepspeed_tpu.ops import paged_attention as pa
        from deepspeed_tpu.utils.logging import logger

        monkeypatch.setattr(
            pa, "decode_kernel_support",
            lambda: (None, "backend 'rocm' has no Pallas TPU lowering"))
        records = []

        class _Cap(logging.Handler):
            def emit(self, r):
                records.append(r)

        cap = _Cap(level=logging.WARNING)
        logger.addHandler(cap)
        try:
            model, params = f32_lm
            eng = _engine(model, params, decode_kernel="pallas")
        finally:
            logger.removeHandler(cap)
        assert eng.decode_kernel == "xla"
        assert eng.decode_kernel_mode == "xla"
        assert "rocm" in eng.decode_kernel_reason
        assert eng.spec_stats["fused"] == 0
        warns = [r for r in records
                 if "decode_kernel" in r.getMessage()]
        assert len(warns) == 1 and warns[0].levelno == logging.WARNING

        # no behavior change: tokens identical to an explicit-xla engine
        rng = np.random.default_rng(8)
        prompt = rng.integers(1, 256, 13).astype(np.int32)
        xeng = _engine(model, params, decode_kernel="xla")
        toks = {}
        for name, e in (("fallback", eng), ("explicit", xeng)):
            first = e.put([0], [prompt])
            out = e.decode_batch([0], [int(np.argmax(first[0]))], steps=6)
            toks[name] = [int(t) for t in out[0]]
            e.flush([0])
        assert toks["fallback"] == toks["explicit"]

    def test_explicit_xla_engine_logs_no_warning(self, f32_lm):
        from deepspeed_tpu.utils.logging import logger

        records = []

        class _Cap(logging.Handler):
            def emit(self, r):
                records.append(r)

        cap = _Cap(level=logging.WARNING)
        logger.addHandler(cap)
        try:
            model, params = f32_lm
            _engine(model, params, decode_kernel="xla")
        finally:
            logger.removeHandler(cap)
        assert not [r for r in records
                    if "decode_kernel" in r.getMessage()]


# ---------------------------------------------------------------------------
# drill wrappers (slow; tools/decode_kernel_drill.py is the authority)
# ---------------------------------------------------------------------------

@pytest.mark.pallas
@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["parity", "fused-fence", "throughput"])
def test_decode_kernel_drill(scenario):
    import sys

    sys.path.insert(0, _TOOLS)
    from decode_kernel_drill import run_scenario

    verdict = run_scenario(scenario)
    assert verdict["ok"], verdict
