"""Sequence-parallel tests: Ulysses and ring attention inside shard_map on the
virtual 8-device mesh must match single-device full attention (pattern: the
reference's Ulysses tests exercise ``DistributedAttention`` over real process
groups)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.ops.ring_attention import ring_attention
from deepspeed_tpu.sequence import DistributedAttention, ulysses_attention
from deepspeed_tpu.sequence.tiling import sequence_tiled_compute, tiled_logits_loss


@pytest.fixture(scope="module")
def sp_mesh(eight_devices):
    return Mesh(np.array(eight_devices[:4]), ("sp",))


def _qkv(T=64, H=4, K=4, d=16):
    q = jax.random.normal(jax.random.key(1), (2, T, H, d))
    k = jax.random.normal(jax.random.key(2), (2, T, K, d))
    v = jax.random.normal(jax.random.key(3), (2, T, K, d))
    return q, k, v


def _run_sp(mesh, fn, q, k, v):
    spec = P(None, "sp", None, None)
    sharded = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec)
    return sharded(q, k, v)


def test_ulysses_matches_full(sp_mesh):
    q, k, v = _qkv()
    out = _run_sp(sp_mesh, lambda q, k, v: ulysses_attention(q, k, v, axis="sp"),
                  q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_grad(sp_mesh):
    q, k, v = _qkv(T=32)

    def loss_sp(q, k, v):
        return _run_sp(sp_mesh,
                       lambda q, k, v: ulysses_attention(q, k, v, axis="sp"),
                       q, k, v).sum()

    g1 = jax.grad(loss_sp)(q, k, v)
    g2 = jax.grad(lambda q: xla_attention(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-4)


def test_distributed_attention_wrapper(sp_mesh):
    q, k, v = _qkv()
    da = DistributedAttention(sequence_process_group="sp")
    out = _run_sp(sp_mesh, lambda q, k, v: da(q, k, v), q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_matches_full(sp_mesh):
    q, k, v = _qkv()
    out = _run_sp(sp_mesh, lambda q, k, v: ring_attention(q, k, v, axis="sp"),
                  q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gqa(sp_mesh):
    q, k, v = _qkv(H=8, K=2)
    out = _run_sp(sp_mesh, lambda q, k, v: ring_attention(q, k, v, axis="sp"),
                  q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_grad(sp_mesh):
    q, k, v = _qkv(T=32)

    def loss_sp(q):
        return _run_sp(sp_mesh,
                       lambda q, k, v: ring_attention(q, k, v, axis="sp"),
                       q, k, v).sum()

    g1 = jax.grad(loss_sp)(q)
    g2 = jax.grad(lambda q: xla_attention(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-4)


# ---------------------------------------------------------------------------
# Engine-reachable SP: attention_impl="ulysses"/"ring" under ds.initialize
# ---------------------------------------------------------------------------

def _sp_engine_config(mesh, ga=1):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": ga,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": mesh,
        "steps_per_print": 100,
    }


def _train(eng, steps, batch):
    losses = []
    for _ in range(steps):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("impl", ["ulysses", "ring"])
def test_sp_engine_training_converges(impl, eight_devices):
    """sp>1 training through the engine converges; exact math parity with the
    dense path is asserted separately by test_sp_engine_loss_parity."""
    import dataclasses

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    rng = np.random.default_rng(0)
    sp_cfg = dataclasses.replace(get_preset("tiny"), attention_impl=impl)
    spe = ds.initialize(model=TransformerLM(sp_cfg),
                        config=_sp_engine_config({"dp": 4, "sp": 2}))[0]
    batch_sp = {"input_ids": rng.integers(0, 256, (8, 32))}
    got = _train(spe, 3, batch_sp)
    assert got[-1] < got[0]


@pytest.mark.parametrize("impl", ["ulysses", "ring"])
def test_sp_engine_loss_parity(impl, eight_devices):
    """Same params + same batch: the sp>1 engine loss equals the dense loss."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset
    import dataclasses

    preset = get_preset("tiny")
    rng = np.random.default_rng(1)
    batch = {"input_ids": rng.integers(0, 256, (8, 32))}

    dense = ds.initialize(model=TransformerLM(preset),
                          config=_sp_engine_config({"dp": 8}))[0]
    spe = ds.initialize(model=TransformerLM(
        dataclasses.replace(preset, attention_impl=impl)),
        config=_sp_engine_config({"dp": 4, "sp": 2}))[0]
    # copy params so both engines evaluate the identical function
    spe.params = jax.device_put(
        jax.tree_util.tree_map(np.asarray, dense.params), spe.param_sharding)
    l_dense = float(dense.forward(batch))
    l_sp = float(spe.forward(batch))
    np.testing.assert_allclose(l_sp, l_dense, rtol=2e-3)


def test_sp_long_context_forward(eight_devices):
    """Long-context functional check: 8k tokens through ring attention on the
    8-way sp mesh (BASELINE.md 128k target scaled to the CPU-mesh test budget —
    per-device attention footprint is T/sp x T/sp, not T x T)."""
    import dataclasses

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, TransformerConfig

    T = 8192
    cfg = TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, max_seq_len=T,
                            attention_impl="ring")
    eng = ds.initialize(model=TransformerLM(cfg),
                        config={
                            "train_micro_batch_size_per_gpu": 1,
                            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                            "zero_optimization": {"stage": 0},
                            "mesh": {"sp": 8},
                            "steps_per_print": 100,
                        })[0]
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (1, T))}
    loss = eng.forward(batch)
    assert np.isfinite(float(loss))


def test_ulysses_head_divisibility_error(eight_devices):
    """GQA with kv_heads < sp must fail loudly, pointing at ring."""
    import dataclasses

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    cfg = dataclasses.replace(get_preset("tiny"), num_heads=8, num_kv_heads=2,
                              attention_impl="ulysses")
    eng = ds.initialize(model=TransformerLM(cfg),
                        config=_sp_engine_config({"dp": 2, "sp": 4}))[0]
    batch = {"input_ids": np.zeros((4, 32), np.int32)}
    with pytest.raises(ValueError, match="ring"):
        eng.forward(batch)


def test_sequence_tiled_compute():
    x = jax.random.normal(jax.random.key(0), (2, 32, 16))
    fn = lambda c: jax.nn.gelu(c) * 2.0
    np.testing.assert_allclose(
        np.asarray(sequence_tiled_compute(fn, x, num_shards=4)),
        np.asarray(fn(x)), atol=1e-6)


def test_tiled_logits_loss_matches_dense():
    B, T, D, V = 2, 32, 16, 64
    h = jax.random.normal(jax.random.key(1), (B, T, D))
    head = jax.random.normal(jax.random.key(2), (D, V))
    labels = np.random.default_rng(0).integers(0, V, (B, T))
    labels[0, :5] = -100
    tiled = tiled_logits_loss(h, head, jnp.asarray(labels), num_shards=4)
    logits = (h @ head).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    mask = labels != -100
    gold = np.take_along_axis(np.asarray(logits), np.maximum(labels, 0)[..., None],
                              axis=-1)[..., 0]
    ref = ((np.asarray(logz) - gold) * mask).sum() / mask.sum()
    np.testing.assert_allclose(float(tiled), ref, rtol=1e-5)


def test_loss_tiling_matches_dense():
    """cfg.loss_tiling computes the same loss as the dense [B,T,V] path
    (model-level wiring of tiled_logits_loss), incl. z_loss and masking."""
    import dataclasses

    import jax

    from deepspeed_tpu.models import TransformerLM, get_preset

    cfg = get_preset("tiny", z_loss=1e-4)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, (2, 32)),
             "attention_mask": (rng.random((2, 32)) > 0.1).astype(np.int32)}
    dense = float(model.loss_fn(params, batch))
    tiled_model = TransformerLM(dataclasses.replace(cfg, loss_tiling=4))
    tiled = float(tiled_model.loss_fn(params, batch))
    np.testing.assert_allclose(tiled, dense, rtol=1e-5)
    # explicit labels with -1 padding (a common convention): both paths must
    # mask every negative label identically
    labels = rng.integers(0, 256, (2, 32))
    labels[:, 25:] = -1
    lbatch = {"input_ids": batch["input_ids"], "labels": labels}
    np.testing.assert_allclose(float(tiled_model.loss_fn(params, lbatch)),
                               float(model.loss_fn(params, lbatch)),
                               rtol=1e-5)
    # grads agree too
    g1 = jax.grad(model.loss_fn)(params, batch)
    g2 = jax.grad(tiled_model.loss_fn)(params, batch)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        # bf16 head matmul: chunked vs one-shot accumulation order differs
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


class TestWindowedSP:
    """Sliding-window attention under sequence parallelism (round-2 weak #4:
    windowed models used to silently fall back to dense masked attention
    under sp — exactly the long-context regime where the window matters)."""

    def test_ulysses_window_matches_dense(self, sp_mesh):
        q, k, v = _qkv()
        out = _run_sp(
            sp_mesh,
            lambda q, k, v: ulysses_attention(q, k, v, axis="sp", window=16),
            q, k, v)
        ref = xla_attention(q, k, v, causal=True, window=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_ring_window_matches_dense(self, sp_mesh):
        q, k, v = _qkv()
        out = _run_sp(
            sp_mesh,
            lambda q, k, v: ring_attention(q, k, v, axis="sp", window=16),
            q, k, v)
        ref = xla_attention(q, k, v, causal=True, window=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_attention_block_passes_window_to_sp_impls(self):
        """The dispatch no longer demotes SP impls to the dense-mask path
        for windowed models: ulysses/ring accept the window natively."""
        import inspect

        from deepspeed_tpu.ops.ring_attention import ring_attention_spmd
        from deepspeed_tpu.sequence.layer import ulysses_attention_spmd

        for fn in (ulysses_attention_spmd, ring_attention_spmd):
            assert "window" in inspect.signature(fn).parameters

    @pytest.mark.parametrize("impl", ["ulysses", "ring"])
    def test_windowed_model_sp_loss_parity(self, impl, eight_devices):
        """Mistral-style (windowed) model under sp=4: loss must match the
        single-replica dense run — through the engine, windowed kernel
        engaged."""
        import dataclasses

        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset

        cfg = dataclasses.replace(get_preset("tiny"), sliding_window=8,
                                  attention_impl=impl, max_seq_len=64)
        model = TransformerLM(cfg)
        base = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 100,
        }
        b = {"input_ids": np.random.default_rng(0).integers(0, 256, (2, 64))}
        eng_sp, *_ = ds.initialize(model=model, config={
            **base, "mesh": {"sp": 4, "dp": 2}})
        loss_sp = float(eng_sp.forward(b))
        # reference: same mesh and data, dense masked attention
        cfg_x = dataclasses.replace(cfg, attention_impl="xla")
        eng_1, *_ = ds.initialize(model=TransformerLM(cfg_x), config={
            **base, "mesh": {"sp": 4, "dp": 2}})
        # same init seed → same params; same batch → same loss
        loss_1 = float(eng_1.forward(b))
        assert abs(loss_sp - loss_1) < 3e-2, (loss_sp, loss_1)


class TestFPDT:
    """Host-streamed KV tier (reference fpdt_layer.py:545 Ulysses-Offload):
    chunked online-softmax attention whose past-KV chunks live in pinned
    host memory and stream back per q-block through the jit."""

    @staticmethod
    def _qkv_gqa(T=512, B=2, H=4, K=2, d=32, seed=0):
        r = np.random.default_rng(seed)
        return (jnp.asarray(r.normal(size=(B, T, H, d)).astype(np.float32)),
                jnp.asarray(r.normal(size=(B, T, K, d)).astype(np.float32)),
                jnp.asarray(r.normal(size=(B, T, K, d)).astype(np.float32)))

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("offload", [False, True])
    def test_matches_dense(self, causal, offload):
        from deepspeed_tpu.sequence.fpdt import fpdt_attention

        q, k, v = self._qkv_gqa()
        out = jax.jit(lambda q, k, v: fpdt_attention(
            q, k, v, causal=causal, chunk=128, offload=offload))(q, k, v)
        ref = xla_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grad_matches_dense(self):
        from deepspeed_tpu.sequence.fpdt import fpdt_attention

        q, k, v = self._qkv_gqa()
        gf = jax.jit(jax.grad(lambda q, k, v: jnp.sum(jnp.square(
            fpdt_attention(q, k, v, causal=True, chunk=128, offload=True))),
            argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(jnp.square(
            xla_attention(q, k, v, causal=True))), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)

    def test_engine_trains_with_fpdt(self, eight_devices):
        import dataclasses

        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset

        import deepspeed_tpu.sequence.fpdt as fpdt_mod

        monkey = pytest.MonkeyPatch()
        monkey.setattr(fpdt_mod, "DEFAULT_CHUNK", 64)  # chunked path at test T
        cfg = dataclasses.replace(get_preset("tiny"), attention_impl="fpdt",
                                  max_seq_len=256)
        eng, *_ = ds.initialize(model=TransformerLM(cfg), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
            "steps_per_print": 100})
        b = {"input_ids": np.random.default_rng(0).integers(
            0, 256, (16, 256))}
        losses = []
        try:
            for _ in range(3):
                loss = eng.forward(b)
                eng.backward(loss)
                eng.step()
                losses.append(float(loss))
        finally:
            monkey.undo()
        assert losses[-1] < losses[0]

    def test_device_working_set_flat_in_context(self):
        """The attention working set must follow the CHUNK, not T: growing T
        4x grows fpdt's temp memory far less than the dense path's O(T^2)
        scores (the property the host tier exists for)."""
        from deepspeed_tpu.profiling import profile_fn
        from deepspeed_tpu.sequence.fpdt import fpdt_attention

        def peak(fn, T):
            r = np.random.default_rng(0)
            q = jnp.asarray(r.normal(size=(1, T, 4, 32)).astype(np.float32))
            stats = profile_fn(
                lambda q: jnp.sum(fn(q, q, q)), q)
            return stats.get("peak_bytes", 0.0)

        fp = lambda q, k, v: fpdt_attention(q, k, v, causal=True, chunk=512,
                                            offload=True)
        xl = lambda q, k, v: xla_attention(q, k, v, causal=True)
        p_f1, p_f4 = peak(fp, 2048), peak(fp, 8192)
        p_x4 = peak(xl, 8192)
        if 0.0 in (p_f1, p_f4, p_x4):
            pytest.skip("backend reports no memory analysis")
        assert p_f4 < 0.5 * p_x4, (p_f4, p_x4)     # far below dense scores
        assert p_f4 / p_f1 < 8, (p_f1, p_f4)       # ~linear, not quadratic


class TestFPDTFusedBlock:
    """Fused per-chunk-projection tier (sequence/fpdt.py
    fpdt_block_attention; reference fpdt_layer.py:545 chunks the qkv
    projections too): full-T q/k/v never materialize, forward or backward."""

    @staticmethod
    def _setup(T=256, D=64, H=4, K=2, chunk=64, dtype="float32",
               window=None):
        import dataclasses

        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      TransformerLM)

        cfg = dataclasses.replace(
            TransformerConfig(arch="llama", vocab_size=64, hidden_size=D,
                              num_layers=1, num_heads=H, num_kv_heads=K,
                              max_seq_len=T, dtype=dtype,
                              param_dtype="float32",
                              sliding_window=window),
            attention_impl="fpdt", fpdt_chunk=chunk)
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(0))
        w = jax.tree_util.tree_map(lambda p: p[0], params["layers"])["attn"]
        r = np.random.default_rng(1)
        x = jnp.asarray(r.normal(size=(2, T, D)).astype(np.float32))
        return cfg, model._freqs, w, x

    def test_matches_dense_block(self):
        import dataclasses

        from deepspeed_tpu.models.transformer import attention_block

        cfg, freqs, w, x = self._setup()
        out = jax.jit(lambda x, w: attention_block(
            x, w, cfg, freqs, xla_attention))(x, w)
        cfg_x = dataclasses.replace(cfg, attention_impl="xla")
        ref = attention_block(x, w, cfg_x, freqs, xla_attention)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_grads_match_dense_block(self):
        import dataclasses

        from deepspeed_tpu.models.transformer import attention_block

        cfg, freqs, w, x = self._setup()

        def loss(x, w, c):
            return jnp.sum(jnp.square(attention_block(
                x, w, c, freqs, xla_attention)))

        gx, gw = jax.jit(jax.grad(
            lambda x, w: loss(x, w, cfg), argnums=(0, 1)))(x, w)
        cfg_x = dataclasses.replace(cfg, attention_impl="xla")
        rx, rw = jax.grad(lambda x, w: loss(x, w, cfg_x),
                          argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=2e-3, rtol=2e-3)
        for key in rw:
            np.testing.assert_allclose(np.asarray(gw[key]),
                                       np.asarray(rw[key]),
                                       atol=2e-3, rtol=2e-3, err_msg=key)

    @pytest.mark.parametrize("window", [96, 200, 500])
    def test_windowed_matches_dense_block(self, window):
        """Sliding-window families route through the fused tier too (r4
        verdict missing #6): the static-chunk-distance pair loop must match
        the dense windowed path exactly — fwd and grads."""
        import dataclasses

        from deepspeed_tpu.models.transformer import attention_block
        from deepspeed_tpu.sequence.fpdt import fpdt_block_attention

        cfg, freqs, w, x = self._setup(T=512, window=window)
        out = jax.jit(lambda x, w: attention_block(
            x, w, cfg, freqs, xla_attention))(x, w)
        # prove the fused tier actually ran (not the dense fallback)
        assert fpdt_block_attention(x, w, cfg, freqs) is not None
        cfg_x = dataclasses.replace(cfg, attention_impl="xla")
        ref = attention_block(x, w, cfg_x, freqs, xla_attention)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

        def loss(x, w, c):
            return jnp.sum(jnp.square(attention_block(
                x, w, c, freqs, xla_attention)))

        gx, gw = jax.jit(jax.grad(
            lambda x, w: loss(x, w, cfg), argnums=(0, 1)))(x, w)
        rx, rw = jax.grad(lambda x, w: loss(x, w, cfg_x),
                          argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=2e-3, rtol=2e-3)
        for key in rw:
            np.testing.assert_allclose(np.asarray(gw[key]),
                                       np.asarray(rw[key]),
                                       atol=2e-3, rtol=2e-3, err_msg=key)

    @pytest.mark.parametrize("window", [None, 200])
    def test_sp_ring_matches_dense(self, window, eight_devices):
        """Fused tier x sequence parallelism: the ppermute ring over
        residual blocks (KV recomputed per visit) must match the dense
        block on an sp mesh — fwd and grads (r4 verdict missing #6:
        'compose with sp in a mesh test')."""
        import dataclasses

        from jax.sharding import NamedSharding, PartitionSpec as P

        from deepspeed_tpu.models.transformer import attention_block

        cfg, freqs, w, x = self._setup(T=512, window=window)
        mesh = jax.make_mesh((4,), ("sp",))
        cfg_x = dataclasses.replace(cfg, attention_impl="xla")
        ref = attention_block(x, w, cfg_x, freqs, xla_attention)

        with jax.sharding.set_mesh(mesh):
            xs = jax.device_put(x, NamedSharding(mesh, P(None, "sp", None)))
            out = jax.jit(lambda x, w: attention_block(
                x, w, cfg, freqs, xla_attention))(xs, w)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=3e-4, rtol=3e-4)

            def loss(x, w, c):
                return jnp.sum(jnp.square(attention_block(
                    x, w, c, freqs, xla_attention)))

            gx, gw = jax.jit(jax.grad(
                lambda x, w: loss(x, w, cfg), argnums=(0, 1)))(xs, w)
        rx, rw = jax.grad(lambda x, w: loss(x, w, cfg_x),
                          argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=3e-3, rtol=3e-3)
        for key in rw:
            np.testing.assert_allclose(np.asarray(gw[key]),
                                       np.asarray(rw[key]),
                                       atol=3e-3, rtol=3e-3, err_msg=key)

    def test_no_full_t_qkv_resident(self):
        """Training-step (fwd+bwd) peak of the fused path must undercut the
        seam path (which materializes full-T q/k/v + their cotangents at the
        projection boundary) and grow ~linearly in T."""
        from deepspeed_tpu.models.transformer import (apply_rope,
                                                      attention_block,
                                                      attn_out_proj, qkv_proj)
        from deepspeed_tpu.profiling import profile_fn
        from deepspeed_tpu.sequence.fpdt import fpdt_attention

        def peak(fn, T):
            cfg, freqs, w, x = self._setup(T=T, D=256, H=4, K=2, chunk=256)
            stats = profile_fn(lambda x, w: jax.grad(
                lambda x: jnp.sum(jnp.square(fn(x, w, cfg, freqs))))(x), x, w)
            return stats.get("peak_bytes", 0.0)

        def fused(x, w, cfg, freqs):
            return attention_block(x, w, cfg, freqs, xla_attention)

        def seam(x, w, cfg, freqs):  # the pre-r4 path: full-T projections
            q, k, v = qkv_proj(x, w, cfg)
            q, k = apply_rope(q, freqs), apply_rope(k, freqs)
            out = fpdt_attention(q, k, v, causal=True,
                                 chunk=cfg.fpdt_chunk, offload=False)
            return attn_out_proj(out, w, cfg)

        p_f1, p_f4 = peak(fused, 2048), peak(fused, 8192)
        p_s4 = peak(seam, 8192)
        if 0.0 in (p_f1, p_f4, p_s4):
            pytest.skip("backend reports no memory analysis")
        assert p_f4 < 0.75 * p_s4, (p_f4, p_s4)
        assert p_f4 / p_f1 < 6, (p_f1, p_f4)
