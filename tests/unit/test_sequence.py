"""Sequence-parallel tests: Ulysses and ring attention inside shard_map on the
virtual 8-device mesh must match single-device full attention (pattern: the
reference's Ulysses tests exercise ``DistributedAttention`` over real process
groups)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.ops.ring_attention import ring_attention
from deepspeed_tpu.sequence import DistributedAttention, ulysses_attention
from deepspeed_tpu.sequence.tiling import sequence_tiled_compute, tiled_logits_loss


@pytest.fixture(scope="module")
def sp_mesh(eight_devices):
    return Mesh(np.array(eight_devices[:4]), ("sp",))


def _qkv(T=64, H=4, K=4, d=16):
    q = jax.random.normal(jax.random.key(1), (2, T, H, d))
    k = jax.random.normal(jax.random.key(2), (2, T, K, d))
    v = jax.random.normal(jax.random.key(3), (2, T, K, d))
    return q, k, v


def _run_sp(mesh, fn, q, k, v):
    spec = P(None, "sp", None, None)
    sharded = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec)
    return sharded(q, k, v)


def test_ulysses_matches_full(sp_mesh):
    q, k, v = _qkv()
    out = _run_sp(sp_mesh, lambda q, k, v: ulysses_attention(q, k, v, axis="sp"),
                  q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_grad(sp_mesh):
    q, k, v = _qkv(T=32)

    def loss_sp(q, k, v):
        return _run_sp(sp_mesh,
                       lambda q, k, v: ulysses_attention(q, k, v, axis="sp"),
                       q, k, v).sum()

    g1 = jax.grad(loss_sp)(q, k, v)
    g2 = jax.grad(lambda q: xla_attention(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-4)


def test_distributed_attention_wrapper(sp_mesh):
    q, k, v = _qkv()
    da = DistributedAttention(sequence_process_group="sp")
    out = _run_sp(sp_mesh, lambda q, k, v: da(q, k, v), q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_matches_full(sp_mesh):
    q, k, v = _qkv()
    out = _run_sp(sp_mesh, lambda q, k, v: ring_attention(q, k, v, axis="sp"),
                  q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gqa(sp_mesh):
    q, k, v = _qkv(H=8, K=2)
    out = _run_sp(sp_mesh, lambda q, k, v: ring_attention(q, k, v, axis="sp"),
                  q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_grad(sp_mesh):
    q, k, v = _qkv(T=32)

    def loss_sp(q):
        return _run_sp(sp_mesh,
                       lambda q, k, v: ring_attention(q, k, v, axis="sp"),
                       q, k, v).sum()

    g1 = jax.grad(loss_sp)(q)
    g2 = jax.grad(lambda q: xla_attention(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-4)


def test_sequence_tiled_compute():
    x = jax.random.normal(jax.random.key(0), (2, 32, 16))
    fn = lambda c: jax.nn.gelu(c) * 2.0
    np.testing.assert_allclose(
        np.asarray(sequence_tiled_compute(fn, x, num_shards=4)),
        np.asarray(fn(x)), atol=1e-6)


def test_tiled_logits_loss_matches_dense():
    B, T, D, V = 2, 32, 16, 64
    h = jax.random.normal(jax.random.key(1), (B, T, D))
    head = jax.random.normal(jax.random.key(2), (D, V))
    labels = np.random.default_rng(0).integers(0, V, (B, T))
    labels[0, :5] = -100
    tiled = tiled_logits_loss(h, head, jnp.asarray(labels), num_shards=4)
    logits = (h @ head).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    mask = labels != -100
    gold = np.take_along_axis(np.asarray(logits), np.maximum(labels, 0)[..., None],
                              axis=-1)[..., 0]
    ref = ((np.asarray(logz) - gold) * mask).sum() / mask.sum()
    np.testing.assert_allclose(float(tiled), ref, rtol=1e-5)
