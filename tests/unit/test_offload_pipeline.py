"""Overlapped offload data path (ISSUE 10): per-op AIO completion, pooled
pinned buffers, chunked leaf IO, the depth-k optimizer pipeline, and the
self-tuning swap configuration.

Pattern: reference ``tests/unit/ops/aio`` handle tests + the swap_tensor
pipelined-optimizer-swapper behavior contracts.
"""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, CPUAdamBuilder

requires_native = pytest.mark.skipif(
    not (AsyncIOBuilder().is_compatible() and CPUAdamBuilder().is_compatible()),
    reason="g++ toolchain unavailable")


@requires_native
class TestPerOpCompletion:
    def test_tickets_wait_individually_out_of_order(self, tmp_path):
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, chunk_mb=1)
        a = np.arange(300_000, dtype=np.float32)
        b = np.arange(400_000, dtype=np.float32) * 3
        ta = sw.swap_out("a", a)
        tb = sw.swap_out("b", b)
        tb.wait()  # waiting b does NOT require a to be complete or reaped
        ta.wait()
        rb = sw.swap_in_start("b")
        ra = sw.swap_in_start("a")
        np.testing.assert_array_equal(rb.wait(), b)  # out of submit order
        np.testing.assert_array_equal(ra.wait(), a)
        ra.release()
        rb.release()
        assert sw.pool.outstanding == 0

    @pytest.mark.parametrize("o_direct", [False, True])
    def test_swap_in_start_many_batched_roundtrip(self, tmp_path, o_direct):
        """ONE multi-file ticket (the KV tier's per-chain promote batch):
        every file's payload lands bit-exact at its aligned segment offset
        in the shared buffer, buffered and O_DIRECT."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, chunk_mb=1,
                                o_direct=o_direct)
        rng = np.random.default_rng(7)
        arrays = {f"leaf{i}": rng.normal(size=n).astype(np.float32)
                  for i, n in enumerate((1000, 70_000, 333))}  # odd tails
        for name, a in arrays.items():
            sw.swap_out(name, a).wait()
        ticket, segs = sw.swap_in_start_many(list(arrays))
        view = ticket.wait()
        for name, a in arrays.items():
            off, nb = segs[name]
            got = view[off:off + nb].view(np.float32)
            np.testing.assert_array_equal(got, a)
        ticket.release()
        assert sw.pool.outstanding == 0
        sw.close()
        sw.close()

    def test_write_does_not_fence_read(self, tmp_path):
        """A pending writeback must not block an independent prefetch wait
        (the old shared-barrier behavior). The read ticket completes and is
        consumable while the write ticket is still un-reaped."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2)
        seed = np.arange(100_000, dtype=np.float32)
        sw.swap_out("seed", seed).wait()
        w = sw.swap_out("big", np.ones(2_000_000, np.float32))
        r = sw.swap_in_start("seed")
        np.testing.assert_array_equal(r.wait(), seed)  # before w is waited
        r.release()
        w.wait()
        sw.close()

    def test_barrier_honors_sticky_chunk_failure(self, tmp_path):
        """A chunk failure reaped by poll() (native error counter already
        decremented) must still fail the next barrier — the ticket's view is
        dropped and its buffer returns, never a silent success."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2)
        a = np.arange(5000, dtype=np.float32)
        sw.swap_out("a", a).wait()
        r = sw.swap_in_start("a")
        r._failed = True  # as poll() records it after reaping a bad chunk
        with pytest.raises(IOError):
            sw.wait()
        assert r.wait() is None  # no garbage view
        assert sw.pool.outstanding == 0
        sw.close()

    def test_ticket_after_barrier_is_benign(self, tmp_path):
        """wait() (the legacy barrier) reaps everything; a later per-ticket
        wait on a barriered op returns instead of hanging, and read views
        are still decoded."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2)
        a = np.arange(5000, dtype=np.float32)
        t = sw.swap_out("a", a)
        sw.wait()
        assert t.wait() is None and t.done
        r = sw.swap_in_start("a")
        sw.wait()
        np.testing.assert_array_equal(r.wait(), a)
        r.release()
        sw.close()


@requires_native
class TestBufferPool:
    def test_no_growth_under_steady_state(self, tmp_path):
        """After warmup, a fixed working set reuses pooled buffers — zero
        new allocations per cycle (the reference's reusable pinned swap
        buffers)."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, chunk_mb=1)
        arrays = {f"t{i}": np.random.default_rng(i).normal(
            size=(100_000 + i,)).astype(np.float32) for i in range(3)}
        for _ in range(2):  # warmup: populate the pool at working-set width
            tickets = [sw.swap_out(n, a) for n, a in arrays.items()]
            for t in tickets:
                t.wait()
            reads = [sw.swap_in_start(n) for n in arrays]
            for r in reads:
                r.wait()
                r.release()
        baseline = sw.pool.allocations
        for _ in range(5):
            tickets = [sw.swap_out(n, a) for n, a in arrays.items()]
            for t in tickets:
                t.wait()
            reads = [sw.swap_in_start(n) for n in arrays]
            for r in reads:
                r.wait()
                r.release()
        assert sw.pool.allocations == baseline, "pool grew in steady state"
        assert sw.pool.reuses > 0
        assert sw.pool.outstanding == 0
        sw.close()

    def test_same_name_inflight_aliasing_regression(self, tmp_path):
        """Two back-to-back swap_outs of the SAME name must each own their
        buffer: the first write's data cannot be clobbered before it lands,
        and the final file content is the second payload."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=1)
        first = np.full(500_000, 1.0, np.float32)
        second = np.full(500_000, 2.0, np.float32)
        t1 = sw.swap_out("x", first)
        t2 = sw.swap_out("x", second)  # submitted while t1 may be queued
        assert t1.tid != t2.tid and t1.buf is not t2.buf
        t1.wait()
        t2.wait()
        # single worker → ops ran in submission order; last write wins
        np.testing.assert_array_equal(sw.swap_in("x"), second)
        assert sw.pool.outstanding == 0
        sw.close()

    def test_close_with_pending_ops_drains_first(self, tmp_path):
        """close() with operations still queued must drain before
        destroying the native handle (no use-after-free window), finish the
        write durably, and be idempotent."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=1)
        payloads = {f"p{i}": np.random.default_rng(i).normal(
            size=(400_000,)).astype(np.float32) for i in range(4)}
        for n, a in payloads.items():
            sw.swap_out(n, a)
        sw.close()  # pending writes still in the queue
        assert sw.handle is None and sw.pool.outstanding == 0
        sw.close()  # idempotent
        sw2 = AsyncTensorSwapper(str(tmp_path), num_threads=1)
        sw2._meta = dict(sw._meta)
        for n, a in payloads.items():  # every file complete on disk
            np.testing.assert_array_equal(sw2.swap_in(n), a)
        sw2.close()


@requires_native
class TestChunkedIO:
    @pytest.mark.parametrize("o_direct", [False, True])
    def test_chunked_roundtrip_bit_exact(self, tmp_path, o_direct):
        """A leaf larger than chunk_mb splits into many ops; the roundtrip
        is bit-exact, including non-chunk-multiple and sub-chunk sizes."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=4, chunk_mb=1,
                                o_direct=o_direct)
        rng = np.random.default_rng(0)
        shapes = [(1 << 20,),        # 4 MB = 4 chunks exactly
                  (1_300_003,),      # ~5 MB, odd tail chunk
                  (777,),            # sub-chunk
                  (257, 1031)]       # 2-D, ~1 MB
        arrays = {f"c{i}": rng.normal(size=s).astype(np.float32)
                  for i, s in enumerate(shapes)}
        tickets = [sw.swap_out(n, a) for n, a in arrays.items()]
        for t in tickets:
            t.wait()
        big = sw.swap_in_start("c1")
        assert len(big.op_ids) > 1 or big.done  # really chunked
        np.testing.assert_array_equal(big.wait(), arrays["c1"])
        big.release()
        for n, a in arrays.items():
            np.testing.assert_array_equal(sw.swap_in(n), a)
        sw.close()

    def test_bandwidth_stats_populate(self, tmp_path):
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, chunk_mb=1)
        a = np.ones(1 << 20, np.float32)
        sw.swap_out("a", a).wait()
        _ = sw.swap_in("a")
        bw = sw.bandwidth()
        assert bw["read_bytes"] == a.nbytes
        assert bw["write_bytes"] == a.nbytes
        assert bw["read_MBps"] > 0 and bw["write_MBps"] > 0
        sw.close()


@requires_native
class TestDepthKPipeline:
    def _params_grads(self, seed=0, leaves=6, n=4096):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        params = {f"l{i}": {"w": jnp.asarray(
            rng.normal(size=(n // 64, 64)), jnp.float32)}
            for i in range(leaves)}
        import jax

        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                rng.normal(size=p.shape) * 0.01, jnp.float32), params)
        return params, grads

    def test_pipeline_matches_serial_bit_exact(self, tmp_path):
        """Depth-k overlap is a scheduling change only: masters, moments,
        and uploaded params must be BIT-identical to the serial path."""
        import jax

        from deepspeed_tpu.offload import HostOffloadOptimizer

        params, grads = self._params_grads()
        outs = {}
        for label, kw in {
            "serial": dict(prefetch_depth=0, upload_overlap=False),
            "depth1": dict(prefetch_depth=1, upload_overlap=False),
            "depth3+upload": dict(prefetch_depth=3, upload_overlap=True),
        }.items():
            opt = HostOffloadOptimizer(
                params, lr=1e-2, nvme_path=str(tmp_path / label),
                aio_threads=4, aio_chunk_mb=1, **kw)
            p = params
            for s in range(3):
                p, skipped = opt.step(grads, p, s)
                assert not skipped
            outs[label] = {
                "params": jax.tree_util.tree_map(np.asarray, p),
                "masters": {k: v.copy() for k, v in opt.master.items()},
                "m": {k: opt.swapper.swap_in(k + ".m")
                      for k in opt.master},
            }
            opt.close()
        for label in ("depth1", "depth3+upload"):
            for k in outs["serial"]["masters"]:
                np.testing.assert_array_equal(
                    outs["serial"]["masters"][k], outs[label]["masters"][k])
                np.testing.assert_array_equal(
                    outs["serial"]["m"][k], outs[label]["m"][k])
            ref = jax.tree_util.tree_leaves(outs["serial"]["params"])
            got = jax.tree_util.tree_leaves(outs[label]["params"])
            for a, b in zip(ref, got):
                np.testing.assert_array_equal(a, b)

    def test_pipeline_overlaps(self, tmp_path):
        """The depth-k pipeline must measurably reduce IO stall vs serial
        (stall fraction strictly below the serial run's on the same data)."""
        from deepspeed_tpu.offload import HostOffloadOptimizer

        params, grads = self._params_grads(leaves=8, n=1 << 16)
        stalls = {}
        for label, depth in (("serial", 0), ("depth3", 3)):
            opt = HostOffloadOptimizer(
                params, lr=1e-2, nvme_path=str(tmp_path / label),
                aio_threads=4, aio_chunk_mb=1, prefetch_depth=depth,
                upload_overlap=False)
            p = params
            for s in range(2):
                p, _ = opt.step(grads, p, s)
            stalls[label] = opt._stall_fraction
            assert opt.swapper.pool.outstanding == 0
            opt.close()
        assert stalls["depth3"] < stalls["serial"]

    def test_abort_mid_pipeline_restores_pool(self, tmp_path):
        """An injected swap-site IO error mid-pipeline aborts cleanly: the
        exception propagates, every pooled buffer is returned, and no
        moment file is torn (all still readable at full size)."""
        from deepspeed_tpu.offload import HostOffloadOptimizer
        from deepspeed_tpu.resilience.faults import (
            FaultInjector, set_injector)

        params, grads = self._params_grads(leaves=5)
        opt = HostOffloadOptimizer(params, lr=1e-2, nvme_path=str(tmp_path),
                                   aio_threads=2, prefetch_depth=2,
                                   upload_overlap=False)
        p, _ = opt.step(grads, params, 0)  # one clean step
        moments = {k: opt.swapper.swap_in(k + ".m") for k in opt.master}
        set_injector(FaultInjector([
            {"kind": "io_error", "site": "swap_read", "times": 1}]))
        try:
            with pytest.raises(OSError):
                opt.step(grads, p, 1)
        finally:
            set_injector(None)
        assert opt.swapper.pool.outstanding == 0
        assert opt.swapper.pending == 0
        for k, before in moments.items():  # no torn files
            after = opt.swapper.swap_in(k + ".m")
            assert after.shape == before.shape
            assert np.isfinite(after).all()
        opt.close()

    def test_engine_config_plumbs_aio_block(self, tmp_path, eight_devices):
        """offload.aio knobs reach the swapper + optimizer through
        ds.initialize, and engine.offload_report() surfaces the pipeline."""
        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset

        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 2,
                    "offload_optimizer": {"device": "nvme",
                                          "nvme_path": str(tmp_path)}},
                "offload": {"aio": {"threads": 3, "chunk_mb": 2,
                                    "prefetch_depth": 4}},
                "mesh": {"fsdp": 8},
                "steps_per_print": 100,
            })
        opt = eng._offload
        assert opt.swapper.num_threads == 3
        assert opt.swapper.chunk_bytes == 2 << 20
        assert opt.prefetch_depth == 4
        b = {"input_ids": np.random.default_rng(0).integers(
            0, 256, (2 * eng.topology.dp_world_size, 16))}
        loss = eng.forward(b)
        eng.backward(loss)
        eng.step()
        rep = eng.offload_report()
        assert rep["enabled"] and rep["device"] == "nvme"
        assert rep["prefetch_depth"] == 4
        assert rep["swapper"]["pool"]["outstanding"] == 0
        assert rep["swapper"]["read_MBps"] > 0
        assert 0.0 <= rep["pipeline_stall_fraction"] <= 1.0

    def test_offload_metrics_in_registry(self, tmp_path):
        """offload/* instruments land in the process registry and render in
        the Prometheus exposition."""
        from deepspeed_tpu.observability.registry import (
            MetricsRegistry, set_registry)
        from deepspeed_tpu.offload import HostOffloadOptimizer

        reg = set_registry(MetricsRegistry())
        try:
            params, grads = self._params_grads(leaves=3)
            opt = HostOffloadOptimizer(params, lr=1e-2,
                                       nvme_path=str(tmp_path),
                                       prefetch_depth=2)
            opt.step(grads, params, 0)
            assert reg.get("offload/swap_in_ms").series
            assert reg.get("offload/swap_out_ms").series
            assert reg.get("offload/adam_ms").series
            assert reg.get("offload/upload_ms").series
            bytes_read = next(iter(
                reg.get("offload/bytes_read").series.values())).value
            assert bytes_read > 0
            text = reg.render_prometheus()
            assert "offload_swap_in_ms_bucket" in text
            assert "offload_bytes_read_total" in text
            assert "offload_pipeline_stall_fraction" in text
            opt.close()
        finally:
            set_registry(None)


@requires_native
class TestAutotune:
    def test_cache_store_and_load(self, tmp_path, monkeypatch):
        """First autotune sweeps and stores; the second call (same device)
        loads the cache instead of re-running the sweep."""
        import deepspeed_tpu.ops.aio_bench as ab

        calls = {"n": 0}
        real_sweep = ab.sweep

        def counting_sweep(*a, **kw):
            calls["n"] += 1
            return real_sweep(
                a[0], sizes_mb=[1], threads=[1, 2], repeats=1,
                o_direct=False, chunks_mb=[0])

        monkeypatch.setattr(ab, "sweep", counting_sweep)
        cache = str(tmp_path / "tune.json")
        cfg1 = ab.autotune_config(str(tmp_path / "swap"), cache_path=cache)
        assert calls["n"] == 1
        assert cfg1["threads"] in (1, 2) and cfg1["chunk_mb"] >= 1
        assert os.path.exists(cache)
        with open(cache) as f:
            stored = json.load(f)
        assert stored[cfg1["device"]]["threads"] == cfg1["threads"]
        cfg2 = ab.autotune_config(str(tmp_path / "swap2"), cache_path=cache)
        assert calls["n"] == 1, "second call must hit the cache"
        assert cfg2 == cfg1

    def test_swapper_adopts_autotuned_config(self, tmp_path, monkeypatch):
        import deepspeed_tpu.ops.aio_bench as ab
        from deepspeed_tpu.offload import AsyncTensorSwapper

        monkeypatch.setattr(
            ab, "autotune_config",
            lambda swap_dir, **kw: {"threads": 7, "chunk_mb": 3})
        sw = AsyncTensorSwapper(str(tmp_path), autotune=True)
        assert sw.num_threads == 7
        assert sw.chunk_bytes == 3 << 20
        assert sw.autotuned == {"threads": 7, "chunk_mb": 3}
        a = np.arange(10_000, dtype=np.float32)
        sw.swap_out("a", a).wait()
        np.testing.assert_array_equal(sw.swap_in("a"), a)
        sw.close()

    def test_explicit_knobs_beat_autotune(self, tmp_path, monkeypatch):
        import deepspeed_tpu.ops.aio_bench as ab
        from deepspeed_tpu.offload import AsyncTensorSwapper

        monkeypatch.setattr(
            ab, "autotune_config",
            lambda *a, **kw: {"threads": 7, "chunk_mb": 3})
        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, chunk_mb=16,
                                autotune=True)
        assert sw.num_threads == 2 and sw.chunk_bytes == 16 << 20
        sw.close()


@requires_native
@pytest.mark.chaos
@pytest.mark.parametrize("scenario", ["io-error-read", "io-error-write",
                                      "pool-steady-state"])
def test_offload_drill_scenario(scenario, tmp_path):
    """Exit-nonzero drill wrappers (tools/offload_drill.py): a swap-site
    io_error mid-pipeline must abort cleanly — pool restored, no torn
    moment files — and steady state must not grow the pool."""
    import os
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools")
    sys.path.insert(0, tools)
    from offload_drill import run_scenario

    verdict = run_scenario(scenario, workdir=str(tmp_path))
    assert verdict["ok"], verdict


@requires_native
class TestSubmitFailureReclaim:
    """dslint burn-down (resource-lifecycle): ``swap_out``/``swap_in_start``
    did ``pool.get`` and then ran fallible work (host copy, chunk submit)
    with no exception path returning the buffer — one submit failure
    permanently shrank the pinned pool (``outstanding`` never decremented,
    steady-state zero-allocation contract silently broken)."""

    def test_swap_out_submit_failure_returns_buffer(self, tmp_path):
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=1)
        arr = np.arange(65536, dtype=np.float32)
        sw.swap_out("warm", arr).wait()        # steady state: pool warmed
        assert sw.pool.outstanding == 0

        def boom(*a, **k):
            raise RuntimeError("submit exploded")
        sw._submit_chunks = boom
        with pytest.raises(RuntimeError):
            sw.swap_out("x", arr)
        assert sw.pool.outstanding == 0        # buffer came back
        with pytest.raises(RuntimeError):
            sw.swap_in_start("warm")
        assert sw.pool.outstanding == 0
        del sw._submit_chunks                  # restore the real method
        np.testing.assert_array_equal(sw.swap_in("warm"), arr)
        sw.close()

    def test_partial_chunk_submit_reaps_before_recycling(self, tmp_path):
        """An exception AFTER some chunks were queued must reap those ops
        before the buffer re-enters the pool — recycling a buffer with IO
        in flight aliases live data."""
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=2, chunk_mb=1)
        arr = np.arange((3 << 20) // 4, dtype=np.float32)  # 3 chunks
        orig = type(sw)._submit_chunks

        def partial(kind, path, buf, nbytes, ids):
            orig(sw, kind, path, buf, min(nbytes, sw.chunk_bytes), ids)
            raise RuntimeError("died mid-submit")
        sw._submit_chunks = partial
        with pytest.raises(RuntimeError):
            sw.swap_out("p", arr)
        assert sw.pool.outstanding == 0        # returned...
        assert sw.pending == 0                 # ...only after the reap
        del sw._submit_chunks
        sw.close()


class TestPinnedPoolConcurrency:
    """ISSUE 12 satellite: the pool gains a second concurrent client (the
    serving KV-tier promote path beside the Adam pipeline) — its free-list
    discipline must hold under multi-threaded get/release/abort churn, and
    returning one buffer twice must raise instead of silently aliasing."""

    def test_double_put_raises(self):
        from deepspeed_tpu.offload import PinnedBufferPool

        pool = PinnedBufferPool()
        buf = pool.get(4096)
        pool.put(buf)
        with pytest.raises(RuntimeError, match="twice"):
            pool.put(buf)

    def test_multithreaded_get_release_stress(self):
        import random
        import threading

        from deepspeed_tpu.offload import PinnedBufferPool

        pool = PinnedBufferPool(max_cached=16)
        stop = threading.Event()
        errors = []
        gets = [0] * 6

        def client(idx):
            rng = random.Random(idx)
            held = []
            try:
                while not stop.is_set():
                    if held and rng.random() < 0.5:
                        pool.put(held.pop(rng.randrange(len(held))))
                    else:
                        nbytes = rng.choice((4096, 65536, 1 << 20))
                        buf = pool.get(nbytes)
                        # exclusive ownership: stamp and verify — a buffer
                        # handed to two clients would tear this pattern
                        buf.data[:8] = idx
                        held.append(buf)
                        gets[idx] += 1
                        if buf.data[0] != idx or buf.data[7] != idx:
                            raise RuntimeError("buffer aliased")
                        if len(held) > 4:
                            pool.put(held.pop(0))
                for b in held:
                    pool.put(b)
            except BaseException as e:      # surfaced to the main thread
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        import time as _time

        _time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        rep = pool.report()
        assert rep["outstanding"] == 0
        assert rep["allocations"] + rep["reuses"] == sum(gets)
        # the free list holds no aliased entries
        ids = [id(b) for b in pool._free]
        assert len(ids) == len(set(ids))

    def test_stress_with_concurrent_swapper_clients(self, tmp_path):
        """Two swappers (the Adam pipeline shape and the KV-tier shape)
        sharing ONE pool from different threads: every roundtrip stays
        bit-exact and the pool fully restores."""
        if not AsyncIOBuilder().is_compatible():
            pytest.skip("g++ toolchain unavailable")
        import threading

        from deepspeed_tpu.offload import AsyncTensorSwapper, PinnedBufferPool

        pool = PinnedBufferPool()
        sw_a = AsyncTensorSwapper(str(tmp_path), num_threads=2, pool=pool)
        sw_b = AsyncTensorSwapper(str(tmp_path), num_threads=2, pool=pool,
                                  namespace="kv")
        errors = []

        def run(sw, tag, scale):
            try:
                for i in range(12):
                    arr = (np.arange(50_000, dtype=np.float32) + i) * scale
                    sw.swap_out(f"{tag}{i % 3}", arr).wait()
                    t = sw.swap_in_start(f"{tag}{i % 3}")
                    got = t.wait()
                    if not np.array_equal(got, arr):
                        raise RuntimeError(f"torn roundtrip {tag}{i}")
                    t.release()
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=run, args=(sw_a, "a", 1.0)),
                   threading.Thread(target=run, args=(sw_b, "b", -2.0))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert pool.outstanding == 0
        sw_a.close()
        sw_b.close()


@requires_native
class TestSwapperNamespace:
    """ISSUE 12 satellite: the KV tier is a second client of one swap
    device — its files must live under the namespace subdir, and discard()
    must bound disk for name-churning clients."""

    def test_namespace_scopes_files(self, tmp_path):
        from deepspeed_tpu.offload import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), namespace="kv",
                                num_threads=1)
        arr = np.arange(1024, dtype=np.float32)
        sw.swap_out("blk0", arr).wait()
        assert os.path.exists(os.path.join(str(tmp_path), "kv",
                                           "blk0.swp"))
        np.testing.assert_array_equal(sw.swap_in("blk0"), arr)
        sw.discard("blk0")
        assert not os.path.exists(os.path.join(str(tmp_path), "kv",
                                               "blk0.swp"))
        with pytest.raises(KeyError):
            sw.swap_in_start("blk0")           # metadata gone too
        sw.close()
