"""Expert-parallel MoE serving: engine wiring of the dropless grouped
path, AutoEP load balancing (tracker -> planner -> placement swap), the
quantized a2a byte accounting, and the ``moe_a2a_error`` fault site.

The bit-identity contracts asserted here are the PR's acceptance
criteria: greedy decode output is invariant to (a) the grouped kernel
choice, (b) expert-parallel width, and (c) an applied rebalance.

Slow wrappers at the bottom delegate to ``tools/serve_drill.py
--scenario moe-storm`` and ``tools/comm_drill.py --scenario moe-a2a``
(markers: ``moe`` + ``slow``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import TransformerLM, get_preset

pytestmark = pytest.mark.moe

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")

_FP32 = {"dtype": "float32", "param_dtype": "float32"}


def _engine(E=4, top_k=2, mesh=None, **kw):
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    return InferenceEngineV2(
        TransformerLM(get_preset("tiny", num_experts=E, top_k=top_k,
                                 moe_dispatch="grouped", **_FP32)),
        max_sequences=8, max_seq_len=128, block_size=16, mesh=mesh, **kw)


def _greedy(eng, prompt, n=8):
    r = eng.put([7], [np.asarray(prompt, np.int32)])
    first = int(np.argmax(np.asarray(r[7], np.float32)))
    out = eng.decode_batch([7], [first], steps=n)
    eng.flush([7])
    return [first] + [int(t) for t in out[7]]


class TestEngineExpertParallel:
    def test_ep_decode_matches_single_device(self, eight_devices):
        """fp32 greedy decode through the ep=4 sharded a2a dispatch is
        IDENTICAL to the unsharded engine — dropless means expert
        parallelism is a pure layout choice."""
        prompt = np.random.default_rng(0).integers(0, 250, 16)
        ref = _greedy(_engine(), prompt)
        ep = _engine(mesh={"ep": 4, "dp": 2})
        assert ep._moe_ep and ep.moe_kernel in ("ragged", "padded")
        assert _greedy(ep, prompt) == ref

    def test_ep_kernel_choice_is_invisible(self, eight_devices):
        """ragged vs padded under ep>1: same greedy tokens."""
        prompt = np.random.default_rng(1).integers(0, 250, 16)
        a = _greedy(_engine(mesh={"ep": 4, "dp": 2},
                            moe_kernel="ragged"), prompt)
        b = _greedy(_engine(mesh={"ep": 4, "dp": 2},
                            moe_kernel="padded"), prompt)
        assert a == b

    def test_rebalance_preserves_greedy(self, eight_devices):
        """An applied AutoEP rebalance (hot expert replicated onto spare
        slots, experts moved between shards) leaves greedy decode output
        bit-identical, and the planner's LPT bound holds."""
        from deepspeed_tpu.observability import MetricsRegistry

        eng = _engine(mesh={"ep": 4, "dp": 2}, moe_replica_slots=1)
        eng.enable_metrics(registry=MetricsRegistry())
        prompt = np.random.default_rng(2).integers(0, 250, 16)
        before = _greedy(eng, prompt)
        plan = eng.rebalance_moe(counts=[1000, 10, 10, 10])
        assert plan is not None and plan.moved_slots > 0
        assert plan.nrep[0] > 1                       # hot expert replicated
        assert plan.imbalance_after <= plan.bound + 1e-9
        assert plan.imbalance_after < plan.imbalance_before
        assert _greedy(eng, prompt) == before
        # second swap (back toward uniform) keeps the contract too
        eng.rebalance_moe(counts=[10, 10, 1000, 10])
        assert _greedy(eng, prompt) == before

    def test_expert_metrics_prometheus(self, eight_devices):
        """Per-expert token counters and the imbalance gauge land in the
        Prometheus exposition under the ``moe/`` namespace and the shard
        counts sum to tokens * top_k."""
        from deepspeed_tpu.moe import set_expert_tracker
        from deepspeed_tpu.observability import MetricsRegistry

        reg = MetricsRegistry()
        eng = _engine(mesh={"ep": 4, "dp": 2})
        eng.enable_metrics(registry=reg)
        try:
            prompt = np.random.default_rng(3).integers(0, 250, 16)
            _greedy(eng, prompt, n=4)
            counts = eng._moe_tracker.snapshot()
            # prefill 16 + 4 decode steps, top_k=2 (>= — retraces replay)
            assert counts.sum() >= (16 + 4) * 2
            text = reg.render_prometheus()
            assert 'moe_expert_tokens_total{expert="0"}' in text
            assert "moe_imbalance" in text
            assert eng._moe_tracker.imbalance() >= 1.0
        finally:
            set_expert_tracker(None)


class TestBalancerUnits:
    def test_plan_properties(self):
        from deepspeed_tpu.moe import plan_rebalance

        plan = plan_rebalance([900, 50, 30, 20], ep=4, slots_per_shard=2)
        assert sum(plan.nrep) == 8 and len(plan.assign) == 8
        assert plan.nrep[0] == 5                      # hot expert replicated
        assert set(plan.assign) == {0, 1, 2, 3}       # nobody evicted
        assert plan.imbalance_after <= plan.bound + 1e-9
        # replanning from the SAME counts and placement is a no-op
        again = plan_rebalance([900, 50, 30, 20], ep=4, slots_per_shard=2,
                               prev_assign=plan.assign)
        assert again.moved_slots == 0
        # uniform load wants no replication
        flat = plan_rebalance([100] * 8, ep=4, slots_per_shard=2)
        assert flat.nrep == [1] * 8 and flat.imbalance_after == 1.0

    def test_placement_tables_and_apply(self):
        from deepspeed_tpu.moe import apply_placement, placement_tables

        assign = [0, 1, 0, 2]                         # expert 0 on both shards
        t = placement_tables(assign, num_experts=3, ep=2)
        assert t["place_nrep"].tolist() == [2, 1, 1]
        # expert 0's replicas live at (shard 0, slot 0) and (shard 1, slot 0)
        assert sorted(zip(t["place_dest"][0].tolist()[:2],
                          t["place_slot"][0].tolist()[:2])) == [(0, 0), (1, 0)]
        w = {"router": jnp.arange(6.0).reshape(2, 3),
             "w_up": jnp.arange(12.0).reshape(3, 4)}
        out = apply_placement(w, assign, num_experts=3, ep=2)
        # slot layout [0, 1, 0, 2]: expert 0 duplicated, router untouched
        np.testing.assert_array_equal(np.asarray(out["w_up"]),
                                      np.asarray(w["w_up"])[[0, 1, 0, 2]])
        np.testing.assert_array_equal(np.asarray(out["router"]),
                                      np.asarray(w["router"]))
        assert out["place_nrep"].tolist() == [2, 1, 1]

    def test_tracker_window(self):
        from deepspeed_tpu.moe import ExpertLoadTracker

        tr = ExpertLoadTracker(4)
        tr.observe(np.array([8, 0, 0, 0]))
        tr.observe(np.array([0, 8, 0, 0]))
        assert tr.snapshot().tolist() == [8, 8, 0, 0]
        assert tr.imbalance() == pytest.approx(2.0)
        tr.reset()
        assert tr.snapshot().sum() == 0 and tr.imbalance() == 1.0


class TestA2ABytes:
    def test_wire_bytes_formula(self):
        import sys

        from deepspeed_tpu.comm import quantized as cq

        del sys  # idiom guard
        # dense single-hop bf16: ep chunks of chunk_elems * 2 bytes
        assert cq.moe_a2a_wire_bytes(8, 512)["all_to_all"] == 8 * 512 * 2
        # int8 shrinks the payload; the scale lanes keep it > 1/2
        q = cq.moe_a2a_wire_bytes(8, 512, bits=8, block_size=128)
        assert 8 * 512 * 1 <= q["all_to_all"] < 8 * 512 * 2
        # two-hop (slice_size=2): cross hop carries m=4 super-chunks,
        # intra hop stays dense bf16
        t = cq.moe_a2a_wire_bytes(8, 512, bits=8, block_size=128,
                                  slice_size=2)
        assert t["all_to_all_intra"] == 8 * 512 * 2
        # the quantized payload crossing slices is byte-for-byte the same
        # volume, carried in m=4 larger messages instead of ep=8 small ones
        assert t["all_to_all"] == q["all_to_all"]

    def test_cost_model_moe_a2a(self):
        from deepspeed_tpu.parallel.cost_model import moe_a2a_bytes

        # whole group inside one slice -> pure ICI
        ici = moe_a2a_bytes(128, 64, 2, ep=8, ici_size=8)
        assert ici["dcn"] == 0 and ici["ici"] > 0
        # group spans slices -> the cross hop rides DCN
        spl = moe_a2a_bytes(128, 64, 2, ep=8, ici_size=2)
        assert spl["dcn"] > 0 and spl["ici"] > 0
        # int8 wire cuts the DCN share, never the intra-slice hop
        q = moe_a2a_bytes(128, 64, 2, ep=8, ici_size=2, quant_bits=8)
        assert q["dcn"] < spl["dcn"] and q["ici"] == spl["ici"]

    def test_enumerate_meshes_ranks_ep(self):
        """The mesh enumerator prices the a2a for expert-sharded shapes
        (an ep axis must not be free — or autotuning would always pick
        it)."""
        from deepspeed_tpu.parallel.cost_model import (ModelProfile,
                                                       collective_volumes)

        prof = ModelProfile(n_params=int(1e8), n_layers=2, n_heads=4,
                            n_kv_heads=4, hidden=64, vocab=256, seq=128,
                            n_experts=8, top_k=2)
        vol = collective_volumes(prof, {"ep": 8}, tokens=1024)
        assert vol["per_axis"].get("ep", 0) > 0


class TestFaultSite:
    def test_on_moe_dispatch_site_pinning(self):
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     InjectedIOError)

        inj = FaultInjector([{"kind": "moe_a2a_error", "times": 1,
                              "site": "decode"}])
        inj.on_moe_dispatch("prefill")                # pinned: no fire
        with pytest.raises(InjectedIOError):
            inj.on_moe_dispatch("decode")
        inj.on_moe_dispatch("decode")                 # budget spent
        assert inj.fired == ["moe_a2a_error@moe_a2a:decode:step=-1"]


def test_bench_moe_trend_gate():
    """A tokens/s regression in any ep-sweep cell trips the ledger gate;
    an unmeasured cell in the newest run is 'no data', not a regression."""
    import sys

    sys.path.insert(0, _TOOLS)
    from bench_trend import compare

    def entry(sha, cells):
        return {"schema": 1, "bench": "bench_moe", "git_sha": sha,
                "result": {"metric": "moe_decode_tokens_per_sec",
                           "moe": cells}}

    a = entry("a", {"E8-ep8-ragged": {"tokens_per_sec": 150.0,
                                      "ragged_speedup": 1.2,
                                      "balance": 0.6},
                    "E4-ep4-ragged": {"tokens_per_sec": 90.0}})
    b = entry("b", {"E8-ep8-ragged": {"tokens_per_sec": 40.0,
                                      "ragged_speedup": 1.15,
                                      "balance": 0.6}})
    rep = compare([a, b], threshold=0.15)
    regressed = {r["metric"] for r in rep["regressions"]}
    assert "moe.E8-ep8-ragged.tokens_per_sec" in regressed
    assert not any("E4-ep4" in m for m in regressed)   # unmeasured: no gate
    assert not any("ragged_speedup" in m for m in regressed)  # within 15%
    assert rep["ok"] is False


# ---------------------------------------------------------------------------
# drill wrappers (slow): the scenario CLIs are the authority
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_moe_storm_drill(tmp_path, monkeypatch):
    """serve_drill moe-storm: skewed-router storm + mid-dispatch a2a
    faults -> zero token loss, bounded rebalance, identical greedy across
    the swap, pool restored."""
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    monkeypatch.setenv("DSTPU_BENCH_LEDGER", "0")
    verdict = run_scenario("moe-storm", workdir=str(tmp_path))
    assert verdict["ok"], verdict


@pytest.mark.slow
def test_moe_a2a_comm_drill(eight_devices):
    """comm_drill moe-a2a: traced wire bytes of the (quantized,
    hierarchical) expert a2a match the analytic payload exactly."""
    import sys

    sys.path.insert(0, _TOOLS)
    from comm_drill import run_scenario

    verdict = run_scenario("moe-a2a")
    assert verdict["ok"], verdict
