"""Config-system tests (parity model: tests/unit/runtime/test_ds_config_dict.py)."""

import json

import pytest

from deepspeed_tpu.config import DeepSpeedTpuConfig, from_config


def test_defaults():
    cfg = from_config(None)
    assert cfg.zero_optimization.stage == 0
    assert cfg.bf16.enabled
    assert not cfg.fp16.enabled
    assert cfg.precision_dtype == "bfloat16"


def test_from_dict_and_json(tmp_path):
    d = {
        "train_batch_size": 32,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
    }
    cfg = from_config(d)
    assert cfg.zero_optimization.stage == 2
    assert cfg.optimizer.params["lr"] == 1e-3
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps(d))
    cfg2 = from_config(str(p))
    assert cfg2.model_dump() == cfg.model_dump()


def test_unknown_key_rejected():
    with pytest.raises(Exception):
        from_config({"zero_optimization": {"stagee": 2}})


def test_invalid_stage_rejected():
    with pytest.raises(Exception):
        from_config({"zero_optimization": {"stage": 7}})


@pytest.mark.parametrize(
    "tb,mb,ga,dp,expect",
    [
        (32, 4, None, 4, (32, 4, 2)),
        (32, None, 2, 4, (32, 4, 2)),
        (None, 4, 2, 4, (32, 4, 2)),
        (None, 4, None, 4, (16, 4, 1)),
        (32, None, None, 4, (32, 8, 1)),
    ],
)
def test_batch_triple_resolution(tb, mb, ga, dp, expect):
    cfg = from_config({
        "train_batch_size": tb,
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": ga,
    })
    cfg.resolve_batch_sizes(dp)
    assert (cfg.train_batch_size, cfg.train_micro_batch_size_per_gpu,
            cfg.gradient_accumulation_steps) == expect


def test_batch_triple_inconsistent():
    cfg = from_config({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 3,
    })
    with pytest.raises(ValueError):
        cfg.resolve_batch_sizes(4)


def test_batch_triple_missing():
    cfg = from_config({})
    with pytest.raises(ValueError):
        cfg.resolve_batch_sizes(4)


def test_auto_values():
    cfg = from_config({"train_batch_size": "auto", "train_micro_batch_size_per_gpu": 2})
    cfg.resolve_batch_sizes(8)
    assert cfg.train_batch_size == 16


def test_mesh_config():
    cfg = from_config({"mesh": {"tp": 2, "fsdp": 2}})
    assert cfg.mesh.resolved_dp(8) == 2
    with pytest.raises(ValueError):
        cfg.mesh.resolved_dp(7)


def test_legacy_monitor_keys():
    cfg = from_config({"tensorboard": {"enabled": True, "output_path": "/tmp/tb"}})
    assert cfg.monitor_config.tensorboard.enabled
