"""Engine tests — the TPU analog of ``tests/unit/v1/zero/test_zero.py``: tiny models
trained a few steps on a virtual 8-device mesh, asserting convergence and
cross-stage equivalence instead of hook/partition internals."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset


def make_config(stage=0, mesh=None, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh or {},
        "steps_per_print": 100,
    }
    cfg.update(over)
    return cfg


def data_iter(batch, seq=32, seed=0):
    """A fixed batch, repeated — convergence tests overfit it deterministically."""
    rng = np.random.default_rng(seed)
    fixed = {"input_ids": rng.integers(0, 256, (batch, seq))}
    while True:
        yield fixed


def train_steps(engine, steps, ga=1, seed=0):
    it = data_iter(engine.train_micro_batch_size_per_gpu()
                   * engine.topology.dp_world_size, seed=seed)
    losses = []
    for _ in range(steps):
        for _ in range(ga):
            loss = engine.forward(next(it))
            engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_converge(stage, eight_devices):
    model = TransformerLM(get_preset("tiny"))
    mesh = {"fsdp": 8} if stage else {"dp": 8}
    eng, *_ = ds.initialize(model=model, config=make_config(stage, mesh))
    losses = train_steps(eng, 5)
    assert losses[-1] < losses[0]
    assert eng.global_steps == 5


def test_stage3_param_sharding(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=make_config(
        3, {"fsdp": 8}, zero_optimization={"stage": 3, "param_persistence_threshold": 0}))
    # large params must actually be sharded over fsdp
    wq = eng.params["layers"]["attn"]["wq"]
    assert "fsdp" in str(eng.param_spec_tree["layers"]["attn"]["wq"])
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert np.prod(shard_shape) < np.prod(wq.shape)


def test_grad_accumulation_boundary(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=make_config(
        1, {"fsdp": 8}, gradient_accumulation_steps=2))
    it = data_iter(2 * 8)
    loss = eng.forward(next(it))
    eng.backward(loss)
    assert not eng.is_gradient_accumulation_boundary()
    eng.step()  # no-op before boundary
    assert eng.global_steps == 0
    loss = eng.forward(next(it))
    eng.backward(loss)
    assert eng.is_gradient_accumulation_boundary()
    eng.step()
    assert eng.global_steps == 1


def test_stage_equivalence(eight_devices):
    """ZeRO stages are layout choices — the math must be identical."""
    ref_losses = None
    for stage in (0, 2, 3):
        model = TransformerLM(get_preset("tiny"))
        mesh = {"fsdp": 8} if stage else {"dp": 8}
        eng, *_ = ds.initialize(model=model, config=make_config(stage, mesh))
        losses = train_steps(eng, 3, seed=7)
        if ref_losses is None:
            ref_losses = losses
        else:
            np.testing.assert_allclose(losses, ref_losses, rtol=2e-3)


def test_fp16_loss_scaler_state(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=make_config(
        0, {"dp": 8}, fp16={"enabled": True, "initial_scale_power": 8},
        bf16={"enabled": False}))
    losses = train_steps(eng, 2)
    assert float(eng.scaler_state["scale"]) >= 1.0
    assert all(np.isfinite(losses))


def test_tp_matches_dp(eight_devices):
    """Tensor-parallel must compute the same loss as pure DP."""
    model = TransformerLM(get_preset("tiny"))
    eng_dp, *_ = ds.initialize(model=model, config=make_config(0, {"dp": 8}))
    l_dp = train_steps(eng_dp, 2, seed=3)
    model2 = TransformerLM(get_preset("tiny"))
    eng_tp, *_ = ds.initialize(model=model2, config=make_config(
        0, {"dp": 2, "tp": 4}, train_micro_batch_size_per_gpu=8))
    l_tp = train_steps(eng_tp, 2, seed=3)
    np.testing.assert_allclose(l_dp, l_tp, rtol=2e-3)


def test_checkpoint_roundtrip(tmp_path, eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=make_config(2, {"fsdp": 8}))
    train_steps(eng, 2)
    eng.save_checkpoint(str(tmp_path), client_state={"note": "hi"})
    step_before = eng.global_steps
    p_before = np.asarray(eng.params["final_norm"]["scale"])

    model2 = TransformerLM(get_preset("tiny"))
    eng2, *_ = ds.initialize(model=model2, config=make_config(2, {"fsdp": 8}))
    path, client = eng2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client["note"] == "hi"
    assert eng2.global_steps == step_before
    np.testing.assert_allclose(np.asarray(eng2.params["final_norm"]["scale"]),
                               p_before, rtol=1e-6)


def test_checkpoint_reshard(tmp_path, eight_devices):
    """Universal-checkpoint behavior: save at stage 3 / fsdp=8, load at stage 0 / dp=8."""
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=make_config(3, {"fsdp": 8}))
    train_steps(eng, 1)
    eng.save_checkpoint(str(tmp_path))

    model2 = TransformerLM(get_preset("tiny"))
    eng2, *_ = ds.initialize(model=model2, config=make_config(0, {"dp": 8}))
    eng2.load_checkpoint(str(tmp_path))
    l2 = train_steps(eng2, 1, seed=9)
    assert np.isfinite(l2[0])


def test_fused_matches_imperative_fp16(eight_devices):
    """fused_train_step must carry the fp16 loss-scaler semantics of the
    forward/backward/step path (reference weak spot: the fused path silently
    dropping DynamicLossScaler)."""
    # scale 2^126 (still finite in fp32): loss*scale overflows to inf, so step 1
    # must be SKIPPED and the scale halved — on both paths identically
    cfg = make_config(0, {"dp": 8}, fp16={"enabled": True, "initial_scale_power": 126})
    m1 = TransformerLM(get_preset("tiny"))
    e1, *_ = ds.initialize(model=m1, config=cfg)
    m2 = TransformerLM(get_preset("tiny"))
    e2, *_ = ds.initialize(model=m2, config=cfg)
    it = data_iter(16)
    batch = next(it)
    l_imp = None
    for _ in range(3):
        loss = e1.forward(batch)
        e1.backward(loss)
        e1.step()
        l_imp = float(loss)
    for _ in range(3):
        l_fused = float(e2.fused_train_step(batch))
    assert e1.skipped_steps >= 1, "overflow case never triggered"
    assert e1.skipped_steps == e2.skipped_steps
    assert e1.global_steps == e2.global_steps
    assert float(e1.scaler_state["scale"]) == float(e2.scaler_state["scale"])
    assert float(e1.scaler_state["scale"]) < 2.0 ** 126  # halved after overflow
    np.testing.assert_allclose(l_imp, l_fused, rtol=2e-2)


def test_fused_step_with_offload(tmp_path, eight_devices):
    """fused_train_step must work with the host-offload optimizer."""
    cfg = make_config(
        2, {"dp": 8},
        zero_optimization={"stage": 2,
                           "offload_optimizer": {"device": "cpu"}})
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=cfg)
    it = data_iter(16)
    losses = [float(eng.fused_train_step(next(it))) for _ in range(4)]
    assert losses[-1] < losses[0]
    assert eng.global_steps == 4
