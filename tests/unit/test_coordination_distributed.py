"""PR 2 coordination drills on a REAL 2-process ``jax.distributed`` fixture.

The thread-simulated ``ThreadFleet`` reducers in ``test_resilience.py``
exercise the decision algebra; these tests exercise the actual
cross-process plane: two separate Python processes rendezvous through
``jax.distributed.initialize`` and agree via
:func:`~deepspeed_tpu.resilience.kv_store_max_reduce` — the coordination
service's key-value store, the reduce path that works even where
multi-process device collectives do not (the CPU backend these tests run
on). Split-brain preemption must converge to a fleet SAVE with IDENTICAL
committed tags, and a single peer's abort vote must abort everyone.

Marked ``slow``: each test pays two interpreter + rendezvous startups.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    rank, port, workdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    import jax
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=rank)
    import numpy as np
    from deepspeed_tpu.resilience import (ABORT, CONTINUE, SAVE,
                                          ResilienceCoordinator,
                                          kv_store_max_reduce)
    from deepspeed_tpu.resilience.manager import write_manifest
    from deepspeed_tpu.runtime.checkpoint import (read_latest_tag,
                                                  write_latest_atomic)

    step = 5
    coord = ResilienceCoordinator(
        reduce_fn=kv_store_max_reduce(num_processes=2, rank=rank,
                                      timeout_ms=60_000))
    out = {"rank": rank}

    # drill 1: split-brain preemption -> fleet SAVE, identical tags
    preempted = rank == 0                # only host 0 got the SIGTERM
    local = SAVE if preempted else CONTINUE
    decision = coord.decide(step, local,
                            "preemption notice" if preempted else "")
    out["save_decision"] = decision
    out["save_reason"] = coord.last_reason
    # commit with the manager's protocol — data -> manifest (stamped with
    # the fleet decision) -> atomic latest. The orbax tensor save needs
    # multi-process device collectives the CPU backend lacks; what this
    # drill pins is the cross-process agreement + commit ordering + stamp.
    tag = f"preempt_step{step}"
    host_dir = os.path.join(workdir, f"host{rank}")
    tag_dir = os.path.join(host_dir, tag)
    os.makedirs(tag_dir, exist_ok=True)
    np.savez(os.path.join(tag_dir, "state.npz"),
             w=np.arange(4.0), step=np.int32(step))
    write_manifest(tag_dir, step,
                   extra={"coordination": coord.decision_record()})
    write_latest_atomic(host_dir, tag)
    out["tag"] = tag
    out["latest"] = read_latest_tag(host_dir)

    # drill 2: one peer's abort vote aborts everyone at the same boundary
    if rank == 1:
        coord.signal_abort("hang: stuck collective all_reduce_host")
    out["abort_decision"] = coord.decide(7)
    out["abort_reason"] = coord.last_reason
    out["counters"] = coord.counters

    with open(os.path.join(workdir, f"result_{rank}.json"), "w") as f:
        json.dump(out, f)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_fleet(tmp_path) -> list:
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    env = {**os.environ, "PYTHONPATH":
           _REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(rank), str(port), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(2)]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process fleet wedged (rendezvous or reduce hang)")
        logs.append(out.decode(errors="replace"))
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker died rc={p.returncode}:\n{log}"
    return [json.loads((tmp_path / f"result_{r}.json").read_text())
            for r in range(2)]


def test_two_process_coordination_drills(tmp_path):
    from deepspeed_tpu.resilience import ABORT, SAVE
    from deepspeed_tpu.resilience.manager import verify_tag_dir

    r0, r1 = _run_fleet(tmp_path)
    # split-brain preemption converged to a fleet SAVE on both processes
    assert r0["save_decision"] == r1["save_decision"] == SAVE
    assert r0["save_reason"] == "preemption notice"     # the signaled host
    assert r1["save_reason"] == "peer signal"           # its peer
    # ...with the IDENTICAL tag committed and verified on each host
    assert r0["tag"] == r1["tag"] == "preempt_step5"
    for rank, res in ((0, r0), (1, r1)):
        assert res["latest"] == res["tag"]
        host = tmp_path / f"host{rank}"
        ok, why = verify_tag_dir(str(host / res["tag"]))
        assert ok, why
        manifest = json.load(open(host / res["tag"] / "manifest.json"))
        assert manifest["coordination"]["decision"] == "SAVE"
        assert manifest["coordination"]["step"] == 5
    # a single peer's abort vote aborted BOTH at the same boundary
    assert r0["abort_decision"] == r1["abort_decision"] == ABORT
    assert r1["abort_reason"].startswith("hang")
    assert r0["abort_reason"].startswith("peer signal")
    # every agreement really crossed the process boundary (2 collectives)
    assert r0["counters"]["collectives"] == 2
    assert r1["counters"]["collectives"] == 2
