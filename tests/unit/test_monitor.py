"""Monitor-layer unit tests (``deepspeed_tpu/monitor``).

Satellite coverage the layer never had: the ``CSVMonitor`` round-trip
(header once, appends accumulate, tag sanitization, the handle cache
actually caching), ``MonitorMaster`` graceful degradation when a backend's
client library fails to import, and the full registry → bridge → CSV
pipeline the observability layer rides on.
"""

import csv
import os

import pytest

from deepspeed_tpu.config.config import MonitorBackendConfig, MonitorConfig
from deepspeed_tpu.monitor.monitor import CSVMonitor, MonitorMaster
from deepspeed_tpu.observability import MetricsRegistry, MonitorBridge

pytestmark = pytest.mark.obs


def _csv_cfg(tmp_path, job="job"):
    return MonitorBackendConfig(enabled=True, output_path=str(tmp_path),
                                job_name=job)


def _rows(path):
    with open(path, newline="") as f:
        return list(csv.reader(f))


class TestCSVMonitor:
    def test_round_trip_header_append_and_sanitization(self, tmp_path):
        mon = CSVMonitor(_csv_cfg(tmp_path))
        mon.write_events([("a/b c", 1.0, 1), ("a/b c", 2.5, 2),
                          ("plain", 7.0, 1)])
        mon.write_events([("a/b c", 4.0, 3)])
        rows = _rows(tmp_path / "job" / "a_b_c.csv")
        assert rows[0] == ["step", "value", "time"]        # header once
        assert [(r[0], r[1]) for r in rows[1:]] == [
            ("1", "1.0"), ("2", "2.5"), ("3", "4.0")]
        assert (tmp_path / "job" / "plain.csv").exists()
        mon.close()

    def test_handle_cache_reuses_one_open_file_per_tag(self, tmp_path):
        mon = CSVMonitor(_csv_cfg(tmp_path))
        mon.write_events([("t", 1.0, 1)])
        f1 = mon._files["t"][0]
        for step in range(2, 6):
            mon.write_events([("t", float(step), step)])
        assert mon._files["t"][0] is f1          # cached, not reopened
        assert len(mon._files) == 1
        # rows are visible to an independent reader without close() —
        # write_events flushes the touched handles
        assert len(_rows(tmp_path / "job" / "t.csv")) == 6
        mon.close()
        assert mon._files == {} and f1.closed
        # writing after close() reopens and appends (no second header)
        mon.write_events([("t", 9.0, 9)])
        rows = _rows(tmp_path / "job" / "t.csv")
        assert rows[-1][0] == "9"
        assert sum(1 for r in rows if r[0] == "step") == 1  # header once
        mon.close()

    def test_close_is_idempotent(self, tmp_path):
        mon = CSVMonitor(_csv_cfg(tmp_path))
        mon.write_events([("t", 1.0, 1)])
        mon.close()
        mon.close()


class TestMonitorMaster:
    def test_degrades_gracefully_when_backend_import_fails(self, tmp_path,
                                                           monkeypatch):
        import deepspeed_tpu.monitor.monitor as mm

        def _boom(self, cfg):
            raise ImportError("no tensorboard in this environment")

        monkeypatch.setattr(mm.TensorBoardMonitor, "__init__", _boom)
        cfg = MonitorConfig(
            tensorboard={"enabled": True, "output_path": str(tmp_path)},
            csv_monitor={"enabled": True, "output_path": str(tmp_path),
                         "job_name": "deg"})
        master = MonitorMaster(cfg)              # must not raise
        assert len(master.backends) == 1
        assert isinstance(master.backends[0], CSVMonitor)
        master.write_events([("x", 1.0, 1)])     # surviving backend works
        assert (tmp_path / "deg" / "x.csv").exists()
        master.close()

    def test_registry_bridge_csv_end_to_end(self, tmp_path):
        """The observability pipeline: instruments → MonitorBridge deltas →
        MonitorMaster → CSV files on disk."""
        reg = MetricsRegistry()
        master = MonitorMaster(MonitorConfig(csv_monitor={
            "enabled": True, "output_path": str(tmp_path),
            "job_name": "e2e"}))
        bridge = MonitorBridge(master, reg)
        reg.counter("serving/requests",
                    labels={"terminal": "completed"}).inc(3)
        h = reg.histogram("serving/ttft_ms")
        for v in (5.0, 9.0, 40.0):
            h.observe(v)
        reg.gauge("serving/kv_occupancy").set(0.25)
        bridge.flush(step=10)
        out = tmp_path / "e2e"
        assert _rows(out / "serving_requests.terminal=completed.csv")[-1][:2] \
            == ["10", "3.0"]
        assert _rows(out / "serving_kv_occupancy.csv")[-1][:2] == ["10", "0.25"]
        ttft_count = _rows(out / "serving_ttft_ms_count.csv")
        assert ttft_count[-1][:2] == ["10", "3.0"]
        assert (out / "serving_ttft_ms_p99.csv").exists()
        # delta semantics: an unchanged registry adds no rows
        bridge.flush(step=11)
        assert len(_rows(out / "serving_kv_occupancy.csv")) == 2
        master.close()
