"""ZeRO++ tests — the TPU analog of ``tests/unit/v1/runtime/zero/test_zeropp.py``:
quantized-collective and hierarchically-partitioned training must stay within
quantization tolerance of the dense ZeRO baseline, and the compiled step must
actually carry int8 payloads on the wire (not silently fall back to fp32)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset


def make_config(stage, mesh, zeropp=None, ga=1):
    zo = {"stage": stage, "param_persistence_threshold": 0}
    zo.update(zeropp or {})
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": ga,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": zo,
        "mesh": mesh,
        "steps_per_print": 100,
    }


def fixed_batch(batch, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, (batch, seq))}


def run_steps(eng, steps, seed=0):
    batch = fixed_batch(eng.train_micro_batch_size_per_gpu()
                        * eng.topology.dp_world_size, seed=seed)
    losses = []
    for _ in range(steps):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("zeropp", [
    {"zero_quantized_gradients": True},
    {"zero_quantized_weights": True},
    {"zero_quantized_weights": True, "zero_quantized_gradients": True},
])
def test_zeropp_matches_dense_stage3(zeropp, eight_devices):
    """qwZ/qgZ training tracks the dense ZeRO-3 baseline within quant tolerance."""
    mesh = {"fsdp": 4, "dp": 2}
    base = ds.initialize(model=TransformerLM(get_preset("tiny")),
                         config=make_config(3, mesh))[0]
    zpp = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config(3, mesh, zeropp))[0]
    assert zpp._zpp is not None, "ZeRO++ plan not built"
    ref = run_steps(base, 4)
    got = run_steps(zpp, 4)
    assert got[-1] < got[0], "quantized run failed to converge"
    np.testing.assert_allclose(got, ref, rtol=0.05)


def test_hpz_secondary_partition(eight_devices):
    """hpZ: training matches dense ZeRO-3; the secondary copy is sharded 1/k
    per device with per-step gathers confined to the k-wide intra groups."""
    mesh = {"fsdp": 8}
    k = 2
    base = ds.initialize(model=TransformerLM(get_preset("tiny")),
                         config=make_config(3, mesh))[0]
    hpz = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config(3, mesh,
                                           {"zero_hpz_partition_size": k}))[0]
    assert hpz._zpp is not None and hpz._zpp.uses_secondary
    # secondary leaves: leading device axis of size fsdp, slice = 1/k of the dim
    prim = jax.tree_util.tree_leaves(hpz.params)
    sec = jax.tree_util.tree_leaves(hpz._hpz_secondary)
    n_fsdp = hpz.topology.size("fsdp")
    assert any(s.shape[0] == n_fsdp and s.ndim == p.ndim + 1
               for s, p in zip(sec, prim))
    ref = run_steps(base, 4)
    got = run_steps(hpz, 4)
    # bf16 secondary copy vs fp32 gather: bf16-level tolerance
    np.testing.assert_allclose(got, ref, rtol=0.02)


def test_hpz_invalid_partition_size(eight_devices):
    with pytest.raises(ValueError, match="zero_hpz_partition_size"):
        ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config(3, {"fsdp": 8},
                                         {"zero_hpz_partition_size": 3}))


def test_qgz_int8_on_the_wire(eight_devices):
    """The compiled fwd/bwd must carry s8 all-to-all traffic (qgZ) — the byte
    reduction the reference asserts through comms logging."""
    eng = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, {"fsdp": 8},
                           {"zero_quantized_gradients": True,
                            "zero_quantized_weights": True}))[0]
    batch = eng._put_batch(fixed_batch(2 * eng.topology.dp_world_size))
    with jax.sharding.set_mesh(eng.mesh):
        lowered = eng._fwd_bwd.lower(eng.params, batch,
                                     eng.scaler_state["scale"])
    hlo = lowered.compile().as_text()
    a2a_lines = [l for l in hlo.splitlines() if "all-to-all" in l]
    assert any("s8" in l for l in a2a_lines), "no int8 all-to-all in HLO (qgZ dead)"
    ag_lines = [l for l in hlo.splitlines() if "all-gather" in l]
    assert any("s8" in l for l in ag_lines), "no int8 all-gather in HLO (qwZ dead)"


def test_zeropp_fused_step_matches_imperative(eight_devices):
    """The fused single-jit ZeRO++ step and forward/backward/step agree."""
    mesh = {"fsdp": 4, "dp": 2}
    zeropp = {"zero_quantized_gradients": True, "zero_hpz_partition_size": 2}
    a = ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config(3, mesh, zeropp, ga=2))[0]
    b = ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config(3, mesh, zeropp, ga=2))[0]
    batch = fixed_batch(2 * 2 * a.topology.dp_world_size)  # ga * micro * dp
    half = {k: v[:v.shape[0] // 2] for k, v in batch.items()}
    half2 = {k: v[v.shape[0] // 2:] for k, v in batch.items()}
    for _ in range(3):
        a.fused_train_step(batch)
        for mb in (half, half2):
            loss = b.forward(mb)
            b.backward(loss)
        b.step()
    assert a.global_steps == b.global_steps == 3
    # NOT bit-identical by design: the imperative path quantize-reduces each
    # microbatch (ga=1 per fwd_bwd) while the fused path reduces the ga-sum
    # once — the difference is bounded by int8 quantization noise.
    pa = jax.tree_util.tree_leaves(a.params)
    pb = jax.tree_util.tree_leaves(b.params)
    for x, y in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-2)


def test_mics_mesh_validation(eight_devices):
    """MiCS keys are validated against the mesh, not silently ignored."""
    ok = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "mics_shard_size": 4},
        "mesh": {"fsdp": 4, "dp": 2}, "steps_per_print": 100})[0]
    assert ok.topology.size("fsdp") == 4
    with pytest.raises(ValueError, match="mics_shard_size"):
        ds.initialize(model=TransformerLM(get_preset("tiny")), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "mics_shard_size": 4},
            "mesh": {"fsdp": 8}, "steps_per_print": 100})
    with pytest.raises(ValueError, match="hierarchical"):
        ds.initialize(model=TransformerLM(get_preset("tiny")), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "mics_shard_size": 4,
                                  "mics_hierarchical_params_gather": True},
            "mesh": {"fsdp": 4, "dp": 2}, "steps_per_print": 100})


def test_zero3_schedule_carries_gather_and_scatter(eight_devices):
    """Round-2 weak #3 (partial): the compiled ZeRO-3 step must contain the
    parameter all-gathers and gradient reduce-scatters that replace the
    reference's prefetch coordinator + IPG buckets. (XLA:CPU lowers them
    synchronously; on TPU/GPU the scheduler emits the async start/done form
    and overlaps them with compute — a backend property, not a program
    one.)"""
    import re

    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
        "mesh": {"fsdp": 8}, "steps_per_print": 100})
    b = eng._put_batch({"input_ids": np.zeros((16, 16), np.int32)})
    with jax.sharding.set_mesh(eng.mesh):
        txt = eng._fwd_bwd.lower(eng.params, b,
                                 eng.scaler_state["scale"]).compile().as_text()
    assert "all-gather" in txt, "ZeRO-3 step compiled without all-gathers"
    # grad partitioning: reduce-scatter proper, or XLA:CPU's all-reduce +
    # dynamic-slice lowering of it — a NON-scalar all-reduce (the scalar
    # mean-loss reduction alone must not satisfy this)
    has_rs = "reduce-scatter" in txt
    has_tensor_ar = bool(re.search(
        r"= *[a-z0-9]+\[[0-9][0-9,]*\][^=\n]*all-reduce", txt))
    assert has_rs or has_tensor_ar, \
        "no grad reduce-scatter (nor tensor all-reduce lowering) in the step"
