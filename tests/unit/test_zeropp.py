"""ZeRO++ tests — the TPU analog of ``tests/unit/v1/runtime/zero/test_zeropp.py``:
quantized-collective and hierarchically-partitioned training must stay within
quantization tolerance of the dense ZeRO baseline, and the compiled step must
actually carry int8 payloads on the wire (not silently fall back to fp32)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset


def make_config(stage, mesh, zeropp=None, ga=1):
    zo = {"stage": stage, "param_persistence_threshold": 0}
    zo.update(zeropp or {})
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": ga,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": zo,
        "mesh": mesh,
        "steps_per_print": 100,
    }


def fixed_batch(batch, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, (batch, seq))}


def run_steps(eng, steps, seed=0):
    batch = fixed_batch(eng.train_micro_batch_size_per_gpu()
                        * eng.topology.dp_world_size, seed=seed)
    losses = []
    for _ in range(steps):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("zeropp", [
    {"zero_quantized_gradients": True},
    {"zero_quantized_weights": True},
    {"zero_quantized_weights": True, "zero_quantized_gradients": True},
])
def test_zeropp_matches_dense_stage3(zeropp, eight_devices):
    """qwZ/qgZ training tracks the dense ZeRO-3 baseline within quant tolerance."""
    mesh = {"fsdp": 4, "dp": 2}
    base = ds.initialize(model=TransformerLM(get_preset("tiny")),
                         config=make_config(3, mesh))[0]
    zpp = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config(3, mesh, zeropp))[0]
    assert zpp._zpp is not None, "ZeRO++ plan not built"
    ref = run_steps(base, 4)
    got = run_steps(zpp, 4)
    assert got[-1] < got[0], "quantized run failed to converge"
    np.testing.assert_allclose(got, ref, rtol=0.05)


def test_hpz_secondary_partition(eight_devices):
    """hpZ: training matches dense ZeRO-3; the secondary copy is sharded 1/k
    per device with per-step gathers confined to the k-wide intra groups."""
    mesh = {"fsdp": 8}
    k = 2
    base = ds.initialize(model=TransformerLM(get_preset("tiny")),
                         config=make_config(3, mesh))[0]
    hpz = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config(3, mesh,
                                           {"zero_hpz_partition_size": k}))[0]
    assert hpz._zpp is not None and hpz._zpp.uses_secondary
    # secondary leaves: leading device axis of size fsdp, slice = 1/k of the dim
    prim = jax.tree_util.tree_leaves(hpz.params)
    sec = jax.tree_util.tree_leaves(hpz._hpz_secondary)
    n_fsdp = hpz.topology.size("fsdp")
    assert any(s.shape[0] == n_fsdp and s.ndim == p.ndim + 1
               for s, p in zip(sec, prim))
    ref = run_steps(base, 4)
    got = run_steps(hpz, 4)
    # bf16 secondary copy vs fp32 gather: bf16-level tolerance
    np.testing.assert_allclose(got, ref, rtol=0.02)


def test_hpz_invalid_partition_size(eight_devices):
    with pytest.raises(ValueError, match="zero_hpz_partition_size"):
        ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config(3, {"fsdp": 8},
                                         {"zero_hpz_partition_size": 3}))


def test_qgz_int8_on_the_wire(eight_devices):
    """The compiled fwd/bwd must carry s8 all-to-all traffic (qgZ) — the byte
    reduction the reference asserts through comms logging."""
    eng = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, {"fsdp": 8},
                           {"zero_quantized_gradients": True,
                            "zero_quantized_weights": True}))[0]
    batch = eng._put_batch(fixed_batch(2 * eng.topology.dp_world_size))
    with jax.sharding.set_mesh(eng.mesh):
        lowered = eng._fwd_bwd.lower(eng.params, batch,
                                     eng.scaler_state["scale"])
    hlo = lowered.compile().as_text()
    a2a_lines = [l for l in hlo.splitlines() if "all-to-all" in l]
    assert any("s8" in l for l in a2a_lines), "no int8 all-to-all in HLO (qgZ dead)"
    ag_lines = [l for l in hlo.splitlines() if "all-gather" in l]
    assert any("s8" in l for l in ag_lines), "no int8 all-gather in HLO (qwZ dead)"


def test_zeropp_fused_step_matches_imperative(eight_devices):
    """The fused single-jit ZeRO++ step and forward/backward/step agree."""
    mesh = {"fsdp": 4, "dp": 2}
    zeropp = {"zero_quantized_gradients": True, "zero_hpz_partition_size": 2}
    a = ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config(3, mesh, zeropp, ga=2))[0]
    b = ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config(3, mesh, zeropp, ga=2))[0]
    batch = fixed_batch(2 * 2 * a.topology.dp_world_size)  # ga * micro * dp
    half = {k: v[:v.shape[0] // 2] for k, v in batch.items()}
    half2 = {k: v[v.shape[0] // 2:] for k, v in batch.items()}
    for _ in range(3):
        a.fused_train_step(batch)
        for mb in (half, half2):
            loss = b.forward(mb)
            b.backward(loss)
        b.step()
    assert a.global_steps == b.global_steps == 3
    # NOT bit-identical by design: the imperative path quantize-reduces each
    # microbatch (ga=1 per fwd_bwd) while the fused path reduces the ga-sum
    # once — the difference is bounded by int8 quantization noise.
    pa = jax.tree_util.tree_leaves(a.params)
    pb = jax.tree_util.tree_leaves(b.params)
    for x, y in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-2)


def test_mics_mesh_validation(eight_devices):
    """MiCS keys are validated against the mesh, not silently ignored."""
    ok = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "mics_shard_size": 4},
        "mesh": {"fsdp": 4, "dp": 2}, "steps_per_print": 100})[0]
    assert ok.topology.size("fsdp") == 4
    with pytest.raises(ValueError, match="mics_shard_size"):
        ds.initialize(model=TransformerLM(get_preset("tiny")), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "mics_shard_size": 4},
            "mesh": {"fsdp": 8}, "steps_per_print": 100})
    with pytest.raises(ValueError, match="hierarchical"):
        ds.initialize(model=TransformerLM(get_preset("tiny")), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "mics_shard_size": 4,
                                  "mics_hierarchical_params_gather": True},
            "mesh": {"fsdp": 4, "dp": 2}, "steps_per_print": 100})


# ---------------------------------------------------------------------------
# the zero_pp config block (qwZ/hpZ/qgZ independently toggleable, bits,
# block size, cross-slice-only) and its wiring into the plan
# ---------------------------------------------------------------------------

def test_zero_pp_block_builds_plan(eight_devices):
    """The validated block spelling engages the explicit region with the
    configured bits/block size; enabled-with-no-features is the dense
    baseline plan (still explicit, still logged, nothing quantized)."""
    eng = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, {"fsdp": 4, "dp": 2},
                           {"zero_pp": {"enabled": True, "qwz": True,
                                        "qgz": True, "weight_bits": 4,
                                        "grad_bits": 8,
                                        "block_size": 512}}))[0]
    f = eng._zpp.features
    assert f["qwz"] and f["qgz"] and not f["hpz"]
    assert f["weight_bits"] == 4 and f["grad_bits"] == 8
    assert f["block_size"] == 512
    dense = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, {"fsdp": 4, "dp": 2},
                           {"zero_pp": {"enabled": True}}))[0]
    assert dense._zpp is not None
    assert not any(dense._zpp.features[k] for k in ("qwz", "qgz", "hpz"))
    got = run_steps(dense, 2)
    assert got[-1] < got[0]


def test_zero_pp_legacy_knobs_fold_into_block():
    from deepspeed_tpu.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(
        train_micro_batch_size_per_gpu=1,
        zero_optimization={"stage": 3, "zero_quantized_weights": True,
                           "zero_hpz_partition_size": 2})
    zpp = cfg.zero_optimization.zero_pp
    assert zpp.enabled and zpp.qwz and zpp.hpz and not zpp.qgz
    assert zpp.hpz_partition_size == 2


def test_zero_pp_conflicting_spellings_rejected():
    from deepspeed_tpu.config import DeepSpeedTpuConfig

    with pytest.raises(Exception, match="one spelling"):
        DeepSpeedTpuConfig(
            train_micro_batch_size_per_gpu=1,
            zero_optimization={"stage": 3,
                               "zero_quantized_gradients": True,
                               "zero_pp": {"enabled": True, "qwz": True}})


def test_zero_pp_validation():
    from deepspeed_tpu.config.config import ZeroPPConfig

    with pytest.raises(Exception, match="weight_bits"):
        ZeroPPConfig(weight_bits=5)
    with pytest.raises(Exception, match="grad_bits"):
        ZeroPPConfig(grad_bits=16)
    with pytest.raises(Exception, match="block_size"):
        ZeroPPConfig(block_size=0)


def test_two_hop_qgz_loss_parity_and_layout(eight_devices):
    """qgZ over a simulated 4x2 sliced mesh: intra-slice bf16 +
    inter-slice quantized matches the dense baseline, and the gradients
    land in the SAME shard layout (params converge identically enough to
    keep training)."""
    mesh = {"fsdp": 8}
    base = ds.initialize(model=TransformerLM(get_preset("tiny")),
                         config=make_config(3, mesh))[0]
    two = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, mesh,
                           {"zero_pp": {"enabled": True, "qgz": True,
                                        "slice_size": 2}}))[0]
    assert two._zpp.features["two_hop"]
    ref = run_steps(base, 4)
    got = run_steps(two, 4)
    assert got[-1] < got[0]
    np.testing.assert_allclose(got, ref, rtol=0.05)


def test_qwz_cross_slice_only_two_hop_gather(eight_devices):
    """qwZ with cross_slice_only on a simulated 4x2 sliced mesh: only the
    DCN hop of the param gather quantizes (int4), the ICI hop stays
    dense — training still tracks the dense baseline."""
    mesh = {"fsdp": 8}
    base = ds.initialize(model=TransformerLM(get_preset("tiny")),
                         config=make_config(3, mesh))[0]
    eng = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, mesh,
                           {"zero_pp": {"enabled": True, "qwz": True,
                                        "weight_bits": 4, "slice_size": 2,
                                        "cross_slice_only": True}}))[0]
    assert eng._zpp.features["cross_slice_only"]
    ref = run_steps(base, 4)
    got = run_steps(eng, 4)
    assert got[-1] < got[0]
    np.testing.assert_allclose(got, ref, rtol=0.05)


def test_slice_size_must_tile_the_axis(eight_devices):
    """An explicit slice_size that cannot tile the fsdp axis is a LOUD
    error — clamping would silently disable the two-hop split."""
    for bad in (3, 16):
        with pytest.raises(ValueError, match="slice_size"):
            ds.initialize(
                model=TransformerLM(get_preset("tiny")),
                config=make_config(3, {"fsdp": 8},
                                   {"zero_pp": {"enabled": True,
                                                "qgz": True,
                                                "slice_size": bad}}))


def test_hpz_single_slice_graceful_fallback(eight_devices):
    """hpz=True with a slice-local default partition on a single-slice
    mesh: the secondary would coincide with the primary — the plan must
    disable it (fall back), not crash or build a pointless copy."""
    eng = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config=make_config(3, {"fsdp": 8},
                           {"zero_pp": {"enabled": True, "hpz": True}}))[0]
    assert eng._zpp is not None and not eng._zpp.uses_secondary
    assert not eng._zpp.features["hpz"]


def test_quant_instruments_in_registry(eight_devices):
    """train/quant_comm_ms + the qwZ/qgZ quant-error gauges land in the
    observability registry and carry real samples after a print-cadence
    step."""
    from deepspeed_tpu.observability import get_registry

    cfg = make_config(3, {"fsdp": 4, "dp": 2},
                      {"zero_pp": {"enabled": True, "qwz": True,
                                   "qgz": True,
                                   "hpz": True, "hpz_partition_size": 2}})
    cfg["steps_per_print"] = 1
    cfg["observability"] = {"enabled": True}
    eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=cfg)[0]
    run_steps(eng, 2)
    names = {f.name for f in get_registry().collect()}
    for want in ("train/quant_comm_ms", "train/qwz_quant_error",
                 "train/qgz_quant_error"):
        assert want in names, want
    with jax.sharding.set_mesh(eng.mesh):
        err = eng._zpp.quant_error_fns["qwz"](eng.params)
    assert 0.0 < float(err) < 0.2   # int8 blockwise error is small, not 0


def test_int4_weight_gather_on_the_wire(eight_devices):
    """weight_bits=4: the compiled step still carries s8 all-gather
    payloads (packed nibbles ride int8 lanes) at HALF the int8 element
    count — the 4x-over-bf16 wire saving qwZ int4 claims."""
    import re

    def lowered(bits):
        eng = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(3, {"fsdp": 8},
                               {"zero_pp": {"enabled": True, "qwz": True,
                                            "weight_bits": bits}}))[0]
        batch = eng._put_batch(fixed_batch(2 * eng.topology.dp_world_size))
        with jax.sharding.set_mesh(eng.mesh):
            return eng._fwd_bwd.lower(
                eng.params, batch,
                eng.scaler_state["scale"]).compile().as_text()

    def s8_gather_elems(hlo):
        total = 0
        for line in hlo.splitlines():
            if "all-gather" not in line:
                continue
            m = re.search(r"= s8\[([0-9,]+)\]", line)
            if m:
                import numpy as _np

                total += int(_np.prod([int(v) for v in
                                       m.group(1).split(",")]))
        return total

    e8 = s8_gather_elems(lowered(8))
    e4 = s8_gather_elems(lowered(4))
    assert e8 > 0 and e4 > 0, "no s8 all-gather payload in HLO"
    assert e4 <= e8 // 2 + 8, (e4, e8)   # packed nibbles: half the bytes


# ---------------------------------------------------------------------------
# drill wrappers (slow; tools/comm_drill.py is the invariant authority)
# ---------------------------------------------------------------------------

@pytest.mark.zpp
@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["bytes", "parity", "two-hop"])
def test_comm_drill(scenario, eight_devices):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "..", "..", "tools"))
    from comm_drill import run_scenario

    verdict = run_scenario(scenario)
    assert verdict["ok"], verdict


def test_zero3_schedule_carries_gather_and_scatter(eight_devices):
    """Round-2 weak #3 (partial): the compiled ZeRO-3 step must contain the
    parameter all-gathers and gradient reduce-scatters that replace the
    reference's prefetch coordinator + IPG buckets. (XLA:CPU lowers them
    synchronously; on TPU/GPU the scheduler emits the async start/done form
    and overlaps them with compute — a backend property, not a program
    one.)"""
    import re

    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
        "mesh": {"fsdp": 8}, "steps_per_print": 100})
    b = eng._put_batch({"input_ids": np.zeros((16, 16), np.int32)})
    with jax.sharding.set_mesh(eng.mesh):
        txt = eng._fwd_bwd.lower(eng.params, b,
                                 eng.scaler_state["scale"]).compile().as_text()
    assert "all-gather" in txt, "ZeRO-3 step compiled without all-gathers"
    # grad partitioning: reduce-scatter proper, or XLA:CPU's all-reduce +
    # dynamic-slice lowering of it — a NON-scalar all-reduce (the scalar
    # mean-loss reduction alone must not satisfy this)
    has_rs = "reduce-scatter" in txt
    has_tensor_ar = bool(re.search(
        r"= *[a-z0-9]+\[[0-9][0-9,]*\][^=\n]*all-reduce", txt))
    assert has_rs or has_tensor_ar, \
        "no grad reduce-scatter (nor tensor all-reduce lowering) in the step"
