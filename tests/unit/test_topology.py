"""Mesh topology tests (parity model: tests/unit/utils/test_groups.py)."""

import pytest

from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.parallel import MESH_AXES, build_mesh


def test_default_mesh_all_dp(eight_devices):
    topo = build_mesh()
    assert topo.world_size == 8
    assert topo.axis_sizes["dp"] == 8
    assert topo.dp_world_size == 8
    assert tuple(topo.mesh.axis_names) == MESH_AXES


def test_mixed_axes(eight_devices):
    topo = build_mesh(MeshConfig(tp=2, fsdp=2))
    assert topo.axis_sizes == {"pp": 1, "dp": 2, "fsdp": 2, "ep": 1, "sp": 1, "tp": 2}
    assert topo.dp_world_size == 4
    assert topo.size("tp") == 2


def test_axis_sizes_override(eight_devices):
    topo = build_mesh(axis_sizes={"fsdp": 8})
    assert topo.axis_sizes["fsdp"] == 8
    assert topo.axis_sizes["dp"] == 1


def test_indivisible_raises(eight_devices):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(tp=3))


def test_explicit_dp_mismatch(eight_devices):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=3, tp=2))
