"""Mesh cost model + autotuner v2 + scaling-harness tests.

Fast tier: enumeration legality/determinism, calibration round-trip,
winner-store semantics, the ``mesh: "auto"`` config path, the Autotuner
engine-lifecycle regression, and the ``bench_scaling`` /
``bench_capacity`` trend series. The ``scaling``+``slow`` wrapper runs a
real tiny 2-world sweep through the harness (the drill CLI
``tools/scaling_drill.py`` is the full-loop authority).
"""

import json
import os
import sys

import numpy as np
import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def _profile(**over):
    from deepspeed_tpu.parallel.cost_model import ModelProfile

    base = dict(n_params=148032, n_layers=2, n_heads=8, n_kv_heads=8,
                hidden=64, vocab=256, seq=64, n_experts=1, top_k=2,
                sp_capable=False)
    base.update(over)
    return ModelProfile(**base)


# ---------------------------------------------------------------------------
# mesh enumeration
# ---------------------------------------------------------------------------
class TestMeshEnumeration:
    def test_factorizations_are_exact_and_legal(self):
        from deepspeed_tpu.parallel.cost_model import enumerate_meshes

        p = _profile()
        for world in (1, 2, 4, 8, 12):
            for m in enumerate_meshes(world, p):
                assert int(np.prod(list(m.values()) or [1])) == world, m
                assert all(v > 1 for v in m.values()), m  # size-1 axes omitted

    def test_divisibility_pruning(self):
        from deepspeed_tpu.parallel.cost_model import enumerate_meshes

        # 8 heads, 2 layers, dense, no sp: tp>8 / pp>2 / ep / sp never appear
        p = _profile()
        meshes = enumerate_meshes(8, p)
        assert {"tp": 8} in meshes and {"fsdp": 8} in meshes
        assert all(m.get("pp", 1) <= 2 for m in meshes)
        assert all("ep" not in m and "sp" not in m for m in meshes)

        # 6 heads: tp must divide 6 AND the device count → tp in {2} at w=8
        p6 = _profile(n_heads=6, n_kv_heads=6, hidden=96)
        assert all(m.get("tp", 1) in (1, 2)
                   for m in enumerate_meshes(8, p6))

        # moe: ep divides the expert count only
        pm = _profile(n_experts=4)
        assert any(m.get("ep") == 4 for m in enumerate_meshes(8, pm))
        assert all(m.get("ep", 1) <= 4 for m in enumerate_meshes(8, pm))

        # sp only for sp-capable models, and it must divide seq and heads
        ps = _profile(sp_capable=True)
        with_sp = [m for m in enumerate_meshes(8, ps) if "sp" in m]
        assert with_sp and all(ps.seq % m["sp"] == 0
                               and ps.n_heads % m["sp"] == 0
                               for m in with_sp)

    def test_deterministic_ordering(self):
        from deepspeed_tpu.parallel.cost_model import enumerate_meshes

        p = _profile(sp_capable=True, n_experts=4)
        a = enumerate_meshes(8, p)
        b = enumerate_meshes(8, p)
        assert a == b
        # canonical MESH_AXES-order sort: stable across processes/hosts
        keys = [tuple(m.get(ax, 1) for ax in
                      ("pp", "dp", "fsdp", "ep", "sp", "tp")) for m in a]
        assert keys == sorted(keys)

    def test_axes_restriction(self):
        from deepspeed_tpu.parallel.cost_model import enumerate_meshes

        p = _profile()
        only = enumerate_meshes(8, p, axes=("dp", "fsdp"))
        assert {"dp": 8} in only and {"fsdp": 8} in only
        assert all(set(m) <= {"dp", "fsdp"} for m in only)


# ---------------------------------------------------------------------------
# cost model: prediction + calibration round-trip
# ---------------------------------------------------------------------------
class TestCostModel:
    def test_volumes_shape_sensitivity(self):
        from deepspeed_tpu.parallel.cost_model import collective_volumes

        p = _profile()
        dp = collective_volumes(p, {"dp": 8}, tokens=1024)
        fsdp = collective_volumes(p, {"fsdp": 8}, zero_stage=3, tokens=1024)
        tp = collective_volumes(p, {"tp": 8}, tokens=128)
        assert dp["ici_bytes"] > 0 and fsdp["ici_bytes"] > 0
        # stage-3 fsdp pays the param gather on top of the grad scatter
        fsdp1 = collective_volumes(p, {"fsdp": 8}, zero_stage=1, tokens=1024)
        assert fsdp["ici_bytes"] > fsdp1["ici_bytes"]
        # tp moves per-layer activations; flops split over the tp group
        assert tp["flops"] == pytest.approx(dp["flops"] * 128 / 1024)
        # pipeline bubble follows (p-1)/(m+p-1)
        pp = collective_volumes(p, {"pp": 2, "fsdp": 4}, zero_stage=3,
                                tokens=1024, micro_batches=2)
        assert pp["bubble_frac"] == pytest.approx(1 / 3)

    def test_quantized_wire_shrinks_fsdp_bytes(self):
        from deepspeed_tpu.parallel.cost_model import collective_volumes

        p = _profile()
        dense = collective_volumes(p, {"fsdp": 8}, zero_stage=3, tokens=512)
        quant = collective_volumes(
            p, {"fsdp": 8}, zero_stage=3, tokens=512,
            zero_pp={"enabled": True, "qwz": True, "qgz": True,
                     "weight_bits": 4, "grad_bits": 8})
        assert quant["ici_bytes"] < 0.5 * dense["ici_bytes"]

    def test_dcn_link_class_from_ici_sizes(self):
        from deepspeed_tpu.parallel.cost_model import collective_volumes

        p = _profile()
        flat = collective_volumes(p, {"fsdp": 8}, zero_stage=3, tokens=512)
        sliced = collective_volumes(p, {"fsdp": 8}, zero_stage=3, tokens=512,
                                    ici_sizes={"fsdp": 4})
        assert flat["dcn_bytes"] == 0
        assert sliced["dcn_bytes"] == flat["ici_bytes"]
        assert sliced["ici_bytes"] == 0

    def test_calibration_round_trip(self):
        """Fit on synthetic curves generated from known link rates →
        recover the rates (the satellite acceptance check)."""
        from deepspeed_tpu.parallel.cost_model import (CostModel,
                                                       LinkBandwidths,
                                                       enumerate_meshes,
                                                       fit_bandwidths)

        p = _profile(sp_capable=True)
        true = LinkBandwidths(flops_per_s=2e11, ici_bytes_per_s=5e9,
                              dcn_bytes_per_s=1e9, overhead_s=2e-3)
        gen = CostModel(true)
        samples = []
        for w in (1, 2, 4, 8):
            for m in enumerate_meshes(w, p):
                # the harness batch law: tokens scale with the dp axes
                tokens = 128 * m.get("dp", 1) * m.get("fsdp", 1)
                for ici in (None, {"fsdp": max(1, m.get("fsdp", 1) // 2)}):
                    pred = gen.predict(p, m, zero_stage=3, tokens=tokens,
                                       ici_sizes=ici)
                    samples.append({
                        "step_s": pred["step_s"], "flops": pred["flops"],
                        "ici_bytes": pred["ici_bytes"],
                        "dcn_bytes": pred["dcn_bytes"],
                        "bubble_frac": pred["bubble_frac"]})
        fit = fit_bandwidths(samples)
        assert fit.calibrated_from == len(samples)
        assert fit.flops_per_s == pytest.approx(true.flops_per_s, rel=0.05)
        assert fit.ici_bytes_per_s == pytest.approx(true.ici_bytes_per_s,
                                                    rel=0.05)
        assert fit.dcn_bytes_per_s == pytest.approx(true.dcn_bytes_per_s,
                                                    rel=0.05)
        assert fit.overhead_s == pytest.approx(true.overhead_s, rel=0.05)

    def test_calibration_degrades_gracefully(self):
        from deepspeed_tpu.parallel.cost_model import (LinkBandwidths,
                                                       fit_bandwidths)

        prior = LinkBandwidths()
        # too little data → the prior comes back untouched
        assert fit_bandwidths([]) == prior
        assert fit_bandwidths([{"step_s": 1.0, "flops": 1.0}]) == prior
        # no DCN variation → DCN keeps the prior, never a fitted zero
        fit = fit_bandwidths([
            {"step_s": 0.1, "flops": 1e10, "ici_bytes": 1e8,
             "dcn_bytes": 0.0, "bubble_frac": 0.0},
            {"step_s": 0.2, "flops": 2e10, "ici_bytes": 3e8,
             "dcn_bytes": 0.0, "bubble_frac": 0.0},
            {"step_s": 0.4, "flops": 4e10, "ici_bytes": 9e8,
             "dcn_bytes": 0.0, "bubble_frac": 0.0},
        ])
        assert fit.dcn_bytes_per_s == prior.dcn_bytes_per_s
        assert fit.ici_bytes_per_s > 0 and fit.flops_per_s > 0

    def test_throughput_ranking_amortizes_overhead(self):
        """Per-step overhead hits a 1-token shape harder than a dp shape
        that amortizes it over 8x tokens — ranking must be by tokens/s,
        not raw step time."""
        from deepspeed_tpu.parallel.cost_model import (CostModel,
                                                       LinkBandwidths)

        p = _profile()
        cm = CostModel(LinkBandwidths(flops_per_s=1e12,
                                      ici_bytes_per_s=1e11,
                                      overhead_s=5e-3))
        tp = cm.predict_throughput(p, {"tp": 8}, micro_batch=2)
        dp = cm.predict_throughput(p, {"dp": 8}, micro_batch=2)
        assert tp["step_s"] < dp["step_s"]          # fewer tokens per step
        assert dp["tokens_per_sec"] > tp["tokens_per_sec"]
        ranked = cm.rank_by_throughput(p, [{"tp": 8}, {"dp": 8}],
                                       micro_batch=2)
        assert ranked[0][0] == {"dp": 8}


# ---------------------------------------------------------------------------
# winner store + mesh:"auto" resolution
# ---------------------------------------------------------------------------
class TestWinnerStore:
    def test_round_trip_and_atomicity(self, tmp_path):
        from deepspeed_tpu.autotuning.mesh_store import WinnerStore

        store = WinnerStore(str(tmp_path / "w.json"))
        assert store.get("sig", 8, "cpu") is None
        store.put("sig", 8, "cpu", {"fsdp": 4, "dp": 2, "tp": 1}, 99.5)
        rec = store.get("sig", 8, "cpu")
        assert rec["mesh"] == {"fsdp": 4, "dp": 2}   # size-1 axes dropped
        assert rec["metric"] == 99.5
        # other keys stay distinct — including the zero stage: a shape
        # tuned under stage-3 fsdp gathers must not leak into stage 0
        assert store.get("sig", 4, "cpu") is None
        assert store.get("sig", 8, "tpu v5e") is None
        assert store.get("sig", 8, "cpu", zero_stage=3) is None
        # corrupt store file → treated as empty, not a crash
        (tmp_path / "w.json").write_text("{not json")
        assert store.get("sig", 8, "cpu") is None
        store.put("sig", 8, "cpu", {"tp": 2}, 1.0)
        assert store.get("sig", 8, "cpu")["mesh"] == {"tp": 2}

    def test_resolution_ladder(self, tmp_path, eight_devices):
        from deepspeed_tpu.autotuning.mesh_store import (
            WinnerStore, device_kind, resolve_auto_axis_sizes)
        from deepspeed_tpu.parallel.cost_model import model_signature

        path = str(tmp_path / "w.json")
        p = _profile()
        # miss → cost-model prediction (a legal factorization of 8)
        got = resolve_auto_axis_sizes(8, p, winner_cache=path)
        assert int(np.prod(list(got.values()))) == 8
        # hit → the measured winner verbatim
        WinnerStore(path).put(model_signature(p), 8, device_kind(),
                              {"fsdp": 8}, 50.0)
        assert resolve_auto_axis_sizes(8, p, winner_cache=path) == \
            {"fsdp": 8}
        # no profile → all-dp fallback
        assert resolve_auto_axis_sizes(8, None, winner_cache=path) == \
            {"dp": 8}
        assert resolve_auto_axis_sizes(1, p) == {"dp": 1}


class TestMeshAutoConfig:
    def test_mesh_auto_spelling(self):
        from deepspeed_tpu.config import from_config

        cfg = from_config({"train_micro_batch_size_per_gpu": 1,
                           "mesh": "auto"})
        assert cfg.mesh.auto is True
        cfg2 = from_config({"train_micro_batch_size_per_gpu": 1,
                            "mesh": {"auto": True}})
        assert cfg2.mesh.auto is True
        assert from_config({"train_micro_batch_size_per_gpu": 1}) \
            .mesh.auto is False

    def test_auto_rejects_explicit_sizes(self):
        from deepspeed_tpu.config import from_config

        with pytest.raises(Exception, match="mutually exclusive"):
            from_config({"train_micro_batch_size_per_gpu": 1,
                         "mesh": {"auto": True, "fsdp": 4}})

    def test_auto_rejects_multi_slice(self):
        # auto resolution returns flat axis sizes; silently dropping the
        # DCN slice factoring must be a loud error, not a slow run
        from deepspeed_tpu.config import from_config

        with pytest.raises(Exception, match="multi-slice"):
            from_config({"train_micro_batch_size_per_gpu": 1,
                         "mesh": {"auto": True, "num_slices": 2}})

    def test_autotuning_section_validation(self):
        from deepspeed_tpu.config import from_config

        cfg = from_config({"train_micro_batch_size_per_gpu": 1,
                           "autotuning": {"top_k": 3,
                                          "winner_cache": "/tmp/x.json"}})
        assert cfg.autotuning.top_k == 3
        with pytest.raises(Exception):
            from_config({"train_micro_batch_size_per_gpu": 1,
                         "autotuning": {"top_k": 0}})
        with pytest.raises(Exception):
            from_config({"train_micro_batch_size_per_gpu": 1,
                         "autotuning": {"mesh_axes": ["dp", "bogus"]}})

    def test_engine_adopts_winner(self, tmp_path, eight_devices):
        """mesh:'auto' + a persisted winner → the engine builds that mesh
        (the build_mesh wiring, end to end on a real engine)."""
        import deepspeed_tpu as ds
        from deepspeed_tpu.autotuning.mesh_store import (WinnerStore,
                                                         device_kind)
        from deepspeed_tpu.models import TransformerLM, get_preset
        from deepspeed_tpu.parallel.cost_model import (ModelProfile,
                                                       model_signature)

        path = str(tmp_path / "w.json")
        model = TransformerLM(get_preset("tiny"))
        sig = model_signature(ModelProfile.from_model(model))
        WinnerStore(path).put(sig, 8, device_kind(), {"fsdp": 4, "dp": 2},
                              10.0, zero_stage=3)
        eng = None
        try:
            eng, *_ = ds.initialize(model=model, config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "param_persistence_threshold": 0},
                "mesh": "auto", "autotuning": {"winner_cache": path},
                "steps_per_print": 10 ** 9})
            assert eng.topology.axis_sizes["fsdp"] == 4
            assert eng.topology.axis_sizes["dp"] == 2
            loss = eng.fused_train_step(
                {"input_ids": np.zeros((8, 16), np.int32)})
            assert np.isfinite(float(loss))
        finally:
            if eng is not None:
                eng.shutdown()


# ---------------------------------------------------------------------------
# autotuner v2: engine lifecycle + mesh axis
# ---------------------------------------------------------------------------
class _FakeLoss:
    def block_until_ready(self):
        return self


class _FakeEngine:
    def __init__(self, fail, shutdowns):
        self._fail = fail
        self._shutdowns = shutdowns

    @property
    def topology(self):
        return type("T", (), {"dp_world_size": 1})()

    def fused_train_step(self, batch):
        if self._fail:
            raise RuntimeError("simulated OOM")
        return _FakeLoss()

    def train_batch_size(self):
        return 2

    def shutdown(self):
        self._shutdowns.append(self._fail)


class TestAutotunerLifecycle:
    def test_every_trial_engine_is_shut_down(self, monkeypatch):
        """Regression: _run_trial leaked engines on BOTH paths — worker
        threads and buffers accumulated across grid trials and skewed
        later timings. Success and failure must both shut down."""
        import deepspeed_tpu as ds
        from deepspeed_tpu.autotuning import Autotuner

        shutdowns = []
        calls = {"n": 0}

        def fake_initialize(model=None, config=None, **kw):
            calls["n"] += 1
            # second trial's step fails (stage 1 in the grid below)
            return (_FakeEngine(fail=config["zero_optimization"]["stage"] == 1,
                                shutdowns=shutdowns), None, None, None)

        monkeypatch.setattr(ds, "initialize", fake_initialize)
        tuner = Autotuner(lambda: object(), {},
                          micro_batch_candidates=(2,),
                          zero_stage_candidates=(0, 1), steps=1,
                          make_batch=lambda n: {"x": np.zeros((n, 4))})
        best = tuner.tune()
        assert best is not None and best.ok
        assert calls["n"] == 2
        # one shutdown per built engine, including the failed trial
        assert sorted(shutdowns) == [False, True]
        failed = [r for r in tuner.results if not r.ok]
        assert len(failed) == 1 and "simulated OOM" in failed[0].error

    def test_mesh_axis_rides_the_grid(self, monkeypatch):
        import deepspeed_tpu as ds
        from deepspeed_tpu.autotuning import Autotuner

        seen = []

        def fake_initialize(model=None, config=None, **kw):
            seen.append(config.get("mesh"))
            return (_FakeEngine(False, []), None, None, None)

        monkeypatch.setattr(ds, "initialize", fake_initialize)
        tuner = Autotuner(lambda: object(), {},
                          micro_batch_candidates=(1,),
                          zero_stage_candidates=(3,),
                          mesh_candidates=[{"fsdp": 8}, {"dp": 8}], steps=1,
                          make_batch=lambda n: {"x": np.zeros((n, 4))})
        best = tuner.tune()
        assert best is not None and best.config["mesh"] in (
            {"fsdp": 8}, {"dp": 8})
        assert seen == [{"fsdp": 8}, {"dp": 8}]

    def test_search_shape_defaults_from_autotuning_config(self):
        from deepspeed_tpu.autotuning import Autotuner

        tuner = Autotuner(lambda: object(), {
            "autotuning": {"top_k": 5, "measure_steps": 7,
                           "mesh_axes": ["dp", "tp"],
                           "winner_cache": "/tmp/wc.json"}},
            make_batch=lambda n: None)
        assert tuner.mesh_top_k == 5 and tuner.steps == 7
        assert tuner.mesh_axes == ("dp", "tp")
        assert tuner._winner_cache == "/tmp/wc.json"
        # explicit constructor args still win
        t2 = Autotuner(lambda: object(),
                       {"autotuning": {"top_k": 5, "measure_steps": 7}},
                       mesh_top_k=1, steps=2, make_batch=lambda n: None)
        assert t2.mesh_top_k == 1 and t2.steps == 2

    def test_winner_persisted_for_mesh_trials(self, monkeypatch, tmp_path):
        import deepspeed_tpu as ds
        from deepspeed_tpu.autotuning import Autotuner, WinnerStore
        from deepspeed_tpu.models import TransformerLM, get_preset

        def fake_initialize(model=None, config=None, **kw):
            return (_FakeEngine(False, []), None, None, None)

        monkeypatch.setattr(ds, "initialize", fake_initialize)
        store = WinnerStore(str(tmp_path / "w.json"))
        tuner = Autotuner(lambda **kw: TransformerLM(get_preset("tiny")),
                          {}, micro_batch_candidates=(1,),
                          zero_stage_candidates=(3,),
                          mesh_candidates=[{"fsdp": 8}], steps=1,
                          winner_store=store,
                          make_batch=lambda n: {"x": np.zeros((n, 4))})
        best = tuner.tune()
        assert best is not None
        data = json.loads((tmp_path / "w.json").read_text())
        recs = list(data["winners"].values())
        assert len(recs) == 1 and recs[0]["mesh"] == {"fsdp": 8}


# ---------------------------------------------------------------------------
# scheduler best-config write-back (satellite coverage)
# ---------------------------------------------------------------------------
class TestSchedulerWriteback:
    def test_best_file_schema_and_failed_runs_excluded(self, tmp_path):
        from deepspeed_tpu.autotuning import ExperimentScheduler

        def runner(exp, exp_dir):
            if exp.config["mesh"] == {"tp": 8}:
                raise RuntimeError("compile failed")
            return 10.0 * exp.config["mesh"].get("fsdp", 1)

        sched = ExperimentScheduler(
            [{"mesh": {"fsdp": 8}}, {"mesh": {"tp": 8}},
             {"mesh": {"dp": 8}}],
            hosts=["h0"], results_dir=str(tmp_path), runner=runner)
        best = sched.run()
        assert best is not None and best.config == {"mesh": {"fsdp": 8}}
        with open(tmp_path / "best_config.json") as f:
            doc = json.load(f)
        assert doc["config"] == {"mesh": {"fsdp": 8}}
        assert doc["metric"] == 80.0 and doc["exp_id"] == best.exp_id

    def test_no_writeback_when_everything_fails(self, tmp_path):
        from deepspeed_tpu.autotuning import ExperimentScheduler

        def runner(exp, exp_dir):
            raise RuntimeError("boom")

        sched = ExperimentScheduler([{"i": 0}, {"i": 1}], hosts=["h0"],
                                    results_dir=str(tmp_path), runner=runner)
        assert sched.run() is None
        assert not (tmp_path / "best_config.json").exists()


# ---------------------------------------------------------------------------
# trend gate: the bench_scaling + per-device capacity series
# ---------------------------------------------------------------------------
class TestScalingTrendSeries:
    def _scaling_entry(self, sha, curves, device="cpu"):
        return {"schema": 1, "bench": "bench_scaling", "git_sha": sha,
                "time": 1, "iso_time": "x",
                "metric": "scaling_tokens_per_sec_per_chip", "value": None,
                "unit": "tokens/s/chip",
                "result": {"device": device, "curves": {device: {
                    shape: {w: {"tokens_per_sec_per_chip": tps,
                                "parallel_efficiency": eff}
                            for w, (tps, eff) in pts.items()}
                    for shape, pts in curves.items()}}}}

    def test_per_shape_world_series_gate(self):
        from bench_trend import compare

        a = self._scaling_entry("a", {
            "fsdp": {"w2": (100.0, 0.9), "w8": (80.0, 0.7)},
            "dp": {"w2": (110.0, 1.0)}})
        # fsdp@w8 regresses 40%; dp@w2 holds; fsdp@w2 unmeasured → no gate
        b = self._scaling_entry("b", {
            "fsdp": {"w8": (48.0, 0.42)},
            "dp": {"w2": (108.0, 0.99)}})
        v = compare([a, b], threshold=0.15)
        regressed = {r["metric"] for r in v["regressions"]}
        assert "curves.cpu.fsdp.w8.tokens_per_sec_per_chip" in regressed
        assert "curves.cpu.fsdp.w8.parallel_efficiency" in regressed
        assert not any("fsdp.w2" in m for m in regressed)
        assert not any(".dp." in m for m in regressed)
        assert not v["ok"]

    def test_scaling_series_is_per_device(self):
        # a fast TPU sweep entry must not become the "best prior" a
        # CPU-harness run gates against (same split as capacity)
        from bench_trend import compare

        cpu = self._scaling_entry("c1", {"dp": {"w8": (150.0, 0.8)}})
        tpu = self._scaling_entry(
            "t1", {"dp": {"w8": (24000.0, 0.9)}}, device="TPU v5e")
        cpu2 = self._scaling_entry("c2", {"dp": {"w8": (145.0, 0.78)}})
        assert compare([cpu, tpu, cpu2], threshold=0.15)["ok"]
        # a genuine same-device drop still gates
        cpu3 = self._scaling_entry("c3", {"dp": {"w8": (60.0, 0.3)}})
        assert not compare([cpu, tpu, cpu2, cpu3], threshold=0.15)["ok"]

    def test_ledger_samples_include_baselines_and_filter_device(self):
        from deepspeed_tpu.parallel.cost_model import samples_from_ledger

        pt = {"step_ms": 100.0, "predicted": {"flops": 1e9,
                                              "ici_bytes": 1e6,
                                              "dcn_bytes": 0,
                                              "bubble_frac": 0.0}}
        def entry(device):
            return {"schema": 1, "bench": "bench_scaling",
                    "result": {"device": device,
                               "curves": {device: {"fsdp":
                                                   {"w2": dict(pt)}}},
                               "baselines": {"dense": dict(pt)}}}
        # the zero-comm w=1 baselines anchor the flops/overhead split —
        # the ledger-backed refit must see the same points the sweep's
        # own in-process calibration used
        assert len(samples_from_ledger([entry("cpu")])) == 2
        # and the fit never mixes device kinds: CPU and TPU rates are
        # orders of magnitude apart — one fit over both fits neither
        both = [entry("cpu"), entry("TPU v5e")]
        assert len(samples_from_ledger(both, device="cpu")) == 2
        assert len(samples_from_ledger(both)) == 4

    def test_capacity_series_is_per_device(self):
        from bench_trend import compare

        old = {"schema": 1, "bench": "bench_capacity", "git_sha": "tpu",
               "time": 1, "iso_time": "x", "metric": "m", "value": None,
               "unit": None, "result": {"best": {"params_b": 0.81}}}
        dev = {"schema": 1, "bench": "bench_capacity", "git_sha": "cpu",
               "time": 2, "iso_time": "x", "metric": "m", "value": None,
               "unit": None,
               "result": {"best": {"params_b": 0.05},
                          "by_device": {"cpu": {"dev":
                                                {"params_b": 0.05}}}}}
        # a CPU dev-ladder restatement after a TPU figure is a NEW series,
        # not a 94% regression of the old one
        v = compare([old, dev], threshold=0.15)
        assert v["ok"], v
        # the dev ladder tops out lower than the full ladder even on one
        # device — a full-ladder figure must not gate a dev-ladder run
        full = json.loads(json.dumps(dev))
        full["git_sha"] = "cpu-full"
        full["result"]["by_device"]["cpu"] = {"full": {"params_b": 0.8}}
        assert compare([old, full, dev], threshold=0.15)["ok"]
        # but a genuine drop within the same (device, ladder) still gates
        dev2 = json.loads(json.dumps(dev))
        dev2["git_sha"] = "cpu2"
        dev2["result"]["by_device"]["cpu"]["dev"]["params_b"] = 0.01
        v2 = compare([old, dev, dev2], threshold=0.15)
        assert not v2["ok"]
        assert v2["regressions"][0]["metric"] == \
            "by_device.cpu.dev.params_b"


# ---------------------------------------------------------------------------
# the real thing (slow): a tiny 2-world sweep through the harness
# ---------------------------------------------------------------------------
@pytest.mark.scaling
@pytest.mark.slow
def test_tiny_two_world_sweep(tmp_path, monkeypatch, eight_devices):
    from bench_ledger import append_ledger, read_ledger
    from bench_trend import compare

    from deepspeed_tpu.autotuning.scaling import run_sweep

    res = run_sweep(worlds=(1, 2), shapes=("dp", "fsdp"), steps=2)
    assert not res["failures"], res["failures"]
    curves = res["curves"][res["device"]]     # device-scoped series
    assert set(curves) == {"dp", "fsdp"}
    for name, pts in curves.items():
        pt = pts["w2"]
        assert pt["tokens_per_sec_per_chip"] > 0
        assert 0 < pt["parallel_efficiency"] < 10
    # the explicit-collective fsdp shape logged real wire bytes
    assert curves["fsdp"]["w2"]["comm_bytes_per_step"].get(
        "reduce_scatter", 0) > 0
    assert res["calibration"]["calibrated_from"] >= 3

    # the entry is ledger-appendable and bench_trend-readable
    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("DSTPU_BENCH_LEDGER_PATH", path)
    assert append_ledger(res, "bench_scaling") == path
    assert append_ledger(res, "bench_scaling") == path
    v = compare(read_ledger(path), threshold=0.15)
    mets = {c["metric"] for c in v["comparisons"]}
    assert f"curves.{res['device']}.dp.w2.tokens_per_sec_per_chip" in mets
    assert v["ok"]


@pytest.mark.scaling
@pytest.mark.slow
def test_drill_store_scenario(eight_devices):
    sys.path.insert(0, TOOLS)
    import scaling_drill

    verdict = scaling_drill.run_scenario("store")
    assert verdict["ok"], verdict
