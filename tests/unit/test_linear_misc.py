"""OptimizedLinear/LoRA, progressive layer drop, eigenvalue, and fp6 tests
(analogs of the reference's ``tests/unit/linear``, PLD schedule tests, and
fp_quantizer tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.linear import (LoRAConfig, OptimizedLinear,
                                  QuantizationConfig, lora_merge,
                                  lora_trainable_mask, lora_wrap_params)
from deepspeed_tpu.ops.quantization import (dequantize_fp6, pack_fp6,
                                            quantize_fp6, unpack_fp6)
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from deepspeed_tpu.runtime.progressive_layer_drop import (ProgressiveLayerDrop,
                                                          layer_keep_probs)


# ---------------------------------------------------------------------------
# LoRA / OptimizedLinear
# ---------------------------------------------------------------------------

def test_optimized_linear_starts_at_base():
    """Zero-init B: the LoRA layer equals the base linear at init."""
    lin = OptimizedLinear(16, 32, LoRAConfig(lora_r=4))
    p = lin.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (3, 16))
    np.testing.assert_allclose(np.asarray(lin.apply(p, x)),
                               np.asarray(x @ p["base"]), atol=1e-6)


def test_optimized_linear_quantized_base():
    lin = OptimizedLinear(64, 32, LoRAConfig(
        lora_r=4, quantization=QuantizationConfig(q_bits=8, group_size=64)))
    p = lin.init(jax.random.key(0))
    assert "base" not in p and p["base_q"].dtype == jnp.int8
    x = jax.random.normal(jax.random.key(1), (3, 64))
    dense = OptimizedLinear(64, 32, LoRAConfig(lora_r=4))
    pd = dense.init(jax.random.key(0))
    # int8 base tracks the dense base within quant tolerance
    np.testing.assert_allclose(np.asarray(lin.apply(p, x)),
                               np.asarray(dense.apply(pd, x)),
                               atol=0.05, rtol=0.05)


def test_lora_wrap_train_merge(eight_devices):
    """The LoRA fine-tuning loop: wrap → train adapters only → merge."""
    import optax

    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.linear.optimized_linear import lora_effective_weight

    model = TransformerLM(get_preset("tiny"))
    params = model.init(jax.random.key(0))
    lora = LoRAConfig(lora_r=4, lora_alpha=8.0)
    wrapped = lora_wrap_params(params, jax.random.key(1), lora)
    assert "lora_a" in wrapped["layers"]["attn"]["wq"]
    # merged(init) == original (B zero-init)
    merged0 = lora_merge(wrapped, lora)
    np.testing.assert_allclose(
        np.asarray(merged0["layers"]["attn"]["wq"]),
        np.asarray(params["layers"]["attn"]["wq"]), atol=1e-6)

    mask = lora_trainable_mask(wrapped)
    tx = optax.multi_transform(
        {"train": optax.adam(1e-2), "freeze": optax.set_to_zero()},
        jax.tree_util.tree_map(lambda m: "train" if m else "freeze", mask))
    opt_state = tx.init(wrapped)

    def loss_fn(w):
        eff = lora_merge(w, lora)
        return model.loss_fn(eff, {"input_ids": np.arange(32).reshape(1, 32)})

    w = wrapped
    losses = []
    for _ in range(5):
        loss, grads = jax.value_and_grad(loss_fn)(w)
        updates, opt_state = tx.update(grads, opt_state, w)
        w = optax.apply_updates(w, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # frozen base untouched; adapters moved
    np.testing.assert_array_equal(
        np.asarray(w["layers"]["attn"]["wq"]["base"]),
        np.asarray(wrapped["layers"]["attn"]["wq"]["base"]))
    assert np.abs(np.asarray(w["layers"]["attn"]["wq"]["lora_b"])).sum() > 0


# ---------------------------------------------------------------------------
# Progressive layer drop
# ---------------------------------------------------------------------------

def test_pld_schedule_matches_reference_formula():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
    assert pld.get_theta() == 1.0
    pld.update_state(0)
    assert pld.get_theta() == pytest.approx(1.0)
    pld.update_state(10_000)
    want = 0.5 * np.exp(-0.001 * 10_000) + 0.5
    assert pld.get_theta() == pytest.approx(want)
    probs = layer_keep_probs(0.5, 4)
    np.testing.assert_allclose(probs, [0.875, 0.75, 0.625, 0.5])


def test_pld_engine_training(eight_devices):
    """PLD under the engine: theta decays across steps, training converges,
    and theta=1 reproduces the dense loss."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.1},
        "steps_per_print": 100})
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 32))}
    thetas, losses = [], []
    for _ in range(5):
        loss = eng.forward(batch)
        thetas.append(eng._pld.get_theta())
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert thetas == sorted(thetas, reverse=True) and thetas[-1] < 1.0
    assert losses[-1] < losses[0]


def test_pld_compiled_tiers_saves_flops(eight_devices):
    """compiled_tiers mode (TPU extension): theta maps to a static depth,
    deeper tiers get DROPPED from the compiled program (the reference's
    actual wall-clock saving) — compiled FLOPs shrink once theta decays,
    training stays finite, and the depth schedule is monotone."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.profiling import profile_fn
    from deepspeed_tpu.runtime.progressive_layer_drop import active_layers

    # schedule sanity: full depth at theta=1, floor at theta_min, monotone
    ks = [active_layers(t, 16, 4, theta_min=0.5)
          for t in (1.0, 0.9, 0.75, 0.6, 0.5)]
    assert ks[0] == 16 and ks == sorted(ks, reverse=True)
    assert ks[-1] == active_layers(0.5, 16, 4, theta_min=0.5) < 16

    import dataclasses

    # scan_layers=False: XLA cost analysis counts a lax.scan body ONCE
    # regardless of trip count, which would hide the depth saving
    cfg8 = dataclasses.replace(get_preset("tiny"), num_layers=8,
                               scan_layers=False)
    eng, *_ = ds.initialize(model=TransformerLM(cfg8), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 1.0, "compiled_tiers": 3},
        "steps_per_print": 100})
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 32))}
    depths, losses = [], []
    for _ in range(4):
        loss = eng.forward(batch)
        depths.append(eng.module._pld_depth)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    L = eng.module.cfg.num_layers
    assert depths[0] == L                      # theta=1 at step 0
    assert depths[-1] < L                      # gamma=1 decays fast
    assert depths == sorted(depths, reverse=True)
    assert np.isfinite(losses[-1])
    # compiled FLOPs at the truncated depth undercut full depth
    flops = {}
    for k in (L, depths[-1]):
        eng.module.set_pld_depth(k)
        stats = profile_fn(
            lambda p, b: eng.module.loss_fn(p, b), eng.params,
            {"input_ids": np.zeros((2, 32), np.int32)})
        flops[k] = stats.get("flops", 0)
    eng.module.set_pld_depth(None)
    if 0 in flops.values():
        pytest.skip("backend reports no cost analysis")
    assert flops[depths[-1]] < 0.9 * flops[L], flops


# ---------------------------------------------------------------------------
# Eigenvalue (Hessian power iteration)
# ---------------------------------------------------------------------------

def test_eigenvalue_quadratic_exact():
    """For loss = 0.5 x^T A x the Hessian IS A: power iteration must find its
    top eigenvalue."""
    rng = np.random.default_rng(0)
    Q = np.linalg.qr(rng.normal(size=(8, 8)))[0]
    eigs = np.array([5.0, 3.0, 2.0, 1.0, 0.5, 0.2, 0.1, 0.05])
    A = (Q * eigs) @ Q.T

    def loss_fn(params, batch):
        x = params["x"]
        return 0.5 * x @ jnp.asarray(A, jnp.float32) @ x

    ev = Eigenvalue(max_iter=200, tol=1e-5)
    lam, vec = ev.compute_eigenvalue(loss_fn, {"x": jnp.zeros(8)}, None)
    assert lam == pytest.approx(5.0, rel=1e-2)


def test_eigenvalue_on_model_loss(eight_devices):
    from deepspeed_tpu.models import TransformerLM, get_preset

    model = TransformerLM(get_preset("tiny"))
    params = model.init(jax.random.key(0))
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (2, 16))}
    lam, _ = Eigenvalue(max_iter=8, tol=1e-2).compute_eigenvalue(
        model.loss_fn, params, batch)
    assert np.isfinite(lam) and lam > 0


# ---------------------------------------------------------------------------
# FP6
# ---------------------------------------------------------------------------

def test_fp6_roundtrip_error_bound():
    x = jax.random.normal(jax.random.key(0), (1024,)) * 3.0
    codes, scale = quantize_fp6(x)
    back = dequantize_fp6(codes, scale, dtype=jnp.float32)
    # e3m2: 2 mantissa bits → relative error <= 2^-3 in the normal range
    # (values below the smallest subnormal flush to zero, as in any float fmt)
    xa = np.abs(np.asarray(x))
    rel = np.abs(np.asarray(back) - np.asarray(x)) / (xa + 1e-3)
    assert np.median(rel) < 0.125
    assert rel[xa > 0.1 * xa.max()].max() < 0.15


def test_fp6_pack_unpack_identity():
    codes = jnp.asarray(np.random.default_rng(0).integers(0, 64, 256),
                        jnp.uint8)
    packed = pack_fp6(codes)
    assert packed.size == 256 * 3 // 4  # true 6-bit storage
    np.testing.assert_array_equal(np.asarray(unpack_fp6(packed, 256)),
                                  np.asarray(codes))


def test_fp6_preserves_sign_and_order():
    x = jnp.asarray([-8.0, -1.0, -0.01, 0.0, 0.01, 1.0, 8.0])
    codes, scale = quantize_fp6(x)
    back = np.asarray(dequantize_fp6(codes, scale, dtype=jnp.float32))
    assert (np.sign(back) == np.sign(np.asarray(x))).all()
    assert (np.diff(back) >= 0).all()