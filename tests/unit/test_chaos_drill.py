"""Pytest wrappers for the chaos drill CLI (``tools/chaos_drill.py``).

Each drill builds a real engine, injects a named fault scenario, and checks
the recovery invariant end to end — marked ``chaos`` + ``slow`` so CI can run
them on demand (``-m chaos``) without taxing the tier-1 fast suite."""

import os
import sys

import pytest

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")
sys.path.insert(0, _TOOLS)


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("scenario",
                         ["preempt-mid-save", "nan-burst", "hung-collective"])
def test_chaos_scenario(scenario, tmp_path, eight_devices):
    from chaos_drill import run_scenario

    verdict = run_scenario(scenario, workdir=str(tmp_path))
    assert verdict["ok"], verdict
