"""Structured pruning + staged compression scheduler (reference
``compression/basic_layer.py`` head/row/channel pruning and
``compression/scheduler.py`` schedule_offset staging)."""

import dataclasses

import jax
import numpy as np
import pytest

from deepspeed_tpu.compression import (
    CompressionScheduler, apply_head_mask, apply_row_mask, clean_heads,
    clean_rows, head_prune_indices, row_prune_indices,
)
from deepspeed_tpu.models import TransformerConfig, TransformerLM


@pytest.fixture(scope="module")
def lm():
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=8, num_kv_heads=4, max_seq_len=64,
                            arch="llama", dtype="float32")
    model = TransformerLM(cfg)
    return model, model.init(jax.random.key(0))


def _logits(model, params, ids):
    return np.asarray(jax.jit(model.logits)(params, ids), np.float32)


def test_head_prune_mask_equals_clean(lm):
    """Masked heads contribute exactly zero, so the physically-sliced model
    (redundancy_clean) must reproduce the masked model's logits — and be
    smaller."""
    model, params = lm
    cfg = model.cfg
    ids = np.random.default_rng(0).integers(0, 128, (2, 16)).astype(np.int32)
    keep = head_prune_indices(params, cfg, ratio=0.5)
    assert keep.shape == (cfg.num_layers, cfg.num_kv_heads // 2)
    masked = apply_head_mask(params, cfg, keep)
    small, small_cfg = clean_heads(params, cfg, keep)
    assert small_cfg.num_kv_heads == cfg.num_kv_heads // 2
    small_model = TransformerLM(small_cfg)
    np.testing.assert_allclose(_logits(model, masked, ids),
                               _logits(small_model, small, ids),
                               atol=1e-5, rtol=1e-5)
    n_full = sum(v.size for v in jax.tree_util.tree_leaves(params))
    n_small = sum(v.size for v in jax.tree_util.tree_leaves(small))
    assert n_small < n_full


def test_row_prune_mask_equals_clean(lm):
    model, params = lm
    cfg = model.cfg
    ids = np.random.default_rng(1).integers(0, 128, (2, 16)).astype(np.int32)
    keep = row_prune_indices(params, cfg, ratio=0.25)
    masked = apply_row_mask(params, cfg, keep)
    small, small_cfg = clean_rows(params, cfg, keep)
    assert small_cfg.intermediate_size < cfg.intermediate_size
    small_model = TransformerLM(small_cfg)
    np.testing.assert_allclose(_logits(model, masked, ids),
                               _logits(small_model, small, ids),
                               atol=1e-5, rtol=1e-5)


def test_staged_scheduler_offsets_and_persistence(lm):
    """Techniques activate at their schedule_offset and masks persist (a
    simulated optimizer update cannot resurrect pruned weights)."""
    model, params = lm
    cfg = model.cfg
    sched = CompressionScheduler(cfg, {
        "head_pruning": {"enabled": True, "ratio": 0.5,
                         "schedule_offset": 5},
        "row_pruning": {"enabled": True, "ratio": 0.25,
                        "schedule_offset": 10},
    })
    p = sched.step(params, 0)
    assert not sched.indices                      # nothing active yet
    p = sched.step(p, 5)
    assert "head" in sched.indices and "row" not in sched.indices
    wo = np.asarray(p["layers"]["attn"]["wo"])
    assert (np.abs(wo).reshape(cfg.num_layers, cfg.num_kv_heads, -1)
            .sum(-1) == 0).sum() == cfg.num_layers * cfg.num_kv_heads // 2
    # simulated optimizer drift resurrects weights; the next step re-masks
    drift = jax.tree_util.tree_map(lambda v: v + 0.01, p)
    p2 = sched.step(drift, 11)
    assert "row" in sched.indices
    wo2 = np.asarray(p2["layers"]["attn"]["wo"])
    assert (np.abs(wo2).reshape(cfg.num_layers, cfg.num_kv_heads, -1)
            .sum(-1) == 0).sum() == cfg.num_layers * cfg.num_kv_heads // 2
    small, small_cfg = sched.redundancy_clean(p2)
    assert small_cfg.num_kv_heads < cfg.num_kv_heads
    assert small_cfg.intermediate_size < cfg.intermediate_size


def test_pruned_quantized_model_trains(lm):
    """A head-pruned + activation-quantized model trains end-to-end through
    the public engine (done criterion of the compression subsystem)."""
    import deepspeed_tpu as ds

    model, _ = lm
    qcfg = dataclasses.replace(model.cfg, act_quant_bits=8)
    qmodel = TransformerLM(qcfg)
    engine, *_ = ds.initialize(model=qmodel, config={
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10 ** 9,
    })
    sched = CompressionScheduler(qcfg, {
        "head_pruning": {"enabled": True, "ratio": 0.5,
                         "schedule_offset": 1},
    })
    rng = np.random.default_rng(2)
    losses = []
    for step in range(4):
        batch = {"input_ids": rng.integers(0, 128, (8, 32)).astype(np.int32)}
        losses.append(float(engine.fused_train_step(batch)))
        engine.params = sched.step(engine.params, step)
    assert all(np.isfinite(losses)), losses
    small, small_cfg = sched.redundancy_clean(engine.params)
    ids = rng.integers(0, 128, (1, 16)).astype(np.int32)
    out = np.asarray(jax.jit(TransformerLM(small_cfg).logits)(small, ids))
    assert np.isfinite(out).all()
