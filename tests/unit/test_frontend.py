"""Network serving front-end tests (``deepspeed_tpu/serving`` HTTP layer).

Three tiers:

* **wire protocol** (no engine): request/response JSON schema round-trip,
  tenant-priority resolution (api-key table, ``x-priority``), SSE framing
  (``sse_event`` and ``iter_sse`` must agree by construction), and the
  ShedError → 429/``Retry-After`` / oversize → 413 / deadline → 504
  status mapping;
* **HTTP over real sockets** (tiny engine): unary + streaming generate on
  the shared probe mux, 429 + ``Retry-After`` on a full queue, router
  failover when a replica enters DRAINING;
* **end-to-end acceptance**: N concurrent mixed-priority clients against
  a 2-replica router — ≥1 429 under an induced ``shed_storm``, a SIGTERM
  drain of one replica with its queued requests migrated to the sibling,
  every admitted uid resolving, pools restored. Real sockets throughout;
  no mocked transport.

The heavier storm drill lives in ``tools/serve_drill.py frontend-storm``
(slow-marked wrapper at the bottom).
"""

import http.client
import io
import json
import os
import signal
import threading
import time

import pytest

from deepspeed_tpu.config.config import (FrontendConfig, RouterConfig,
                                         ServingConfig)
from deepspeed_tpu.serving import (COMPLETED, DRAINING, ContinuousBatcher,
                                   FrontendError, GenerateClient, Replica,
                                   ReplicaRouter, ServingFrontend,
                                   ShedError)
from deepspeed_tpu.serving.protocol import (GENERATE_PATH, ProtocolError,
                                            iter_sse, parse_generate_request,
                                            response_for_record,
                                            shed_response, sse_event)

pytestmark = pytest.mark.frontend

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")

TERMINAL = ("completed", "shed", "expired", "cancelled")


# ---------------------------------------------------------------------------
# wire protocol (no engine, no sockets)
# ---------------------------------------------------------------------------

class TestProtocol:
    CFG = FrontendConfig()

    def test_request_schema_roundtrip(self):
        body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 7,
                           "deadline_s": 2.5, "stream": True}).encode()
        r = parse_generate_request(body, {}, self.CFG)
        assert r.prompt == [1, 2, 3] and r.max_new_tokens == 7
        assert r.deadline_s == 2.5 and r.stream and r.priority == 0

    @pytest.mark.parametrize("body,status", [
        (b"{not json", 400),
        (json.dumps({"prompt": "a string"}).encode(), 400),
        (json.dumps({"prompt": []}).encode(), 400),
        (json.dumps({"prompt": [1, "x"]}).encode(), 400),
        (json.dumps({"prompt": [1], "max_new_tokens": 0}).encode(), 400),
        (json.dumps({"prompt": [1], "max_new_tokens": True}).encode(), 400),
        (json.dumps({"prompt": [1], "deadline_s": -1}).encode(), 400),
        (json.dumps({"prompt": list(range(9000))}).encode(), 413),
    ])
    def test_bad_requests_get_typed_4xx(self, body, status):
        with pytest.raises(ProtocolError) as ei:
            parse_generate_request(body, {}, self.CFG)
        assert ei.value.status == status
        assert "error" in ei.value.body()

    def test_tenant_priority_resolution(self):
        cfg = FrontendConfig(api_keys={"gold": 9}, default_priority=1,
                             max_header_priority=5, min_header_priority=-2)
        body = json.dumps({"prompt": [1]}).encode()
        # api key wins over everything
        assert parse_generate_request(
            body, {"x-api-key": "gold", "x-priority": "3"},
            cfg).priority == 9
        # header override when allowed
        assert parse_generate_request(
            body, {"x-priority": "3"}, cfg).priority == 3
        # ...but clamped both ways: self-PROMOTION can never outrank the
        # paying tenants, and the floor stops unbounded negative values
        # from minting per-priority metric labels
        assert parse_generate_request(
            body, {"x-priority": "999"}, cfg).priority == 5
        assert parse_generate_request(
            body, {"x-priority": "-2"}, cfg).priority == -2
        assert parse_generate_request(
            body, {"x-priority": "-999"}, cfg).priority == -2
        # body override
        assert parse_generate_request(
            json.dumps({"prompt": [1], "priority": 4}).encode(), {},
            cfg).priority == 4
        # default
        assert parse_generate_request(body, {}, cfg).priority == 1
        # override path closed
        off = FrontendConfig(allow_priority_header=False,
                             default_priority=1)
        assert parse_generate_request(
            body, {"x-priority": "3"}, off).priority == 1
        # tenant auth required
        gated = FrontendConfig(api_keys={"gold": 9}, require_api_key=True)
        with pytest.raises(ProtocolError) as ei:
            parse_generate_request(body, {"x-api-key": "wrong"}, gated)
        assert ei.value.status == 401

    def test_shed_maps_to_429_with_retry_after(self):
        status, headers, body = shed_response(
            ShedError("queue_full", retryable=True, retry_after_s=2.3))
        assert status == 429
        assert headers["Retry-After"] == "3"     # integer ceil on the wire
        assert body["error"]["retryable"] and \
            body["error"]["reason"] == "queue_full"
        status, headers, body = shed_response(
            ShedError("oversize", retryable=False))
        assert status == 413 and not body["error"]["retryable"]

    def test_terminal_record_status_mapping(self):
        ok = {"state": "completed", "tokens": [1, 2], "error": None}
        assert response_for_record(7, ok)[0] == 200
        shed = {"state": "shed", "tokens": [],
                "error": {"reason": "kv_pressure", "retryable": True,
                          "retry_after_s": 5.0}}
        status, headers, body = response_for_record(7, shed)
        assert status == 429 and headers["Retry-After"] == "5"
        assert body["id"] == 7
        assert response_for_record(7, {"state": "expired"})[0] == 504
        assert response_for_record(7, {"state": "cancelled"})[0] == 499

    def test_sse_framing_roundtrip(self):
        frames = (sse_event({"token": 5, "index": 0}, event="token")
                  + sse_event({"note": "no event name"})
                  + sse_event({"state": "completed"}, event="end"))
        # the exact frame grammar, not just the parse
        assert frames.startswith(b"event: token\ndata: ")
        assert frames.endswith(b"\n\n")
        evs = list(iter_sse(io.BytesIO(frames)))
        assert [e["event"] for e in evs] == ["token", None, "end"]
        assert evs[0]["data"] == {"token": 5, "index": 0}
        assert evs[2]["data"]["state"] == "completed"


def test_frontend_config_block_consumed():
    """`serving.frontend` / `serving.router` ride the root config; the
    front-end builder requires the explicit enable."""
    from deepspeed_tpu.config import DeepSpeedTpuConfig

    class _Backend:
        health = "ready"

        def report(self):
            return {}

    cfg = DeepSpeedTpuConfig(train_batch_size=8, serving={
        "enabled": True,
        "frontend": {"enabled": True, "api_keys": {"k": 3}},
        "router": {"failover_attempts": 2}})
    assert cfg.serving.router.failover_attempts == 2
    assert cfg.serving.router.migrate_on_drain
    fe = ServingFrontend.from_deepspeed_config(_Backend(), cfg)
    try:
        assert fe.cfg.api_keys == {"k": 3}
    finally:
        fe.close()
    with pytest.raises(ValueError, match="serving.frontend.enabled"):
        ServingFrontend.from_deepspeed_config(
            _Backend(), DeepSpeedTpuConfig(train_batch_size=8))


# ---------------------------------------------------------------------------
# HTTP over real sockets (tiny engine)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engines():
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    return [InferenceEngineV2(TransformerLM(get_preset("tiny")),
                              max_sequences=8, max_seq_len=128,
                              block_size=16) for _ in range(2)]


def _batcher(engine, **kw):
    cfg = ServingConfig(**{"prefill_chunk": 32, "default_max_new_tokens": 4,
                           **kw})
    return ContinuousBatcher(engine, cfg)


def _pool_restored(engine):
    alloc = engine.state.allocator
    return (alloc.free_blocks == alloc.num_blocks
            and not engine.state.sequences)


@pytest.fixture()
def clean_pools(engines):
    yield
    for eng in engines:
        assert _pool_restored(eng), "test leaked KV blocks/sequences"


def test_unary_generate_on_shared_mux(engines, clean_pools):
    """POST /v1/generate next to /metrics + /readyz on ONE port; the
    response carries tokens, usage, and the span."""
    from deepspeed_tpu.observability import MetricsRegistry

    reg = MetricsRegistry()
    b = ContinuousBatcher(engines[0], ServingConfig(
        prefill_chunk=32, default_max_new_tokens=4), registry=reg)
    rep = Replica("solo", b).start()
    try:
        with ServingFrontend(rep, FrontendConfig(), registry=reg) as fe:
            cli = GenerateClient(fe.url, timeout_s=120)
            out = cli.generate(list(range(1, 17)), max_new_tokens=3)
            assert out["state"] == COMPLETED and len(out["tokens"]) == 3
            assert out["usage"] == {"prompt_tokens": 16,
                                    "completion_tokens": 3}
            assert out["span"]["ttft_ms"] is not None
            # same port: scrape + probes + state
            conn = http.client.HTTPConnection(fe.server.host,
                                              fe.server.port, timeout=10)
            conn.request("GET", "/metrics")
            scrape = conn.getresponse()
            text = scrape.read().decode()
            assert scrape.status == 200
            assert "serving_queue_depth" in text
            assert 'frontend_http_requests_total{code="200"} 1' in text
            conn.request("GET", "/readyz")
            assert conn.getresponse().read() and True
            conn.close()
            assert cli.state()["health"] == "ready"
    finally:
        rep.close()


def test_queue_full_surfaces_429_with_load_aware_retry_after(
        engines, clean_pools):
    b = _batcher(engines[0], max_queue_depth=2, retry_after_s=0.5)
    rep = Replica("solo", b).start()
    rep.paused = True                 # nothing admits: the queue IS full
    try:
        with ServingFrontend(rep, FrontendConfig()) as fe:
            for _ in range(2):
                rep.submit(list(range(8)), max_new_tokens=2)
            cli = GenerateClient(fe.url, timeout_s=30)
            with pytest.raises(FrontendError) as ei:
                cli.generate(list(range(8)), max_new_tokens=2)
            e = ei.value
            assert e.status == 429 and e.retryable
            # Retry-After header made it back, scaled above the 0.5s base
            assert e.retry_after_s is not None and e.retry_after_s >= 1
            assert e.body["error"]["reason"] == "queue_full"
            assert e.body["error"]["retry_after_s"] > 0.5
        rep.paused = False
        _wait(lambda: rep.stats["active"] == 0
              and rep.stats["queue_depth"] == 0)
    finally:
        rep.close()


def test_streaming_sse_chunked_over_http(engines, clean_pools):
    """The streaming variant really is chunked SSE on the wire: token
    events arrive one per generated token, then the end record."""
    b = _batcher(engines[0])
    rep = Replica("solo", b).start()
    try:
        with ServingFrontend(rep, FrontendConfig()) as fe:
            conn = http.client.HTTPConnection(fe.server.host,
                                              fe.server.port, timeout=60)
            conn.request("POST", GENERATE_PATH, body=json.dumps(
                {"prompt": list(range(1, 13)), "max_new_tokens": 3,
                 "stream": True}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Content-Type").startswith(
                "text/event-stream")
            assert resp.getheader("Transfer-Encoding") == "chunked"
            evs = list(iter_sse(resp))
            conn.close()
            tokens = [e for e in evs if e["event"] == "token"]
            assert len(tokens) == 3
            assert [t["data"]["index"] for t in tokens] == [0, 1, 2]
            end = evs[-1]
            assert end["event"] == "end"
            assert end["data"]["state"] == COMPLETED
            assert end["data"]["tokens"] == [t["data"]["token"]
                                             for t in tokens]
    finally:
        rep.close()


def test_deadline_expiry_maps_to_504(engines, clean_pools):
    b = _batcher(engines[0])
    rep = Replica("solo", b).start()
    try:
        with ServingFrontend(rep, FrontendConfig()) as fe:
            cli = GenerateClient(fe.url, timeout_s=60)
            with pytest.raises(FrontendError) as ei:
                cli.generate(list(range(1, 97)), max_new_tokens=8,
                             deadline_s=0.001)   # expires mid-prefill
            assert ei.value.status == 504
        _wait(lambda: rep.stats["active"] == 0
              and rep.stats["queue_depth"] == 0)
    finally:
        rep.close()


# ---------------------------------------------------------------------------
# cancellation: server timeout + client disconnect must reach backend.cancel
# ---------------------------------------------------------------------------

class _StallBackend:
    """Admits and then never resolves: forces the front-end's server-side
    timeout. ``cancel`` records the uid and can be armed to raise — the
    front-end's best-effort cancel must swallow a failing backend instead
    of crashing the handler mid-response."""
    health = "ready"

    def __init__(self, cancel_raises=None):
        self.cancelled = []
        self.submitted = []
        self._raise = cancel_raises

    def submit(self, prompt, *, max_new_tokens=None, deadline_s=None,
               priority=0, events=None, trace_id=None):
        self.submitted.append(prompt)
        return 42

    def cancel(self, uid):
        self.cancelled.append(uid)
        if self._raise is not None:
            raise self._raise
        return True

    def report(self):
        return {}


class _ChattyBackend(_StallBackend):
    """Streams token events until cancelled: the handler is always
    writing, so a client disconnect surfaces as a broken pipe."""

    def submit(self, prompt, *, max_new_tokens=None, deadline_s=None,
               priority=0, events=None, trace_id=None):
        def pump():
            i = 0
            while not self.cancelled and i < 100_000:
                events.put({"event": "token", "token": 1, "index": i})
                i += 1
                time.sleep(0.001)

        threading.Thread(target=pump, daemon=True).start()
        return 42


def test_unary_server_timeout_cancels_backend_and_maps_504():
    be = _StallBackend()
    with ServingFrontend(be, FrontendConfig(request_timeout_s=0.1)) as fe:
        with pytest.raises(FrontendError) as ei:
            GenerateClient(fe.url, timeout_s=30).generate([1, 2, 3])
        assert ei.value.status == 504
        assert ei.value.body["error"]["type"] == "server_timeout"
        assert be.cancelled == [42]


def test_stream_server_timeout_cancels_even_when_cancel_raises():
    """The stream-timeout path must still deliver a clean terminal SSE
    event (not a raw 500 injected into the chunked body) even when the
    backend's cancel itself blows up with an arbitrary exception."""
    be = _StallBackend(cancel_raises=RuntimeError("backend gone"))
    with ServingFrontend(be, FrontendConfig(request_timeout_s=0.1)) as fe:
        conn = http.client.HTTPConnection(fe.server.host, fe.server.port,
                                          timeout=30)
        conn.request("POST", GENERATE_PATH,
                     body=json.dumps({"prompt": [1], "stream": True}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        evs = list(iter_sse(resp))      # terminated chunked stream parses
        conn.close()
        assert be.cancelled == [42]     # cancel reached the backend...
        assert evs[-1]["event"] == "end"   # ...and its raise stayed quiet
        assert evs[-1]["data"]["finish_reason"] == "server_timeout"


def test_unary_client_disconnect_cancels_backend():
    """The unary wait never touches the socket until the terminal send —
    the disconnect must be peeked for between event polls, or the request
    generates to completion for nobody."""
    be = _StallBackend()
    with ServingFrontend(be, FrontendConfig()) as fe:
        conn = http.client.HTTPConnection(fe.server.host, fe.server.port,
                                          timeout=30)
        conn.request("POST", GENERATE_PATH,
                     body=json.dumps({"prompt": [1]}),
                     headers={"Content-Type": "application/json"})
        time.sleep(0.2)                 # handler is in the event wait...
        conn.sock.close()               # ...and the client vanishes
        conn.close()
        _wait(lambda: be.cancelled == [42], timeout=30)


def test_bad_content_length_maps_to_400():
    import socket as socket_mod

    be = _StallBackend()
    with ServingFrontend(be, FrontendConfig()) as fe:
        s = socket_mod.create_connection((fe.server.host, fe.server.port),
                                         timeout=10)
        s.sendall(b"POST " + GENERATE_PATH.encode() + b" HTTP/1.1\r\n"
                  b"Host: x\r\nContent-Length: abc\r\n\r\n")
        status = s.recv(4096).split(b"\r\n", 1)[0]
        s.close()
        assert b" 400 " in status, status
        assert be.submitted == [] and be.cancelled == []


def test_client_disconnect_mid_stream_cancels_backend():
    be = _ChattyBackend()
    with ServingFrontend(be, FrontendConfig()) as fe:
        conn = http.client.HTTPConnection(fe.server.host, fe.server.port,
                                          timeout=30)
        conn.request("POST", GENERATE_PATH,
                     body=json.dumps({"prompt": [1], "stream": True}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        resp.read(64)                   # stream is live...
        conn.sock.close()               # ...then the client vanishes
        conn.close()
        _wait(lambda: be.cancelled == [42], timeout=30)


def test_router_routes_away_from_draining_and_fails_over(
        engines, clean_pools):
    """Readiness semantics at the router: a DRAINING replica gets no new
    traffic; retryable sheds fail over to a sibling; when every routable
    replica refuses, the 429 carries the pool-wide hint."""
    b0 = _batcher(engines[0], max_queue_depth=2)
    b1 = _batcher(engines[1], max_queue_depth=2)
    r0, r1 = Replica("r0", b0), Replica("r1", b1)
    router = ReplicaRouter([r0, r1], RouterConfig()).start()
    try:
        router.drain_replica("r1", "test")
        _wait(lambda: r1.stats["health"] == DRAINING)
        assert not r1.routable
        with ServingFrontend(router, FrontendConfig()) as fe:
            out = GenerateClient(fe.url, timeout_s=120).generate(
                list(range(1, 9)), max_new_tokens=2)
            assert out["state"] == COMPLETED     # r0 took it
            _wait(lambda: router.health == "ready")   # r0 served → READY
            # now fill r0 while paused: every routable replica refuses
            r0.paused = True
            for _ in range(2):
                r0.submit(list(range(8)), max_new_tokens=2)
            with pytest.raises(FrontendError) as ei:
                GenerateClient(fe.url, timeout_s=30).generate(
                    list(range(8)), max_new_tokens=2)
            assert ei.value.status == 429
            assert ei.value.retry_after_s is not None
            assert router.counters["rejected"] == 1
            r0.paused = False
            _wait(lambda: r0.stats["active"] == 0
                  and r0.stats["queue_depth"] == 0)
    finally:
        router.close()


def _wait(cond, timeout=60.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError("condition not reached in time")


# ---------------------------------------------------------------------------
# end-to-end acceptance: storm + SIGTERM drain + migration, real sockets
# ---------------------------------------------------------------------------

def test_e2e_storm_sigterm_drain_migration(engines, clean_pools):
    """N concurrent mixed-priority clients against a 2-replica router:
    ≥1 429+Retry-After under an induced shed_storm, then a SIGTERM drain
    of one replica migrates its queued requests to the sibling, every
    admitted uid resolves, and the pools come back empty."""
    from deepspeed_tpu.resilience import FaultInjector, set_injector

    b0 = _batcher(engines[0], max_queue_depth=8, default_max_new_tokens=3)
    b1 = _batcher(engines[1], max_queue_depth=8, default_max_new_tokens=3)
    r0, r1 = Replica("r0", b0), Replica("r1", b1)
    router = ReplicaRouter([r0, r1], RouterConfig()).start()
    fe = ServingFrontend(router, FrontendConfig(
        api_keys={"gold": 5}, max_header_priority=4)).start()
    results, lock = [], threading.Lock()

    def unary(i, key=None):
        cli = GenerateClient(fe.url, api_key=key, timeout_s=120)
        try:
            out = cli.generate(list(range(1, 10 + i % 3)),
                               max_new_tokens=3,
                               priority=(i % 2) * 3 if key is None
                               else None)
            with lock:
                results.append(("ok", out))
        except FrontendError as e:
            with lock:
                results.append(("err", e))

    def streamer(i):
        try:
            evs = list(GenerateClient(fe.url, timeout_s=120).stream(
                list(range(1, 12)), max_new_tokens=3))
            with lock:
                results.append(("stream", evs))
        except FrontendError as e:
            with lock:
                results.append(("err", e))

    try:
        # ---- phase 1: storm. Queues fill while the workers are paused,
        # then shed_storm sheds them — every client sees a 429 one way
        # (queue_full at submit, after sibling failover) or the other
        # (shed_storm terminal record).
        r0.paused = r1.paused = True
        storm = [threading.Thread(target=unary, args=(i, None))
                 for i in range(20)]
        for t in storm:
            t.start()
        _wait(lambda: r0.stats["queue_depth"] + r1.stats["queue_depth"]
              + sum(1 for r in results if r[0] == "err") >= 20)
        set_injector(FaultInjector([{"kind": "shed_storm", "times": 2}]))
        r0.paused = r1.paused = False
        for t in storm:
            t.join(timeout=120)
        set_injector(None)
        errs = [r[1] for r in results if r[0] == "err"]
        assert len(errs) >= 1
        assert all(e.status == 429 for e in errs)
        assert all(e.retry_after_s is not None and e.retry_after_s >= 1
                   for e in errs)                       # Retry-After header
        reasons = {(e.body.get("error") or {}).get("reason")
                   for e in errs}
        assert "shed_storm" in reasons          # the induced storm showed
        # admitted-then-shed 429 bodies carry the router uid: none lost
        for e in errs:
            if "id" in e.body:
                assert router.resolve(e.body["id"]) in TERMINAL

        # ---- phase 2: SIGTERM drains r0 mid-flight; its queued requests
        # migrate to r1 and still complete for their clients.
        results.clear()
        r0.paused = r1.paused = True
        wave = ([threading.Thread(target=unary, args=(i, "gold"))
                 for i in range(4)]
                + [threading.Thread(target=streamer, args=(i,))
                   for i in range(4)])
        for t in wave:
            t.start()
        _wait(lambda: r0.stats["queue_depth"] + r1.stats["queue_depth"]
              >= 8)
        queued_r0 = r0.stats["queue_depth"]
        assert queued_r0 >= 1                   # something TO migrate
        router.install_signal_handlers(drain="r0")
        os.kill(os.getpid(), signal.SIGTERM)
        _wait(lambda: router.counters["migrated"]
              + router.counters["migration_failed"] >= queued_r0)
        r0.paused = r1.paused = False
        for t in wave:
            t.join(timeout=120)
        assert router.counters["migrated"] >= 1
        oks = [r[1] for r in results if r[0] == "ok"]
        streams = [r[1] for r in results if r[0] == "stream"]
        assert len(oks) == 4 and len(streams) == 4
        for out in oks:
            assert out["state"] == COMPLETED and len(out["tokens"]) == 3
            assert router.resolve(out["id"]) == COMPLETED
        for evs in streams:
            assert evs[-1]["event"] == "end"
            assert evs[-1]["data"]["state"] == COMPLETED
        # a drained r0 leaves the pool ready (r1 serves) — probe semantics
        conn = http.client.HTTPConnection(fe.server.host, fe.server.port,
                                          timeout=10)
        conn.request("GET", "/readyz")
        assert conn.getresponse().status == 200
        conn.close()
        assert r0.stats["health"] == DRAINING
        _wait(lambda: r1.stats["active"] == 0
              and r1.stats["queue_depth"] == 0)
    finally:
        set_injector(None)
        router.restore_signal_handlers()
        fe.close()
        fe.close()                              # idempotent, no double-free
        router.close()
        router.close()


# ---------------------------------------------------------------------------
# drill wrapper (slow; the CLI is the invariant authority)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_frontend_storm_drill(tmp_path):
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    verdict = run_scenario("frontend-storm", workdir=str(tmp_path))
    assert verdict["ok"], verdict


def test_router_route_reads_hold_the_lock():
    """dslint burn-down (lock-discipline): ``cancel``/``resolve`` used to
    probe ``_routes`` and then read ``route.replica``/``route.uid`` with NO
    lock, racing ``submit(_ruid=...)``'s migration rewrite of that pair
    under ``_lock`` — a torn read aims the command at the wrong replica.
    Both now snapshot (replica, uid) via ``_route_loc`` under the lock;
    this pins the contract with a dict proxy that asserts the lock is held
    on every route-table probe."""
    from deepspeed_tpu.serving.router import ReplicaRouter, _Route

    class _StubReplica:
        def __init__(self, name):
            self.name = name
            self.incarnation = 0
            self.cancelled = []
            self.resolved = []

        def cancel(self, uid):
            self.cancelled.append(uid)
            return True

        def resolve(self, uid):
            self.resolved.append(uid)
            return COMPLETED

    rep = _StubReplica("r0")
    router = ReplicaRouter([rep], RouterConfig())

    class _LockAssertingRoutes(dict):
        def get(self, key, default=None):
            assert router._lock.locked(), \
                "_routes probed outside 'with self._lock:'"
            return super().get(key, default)

    routes = _LockAssertingRoutes()
    routes[7] = _Route("r0", rep.incarnation, 42, None)
    router._routes = routes

    assert router.cancel(7) is True
    assert rep.cancelled == [42]
    assert router.resolve(7) == COMPLETED
    assert rep.resolved == [42]
    # unknown ruids stay well-behaved through the locked path too
    assert router.cancel(999) is False
    assert router.resolve(999) is None
