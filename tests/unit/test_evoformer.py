"""Evoformer attention tests — parity with a dense numpy reference of the
DS4Sci_EvoformerAttention math (analog of tests/unit/ops/deepspeed4science)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.evoformer_attn import DS4Sci_EvoformerAttention


def _ref(Q, K, V, b1=None, b2=None):
    s = np.einsum("bnqhd,bnkhd->bnhqk", Q, K) / np.sqrt(Q.shape[-1])
    if b1 is not None:
        s = s + b1
    if b2 is not None:
        s = s + b2
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bnhqk,bnkhd->bnqhd", p, V)


def _inputs(B=2, N=3, L=32, H=4, D=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 5)
    Q = jax.random.normal(ks[0], (B, N, L, H, D))
    K = jax.random.normal(ks[1], (B, N, L, H, D))
    V = jax.random.normal(ks[2], (B, N, L, H, D))
    b1 = jax.random.normal(ks[3], (B, N, 1, 1, L)) * 0.5
    b2 = jax.random.normal(ks[4], (B, 1, H, L, L)) * 0.5
    return Q, K, V, b1, b2


@pytest.mark.parametrize("use_b1,use_b2", [(False, False), (True, False),
                                           (False, True), (True, True)])
def test_matches_dense_reference(use_b1, use_b2):
    Q, K, V, b1, b2 = _inputs()
    biases = [b1 if use_b1 else None, b2 if use_b2 else None]
    got = DS4Sci_EvoformerAttention(Q, K, V, biases)
    ref = _ref(*map(np.asarray, (Q, K, V)),
               np.asarray(b1) if use_b1 else None,
               np.asarray(b2) if use_b2 else None)
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-5)


@pytest.mark.parametrize("L", [64, 72])  # 72: not a chunk multiple → padded scan
def test_chunked_matches_unchunked(L):
    Q, K, V, b1, b2 = _inputs(L=L)
    full = DS4Sci_EvoformerAttention(Q, K, V, [b1, b2], chunk_size=1024)
    chunked = DS4Sci_EvoformerAttention(Q, K, V, [b1, b2], chunk_size=16)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               atol=2e-5)


def test_too_many_biases_and_rank4_b2_rejected():
    Q, K, V, b1, b2 = _inputs(B=1, N=2, L=16, H=2, D=8)
    with pytest.raises(AssertionError, match="at most two"):
        DS4Sci_EvoformerAttention(Q, K, V, [b1, b2, b1])
    with pytest.raises(AssertionError, match="rank-4"):
        DS4Sci_EvoformerAttention(Q[:, 0], K[:, 0], V[:, 0],
                                  [None, jnp.zeros((1, 1, 2, 16, 16))])


def test_bias_gradients_flow():
    """The CUDA kernel hand-codes dB1/dB2; autodiff must produce them here."""
    Q, K, V, b1, b2 = _inputs(B=1, N=2, L=16, H=2, D=8)

    def loss(b1, b2):
        return DS4Sci_EvoformerAttention(Q, K, V, [b1, b2]).sum()

    g1, g2 = jax.grad(loss, argnums=(0, 1))(b1, b2)
    assert g1.shape == b1.shape and g2.shape == b2.shape
    assert float(jnp.abs(g1).sum()) > 0 and float(jnp.abs(g2).sum()) > 0


def test_bad_bias_shape_raises():
    Q, K, V, b1, _ = _inputs()
    with pytest.raises(AssertionError, match="bias1"):
        DS4Sci_EvoformerAttention(Q, K, V, [np.zeros((1, 1, 1, 1, 1))])
