"""Robustness of the on-disk tuning caches a replica touches at startup:
the mesh autotuner's ``WinnerStore`` and the AIO bench's autotune cache.

A corrupt, torn, or concurrently-written cache file must degrade to "no
cached answer" (cost-model / default fallback) — never crash engine init
or a respawn. These are the same torn-file semantics the warm-start
weight cache is drilled for in ``test_fleet.py``."""

import json
import os
import threading

import pytest


@pytest.mark.elastic
@pytest.mark.scaling
class TestWinnerStoreRobustness:
    def _store(self, tmp_path):
        from deepspeed_tpu.autotuning.mesh_store import WinnerStore

        return WinnerStore(str(tmp_path / "winners.json"))

    def test_corrupt_json_falls_back_empty(self, tmp_path):
        st = self._store(tmp_path)
        with open(st.path, "w") as f:
            f.write("{definitely not json")
        assert st.get("sig", 8, "cpu") is None
        # put() heals the file
        st.put("sig", 8, "cpu", {"dp": 8}, 123.0)
        assert st.get("sig", 8, "cpu")["metric"] == 123.0

    def test_torn_file_falls_back_empty(self, tmp_path):
        st = self._store(tmp_path)
        st.put("sig", 8, "cpu", {"dp": 8}, 123.0)
        size = os.path.getsize(st.path)
        with open(st.path, "r+b") as f:
            f.truncate(size // 2)
        assert st.get("sig", 8, "cpu") is None

    def test_wrong_schema_falls_back_empty(self, tmp_path):
        st = self._store(tmp_path)
        with open(st.path, "w") as f:
            json.dump({"schema": 999, "winners": {"x": {}}}, f)
        assert st.get("sig", 8, "cpu") is None

    def test_missing_file_ok(self, tmp_path):
        st = self._store(tmp_path)
        assert st.get("sig", 8, "cpu") is None

    def test_concurrent_puts_leave_valid_file(self, tmp_path):
        st = self._store(tmp_path)
        errors = []

        def hammer(i):
            try:
                for j in range(10):
                    st.put(f"sig{i}", 8, "cpu", {"dp": 8}, float(i * 10 + j))
            except Exception as e:   # pragma: no cover - the failure mode
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # whatever interleaving won, the file is valid schema'd JSON and
        # readable (atomic tmp+rename: no torn merge states)
        with open(st.path) as f:
            data = json.load(f)
        assert data["schema"] and isinstance(data["winners"], dict)
        assert st.get("sig0", 8, "cpu") is None or \
            st.get("sig0", 8, "cpu")["metric"] >= 0

    def test_resolve_auto_never_raises_on_damage(self, tmp_path):
        """The ``mesh: auto`` ladder with a damaged winner cache: cost
        model / all-dp fallback, never an exception into engine init."""
        from deepspeed_tpu.autotuning.mesh_store import (
            WinnerStore, resolve_auto_axis_sizes)

        cache = str(tmp_path / "winners.json")
        for damage in ("{torn", "", json.dumps([1, 2, 3]),
                       json.dumps({"schema": 1, "winners": "not-a-dict"})):
            with open(cache, "w") as f:
                f.write(damage)
            axes = resolve_auto_axis_sizes(8, None, winner_cache=cache,
                                           kind="cpu")
            assert isinstance(axes, dict)
            assert all(isinstance(v, int) for v in axes.values())
        # and a healthy winner is actually adopted afterwards
        WinnerStore(cache).put("m", 8, "cpu", {"dp": 4, "tp": 2}, 50.0)


@pytest.mark.elastic
class TestAioAutotuneCacheRobustness:
    def _fake_sweep(self, monkeypatch):
        from deepspeed_tpu.ops import aio_bench

        calls = []

        def sweep(bench_dir, **kw):
            calls.append(bench_dir)
            return [{"threads": 2, "chunk_mb": 4,
                     "read_MBps": 100.0, "write_MBps": 80.0}]

        monkeypatch.setattr(aio_bench, "sweep", sweep)
        return calls

    def test_corrupt_cache_rebenches_and_heals(self, tmp_path, monkeypatch):
        from deepspeed_tpu.ops.aio_bench import autotune_config

        calls = self._fake_sweep(monkeypatch)
        cache = str(tmp_path / "aio_cache.json")
        with open(cache, "w") as f:
            f.write("~~~corrupt~~~")
        cfg = autotune_config(str(tmp_path / "swap"), cache_path=cache)
        assert cfg["threads"] == 2 and len(calls) == 1
        # healed: the second call is a cache hit, no re-sweep
        cfg2 = autotune_config(str(tmp_path / "swap"), cache_path=cache)
        assert cfg2["threads"] == 2 and len(calls) == 1

    def test_swapper_survives_corrupt_autotune_cache(self, tmp_path,
                                                     monkeypatch):
        """Engine-init path: an AsyncTensorSwapper with autotune enabled
        and a corrupt cache must come up (defaults or re-bench), never
        raise out of __init__."""
        import numpy as np

        from deepspeed_tpu.offload.swap import AsyncTensorSwapper

        self._fake_sweep(monkeypatch)
        cache = str(tmp_path / "aio_cache.json")
        with open(cache, "w") as f:
            f.write('{"truncated": ')
        sw = AsyncTensorSwapper(str(tmp_path / "swap"), autotune=True,
                                autotune_cache=cache)
        arr = np.arange(32, dtype=np.float32)
        sw.swap_out("t0", arr)
        sw.wait()
        ticket, segments = sw.swap_in_start_many(["t0"])
        try:
            flat = ticket.wait()
            off, nbytes = segments["t0"]
            out = np.frombuffer(flat[off:off + nbytes].tobytes(),
                                dtype=np.float32)
        finally:
            ticket.release()
        np.testing.assert_array_equal(out, arr)

    def test_sweep_failure_degrades_to_defaults(self, tmp_path,
                                                monkeypatch):
        from deepspeed_tpu.offload.swap import AsyncTensorSwapper
        from deepspeed_tpu.ops import aio_bench

        def boom(*a, **kw):
            raise OSError("injected bench failure")

        monkeypatch.setattr(aio_bench, "sweep", boom)
        sw = AsyncTensorSwapper(str(tmp_path / "swap"), autotune=True,
                                autotune_cache=str(tmp_path / "c.json"))
        assert sw.autotuned is None          # fell back, did not raise
