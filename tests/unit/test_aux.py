"""Aux-subsystem unit tests (pattern: reference ``tests/unit/launcher``,
``tests/unit/elasticity``, ``unit/autotuning``, ``unit/profiling`` — pure-unit,
no device work)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.elasticity import compute_elastic_config, get_compatible_chip_counts
from deepspeed_tpu.launcher.runner import filter_hosts, parse_hostfile


class TestLauncher:
    def test_parse_hostfile(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text("# comment\nworker-0 slots=4\nworker-1 slots=4\n\n")
        hosts = parse_hostfile(str(hf))
        assert hosts == {"worker-0": 4, "worker-1": 4}

    def test_parse_hostfile_duplicate(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text("a slots=2\na slots=4\n")
        with pytest.raises(ValueError, match="duplicate"):
            parse_hostfile(str(hf))

    def test_parse_hostfile_empty(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text("# nothing\n")
        with pytest.raises(ValueError, match="empty"):
            parse_hostfile(str(hf))

    def test_filters(self):
        hosts = {"a": 4, "b": 4, "c": 4}
        assert filter_hosts(hosts, include="a,b") == {"a": 4, "b": 4}
        assert filter_hosts(hosts, exclude="c") == {"a": 4, "b": 4}
        with pytest.raises(ValueError):
            filter_hosts(hosts, include="zzz")

    @pytest.mark.parametrize("name", ["pdsh", "openmpi", "slurm", "mpich",
                                      "impi"])
    def test_multinode_runner_cmds(self, name):
        """Reference multinode_runner.py parity: each transport builds one
        command that fans the script out with the rendezvous env (pure-unit,
        same as tests/unit/launcher's multinode cmd tests)."""
        import argparse

        from deepspeed_tpu.launcher.multinode_runner import RUNNERS

        args = argparse.Namespace(script="train.py", script_args=["--x", "1"],
                                  master_port=29500, slurm_comment="")
        hosts = {"h0": 1, "h1": 1}
        runner = RUNNERS[name](args)
        base_env = {"PYTHONPATH": "/repo", "HOME": "/root"}
        cmd = runner.get_cmd(base_env, hosts)
        joined = " ".join(cmd)
        assert cmd[0] in ("pdsh", "mpirun", "srun", "mpiexec")
        assert "train.py" in joined and "--x" in joined
        if name == "slurm":
            # slurm forwards rendezvous via the srun process env (inline
            # --export K=V cannot carry comma-valued DSTPU_HOSTS) and pins
            # the coordinator to the sorted-first host (= SLURM task 0)
            env = runner.get_env(base_env, hosts)
            assert env["DSTPU_COORDINATOR"] == "h0:29500"
            assert env["DSTPU_HOSTS"] == "h0,h1"
            assert "--ntasks-per-node" in cmd and "--export" in cmd
            assert "ALL" in cmd and "DSTPU_HOSTS" not in joined
        else:
            assert "DSTPU_COORDINATOR" in joined and "h0:29500" in joined
            assert "DSTPU_WORLD_SIZE" in joined
            assert "PYTHONPATH" in joined     # exported prefix forwarded
            assert "HOME" not in joined       # non-exported env NOT forwarded
        if name in ("openmpi", "mpich", "impi"):
            assert "2" in cmd  # one rank per host

    def test_scheduler_rank_discovery(self, monkeypatch):
        """init_distributed reads scheduler-native rank envs (SLURM/OMPI/PMI)
        when the launcher's DSTPU_RANK is absent."""
        import deepspeed_tpu.comm.comm as c

        captured = {}

        def fake_init(**kw):
            captured.update(kw)

        monkeypatch.setattr(c.jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(c, "_initialized", False)
        monkeypatch.setenv("DSTPU_COORDINATOR", "h0:29500")
        monkeypatch.setenv("DSTPU_WORLD_SIZE", "4")
        monkeypatch.setenv("SLURM_PROCID", "3")
        monkeypatch.delenv("DSTPU_RANK", raising=False)
        c.init_distributed()
        assert captured == {"coordinator_address": "h0:29500",
                            "process_id": 3, "num_processes": 4}
        monkeypatch.setattr(c, "_initialized", True)


class TestElasticity:
    def test_compatible_chips(self):
        chips = get_compatible_chip_counts(64, [1, 2, 4], min_chips=1, max_chips=16)
        assert 8 in chips and 16 in chips
        assert all(any(64 % (n * mb) == 0 for mb in [1, 2, 4]) for n in chips)

    def test_elastic_config(self):
        batch, chips, micro = compute_elastic_config({
            "max_train_batch_size": 64,
            "micro_batch_sizes": [1, 2, 4],
            "min_gpus": 1, "max_gpus": 16,
        })
        assert batch <= 64 and len(chips) >= 8
        for n, mb in micro.items():
            assert batch % (n * mb) == 0

    def test_incompatible_world_raises(self):
        with pytest.raises(ValueError, match="not elastic-compatible"):
            compute_elastic_config({
                "max_train_batch_size": 8,
                "micro_batch_sizes": [8],
                "min_gpus": 1, "max_gpus": 4,
            }, target_chips=3)


class TestCompression:
    def test_magnitude_pruning(self):
        import jax

        from deepspeed_tpu.compression import prune_magnitude

        params = {"w": jax.random.normal(jax.random.key(0), (32, 32))}
        pruned = prune_magnitude(params, sparsity=0.5)
        frac = float((np.asarray(pruned["w"]) == 0).mean())
        assert 0.45 <= frac <= 0.55

    def test_ste_quantize_grad_passthrough(self):
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.compression import ste_quantize

        x = jnp.linspace(-1, 1, 256)
        g = jax.grad(lambda x: (ste_quantize(x) ** 2).sum())(x)
        # straight-through: grad ≈ 2*xq, nonzero, finite
        assert bool(jnp.all(jnp.isfinite(g)))
        assert float(jnp.abs(g).sum()) > 0

    def test_ptq_roundtrip_close(self):
        import jax

        from deepspeed_tpu.compression import quantize_weights_ptq

        params = {"w": jax.random.normal(jax.random.key(1), (64, 64))}
        q = quantize_weights_ptq(params, bits=8)
        err = np.abs(np.asarray(q["w"]) - np.asarray(params["w"])).max()
        assert err < 0.05


class TestEnvReport:
    def test_report_runs(self):
        from deepspeed_tpu.env_report import report

        text = report()
        assert "deepspeed_tpu" in text and "op compatibility" in text


class TestProfiler:
    def test_profile_fn_flops(self):
        import jax.numpy as jnp

        from deepspeed_tpu.profiling import profile_fn

        def f(a, b):
            return a @ b

        stats = profile_fn(f, jnp.ones((64, 64)), jnp.ones((64, 64)))
        # 2*64^3 flops expected (cost analysis may fold, allow wide band)
        assert stats["flops"] > 1e4


class TestAutotuner:
    def test_grid_sweeps_all_axes(self, eight_devices):
        """The tuner enumerates micro-batch x stage x remat x offload (the
        reference tuner's full axis set) and returns the fastest OK trial."""
        from deepspeed_tpu.autotuning import Autotuner
        from deepspeed_tpu.models import TransformerLM, TransformerConfig

        def factory(remat_policy="none"):
            return TransformerLM(TransformerConfig(
                vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=32, remat_policy=remat_policy))

        tuner = Autotuner(
            factory,
            {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
             "mesh": {"dp": 8}, "steps_per_print": 1000},
            micro_batch_candidates=(1, 2),
            zero_stage_candidates=(0, 1),
            remat_candidates=("none", "full"),
            offload_candidates=(None, "cpu"),
            steps=1,
            make_batch=lambda n: {"input_ids": np.zeros((n, 16), np.int32)})
        best = tuner.tune()
        assert best is not None and best.ok
        axes = {(r.config["micro_batch"], r.config["stage"],
                 r.config["remat"], r.config["offload"])
                for r in tuner.results}
        # offload trials only run at stage >= 1
        assert (1, 1, "none", "cpu") in axes
        assert all(off is None or stage >= 1
                   for (_, stage, _, off) in axes)
        assert {r.config["remat"] for r in tuner.results} == {"none", "full"}


class TestAIOBench:
    def test_sweep(self, tmp_path):
        from deepspeed_tpu.ops.aio_bench import sweep

        res = sweep(str(tmp_path), sizes_mb=[1], threads=[1, 2], repeats=2)
        assert len(res) == 2
        for r in res:
            assert r["write_MBps"] > 0 and r["read_MBps"] > 0


class TestActivationOffload:
    def test_offload_attn_policy(self):
        """FPDT-style host offload: saved attention outputs round-trip through
        pinned host memory; gradients match the no-remat baseline. (Under
        SPMD meshes this policy is TPU-only — the CPU partitioner rejects
        device-placement annotations; single-device covers the math here.)"""
        import jax
        import jax.numpy as jnp
        from jax.ad_checkpoint import checkpoint_name

        from deepspeed_tpu.runtime.activation_checkpointing import (
            POLICIES, checkpoint_wrapper, resolve_policy)

        assert "offload_attn" in POLICIES
        assert resolve_policy("offload_attn") is not None

        def f(w, x):
            h = checkpoint_name(jnp.tanh(x @ w), "flash_attn_out")
            return (h @ w.T).sum()

        w = jax.random.normal(jax.random.key(0), (8, 8))
        x = jax.random.normal(jax.random.key(1), (4, 8))
        g_off = jax.grad(checkpoint_wrapper(f, policy="offload_attn"))(w, x)
        g_ref = jax.grad(f)(w, x)
        np.testing.assert_allclose(np.asarray(g_off), np.asarray(g_ref),
                                   atol=1e-5)


class TestSanityChecks:
    """SURVEY §5.2: the engine-level sanity pass (reference sanity_checks
    config engine.py:1346 + cross-rank asserts zero/utils)."""

    def _engine(self, eight_devices, **extra):
        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset

        cfg = {"train_micro_batch_size_per_gpu": 2,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 3,
                                     "param_persistence_threshold": 0},
               "mesh": {"fsdp": 8}, "steps_per_print": 100, **extra}
        return ds.initialize(model=TransformerLM(get_preset("tiny")),
                             config=cfg)[0]

    def test_startup_and_first_batch_pass(self, eight_devices):
        eng = self._engine(eight_devices, sanity_checks=True)
        b = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 16))}
        loss = eng.forward(b)
        eng.backward(loss)
        eng.step()
        assert eng._first_batch_checked
        assert np.isfinite(float(loss))

    def test_param_integrity_catches_nan(self, eight_devices):
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.runtime.sanity import check_param_integrity

        eng = self._engine(eight_devices)
        # poison one leaf
        eng.params["final_norm"]["scale"] = eng.params["final_norm"][
            "scale"].at[0].set(jnp.nan)
        with pytest.raises(RuntimeError, match="non-finite"):
            check_param_integrity(eng)

    def test_param_placement_catches_mismatch(self, eight_devices):
        import jax

        from deepspeed_tpu.runtime.sanity import check_param_placement

        eng = self._engine(eight_devices)
        check_param_placement(eng)  # sane engine passes
        # replicate a leaf that the engine declared sharded
        from jax.sharding import NamedSharding, PartitionSpec as P

        eng.params["embed"]["tokens"] = jax.device_put(
            eng.params["embed"]["tokens"], NamedSharding(eng.mesh, P()))
        with pytest.raises(RuntimeError, match="placed as"):
            check_param_placement(eng)

    def test_integrity_ignores_integer_leaves(self, eight_devices):
        from deepspeed_tpu.runtime.sanity import check_param_integrity

        eng = self._engine(eight_devices)
        import jax.numpy as jnp

        eng.params["counter"] = jnp.zeros((4,), jnp.int32)
        check_param_integrity(eng)  # must not raise on integer leaves


def test_per_module_profile_attributes_blocks(eight_devices):
    """Round-2 weak #9: the profiler now breaks cost down per named module
    (the reference profiler's 'top modules' view) instead of whole-program
    totals only."""
    import jax

    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.profiling import per_module_profile

    model = TransformerLM(get_preset("tiny"))
    params = model.init(jax.random.key(0))
    ids = np.random.default_rng(0).integers(0, 256, (2, 16))
    mods = per_module_profile(lambda p: model.logits(p, ids), params)
    scopes = set(mods)
    assert any(s.startswith("mlp") for s in scopes), scopes
    assert any(s.startswith("attn") for s in scopes), scopes
    assert any("lm_head" in s for s in scopes), scopes
    # the mlp is the FLOPs-heaviest block of a dense decoder layer
    top = next(iter(mods))
    assert top.startswith("mlp"), mods
    assert all(v["gflops"] >= 0 and v["ops"] > 0 for v in mods.values())


class TestExperimentScheduler:
    """Multi-host autotuning scheduler (reference autotuning/scheduler.py):
    experiments fan out over a host pool, failures are recorded not raised,
    and the best config is written back."""

    def test_parallel_scheduling_and_best_writeback(self, tmp_path):
        import json
        import threading

        from deepspeed_tpu.autotuning import ExperimentScheduler

        in_flight, peak = [0], [0]
        lock = threading.Lock()

        def runner(exp, exp_dir):
            with lock:
                in_flight[0] += 1
                peak[0] = max(peak[0], in_flight[0])
            try:
                import time
                time.sleep(0.05)
                if exp.config["mb"] == 3:
                    raise RuntimeError("simulated OOM")
                return float(exp.config["mb"] * 10)
            finally:
                with lock:
                    in_flight[0] -= 1

        sched = ExperimentScheduler(
            [{"mb": m} for m in (1, 2, 3, 4)],
            hosts=["host-a", "host-b"], results_dir=str(tmp_path),
            runner=runner)
        best = sched.run()
        assert best is not None and best.config == {"mb": 4}
        assert peak[0] == 2              # both hosts were busy concurrently
        statuses = {e.config["mb"]: e.status for e in sched.experiments}
        assert statuses[3] == "failed" and statuses[4] == "done"
        with open(tmp_path / "best_config.json") as f:
            assert json.load(f)["config"] == {"mb": 4}

    def test_multi_host_reservations(self, tmp_path):
        from deepspeed_tpu.autotuning import ExperimentScheduler

        seen = []

        def runner(exp, exp_dir):
            seen.append(tuple(sorted(exp.hosts)))
            return 1.0

        sched = ExperimentScheduler(
            [{"i": 0}, {"i": 1}], hosts=["h0", "h1", "h2", "h3"],
            results_dir=str(tmp_path), runner=runner, hosts_per_exp=2)
        assert sched.run() is not None
        assert all(len(h) == 2 for h in seen)
        assert len(set(sum(map(list, seen), []))) == 4  # disjoint host sets
