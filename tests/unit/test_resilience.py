"""Resilience-layer tests: fault injection drills for the retry policy, the
preemption-safe CheckpointManager, the self-healing step guard, and the
closed elastic-agent recovery loop (the analog of the reference's elastic
agent + checkpoint-commit integration tests, with deterministic faults in
place of real host losses)."""

import json
import os
import signal
import textwrap
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu import comm
from deepspeed_tpu.elasticity import ElasticAgent, subprocess_spawn
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.resilience import (ABORT, CONTINUE, SAVE,
                                      CheckpointManager, CoordinatedAbort,
                                      FaultInjector, InjectedIOError,
                                      ResilienceCoordinator,
                                      RetryDeadlineExceeded, RetryPolicy,
                                      TooManyBadSteps, retry_call,
                                      set_injector)
from deepspeed_tpu.resilience.faults import tear_checkpoint_dir
from deepspeed_tpu.resilience.manager import STAGING_FILE, verify_tag_dir


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test starts and ends with an inert process-wide injector."""
    set_injector(None)
    yield
    set_injector(None)
    comm.set_retry_policy(None)


def make_config(stage=2, mesh=None, resilience=None, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh or {"fsdp": 8},
        "steps_per_print": 100,
        "resilience": {"enabled": True, **(resilience or {})},
    }
    cfg.update(over)
    return cfg


def data_iter(batch, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    fixed = {"input_ids": rng.integers(0, 256, (batch, seq))}
    while True:
        yield fixed


def train_steps(engine, steps, seed=0):
    it = data_iter(engine.train_micro_batch_size_per_gpu()
                   * engine.topology.dp_world_size, seed=seed)
    losses = []
    while len(losses) < steps:
        loss = engine.forward(next(it))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_call(flaky, policy=RetryPolicy(max_attempts=5,
                                                   base_delay_s=0.001))
        assert out == "ok" and len(calls) == 3

    def test_attempt_budget_exhausted(self):
        def always():
            raise OSError("down")

        with pytest.raises(RetryDeadlineExceeded):
            retry_call(always, policy=RetryPolicy(max_attempts=2,
                                                  base_delay_s=0.001))

    def test_non_retryable_passes_through(self):
        def bad():
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            retry_call(bad, policy=RetryPolicy(max_attempts=3,
                                               base_delay_s=0.001))

    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=0.3,
                        jitter=0.0)
        assert p.delay(0) == pytest.approx(0.1)
        assert p.delay(1) == pytest.approx(0.2)
        assert p.delay(5) == pytest.approx(0.3)  # capped

    def test_comm_retry_succeeds_after_two_injected_failures(self):
        """The acceptance drill: a host collective fails twice, the armed
        policy retries, the third attempt lands."""
        set_injector(FaultInjector([
            {"kind": "failed_collective", "times": 2}]))
        comm.set_retry_policy(RetryPolicy(max_attempts=3, base_delay_s=0.001))
        out = comm.all_reduce_host(np.int64(7))
        assert int(out) == 7
        assert comm.get_retry_stats()["retries"] == 2

    def test_comm_failure_without_policy_raises(self):
        set_injector(FaultInjector([{"kind": "failed_collective"}]))
        comm.set_retry_policy(None)
        with pytest.raises(InjectedIOError):
            comm.all_reduce_host(np.int64(1))


# ---------------------------------------------------------------------------
# Step guard
# ---------------------------------------------------------------------------

class TestStepGuard:
    def test_nan_step_skipped_without_corrupting_state(self, eight_devices):
        """A poisoned-gradient step must be dropped whole: params and
        optimizer state identical to before, LR schedule not ticked, and
        training healthy afterwards."""
        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(
                scheduler={"type": "WarmupLR",
                           "params": {"warmup_num_steps": 100}},
                resilience={"faults": [{"kind": "nan_grads", "step": 2}]}))
        import jax

        train_steps(eng, 2)
        p_before = [np.asarray(x) for x in jax.tree_util.tree_leaves(eng.params)]
        o_before = [np.asarray(x)
                    for x in jax.tree_util.tree_leaves(eng.opt_state)]
        lr_before = eng.get_lr()[0]
        it = data_iter(16)
        loss = eng.forward(next(it))
        eng.backward(loss)
        eng.step()  # global_steps==2 → fault fires → skip
        assert eng.skipped_steps == 1
        assert eng.global_steps == 2
        for got, want in zip(jax.tree_util.tree_leaves(eng.params), p_before):
            np.testing.assert_array_equal(np.asarray(got), want)
        for got, want in zip(jax.tree_util.tree_leaves(eng.opt_state), o_before):
            np.testing.assert_array_equal(np.asarray(got), want)
        assert eng.get_lr()[0] == lr_before  # the LR rewind
        losses = train_steps(eng, 2, seed=5)
        assert all(np.isfinite(losses))
        rep = eng.resilience_report()
        assert rep["guard"]["bad_steps_skipped"] == 1
        assert rep["faults_fired"] == ["nan_grads@grads:step=2"]

    def test_persistent_nan_aborts_to_agent(self, eight_devices, tmp_path):
        """Every step poisoned: after max_consecutive_bad_steps the guard
        writes the report and raises for the elastic agent."""
        os.environ["DSTPU_CHECKPOINT_DIR"] = str(tmp_path)
        try:
            eng, *_ = ds.initialize(
                model=TransformerLM(get_preset("tiny")),
                config=make_config(resilience={
                    "max_consecutive_bad_steps": 2,
                    "faults": [{"kind": "nan_grads", "step": -1,
                                "times": 99}]}))
            with pytest.raises(TooManyBadSteps):
                train_steps(eng, 3)
        finally:
            del os.environ["DSTPU_CHECKPOINT_DIR"]
        rep = json.load(open(tmp_path / "resilience_report.json"))
        assert rep["aborted"] is True
        assert rep["guard"]["bad_steps_skipped"] == 2
        assert rep["consecutive_bad_steps"] == 2

    def test_injected_soft_crash(self, eight_devices):
        from deepspeed_tpu.resilience import InjectedCrash

        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(resilience={
                "faults": [{"kind": "crash", "step": 1}]}))
        train_steps(eng, 1)
        with pytest.raises(InjectedCrash):
            train_steps(eng, 1)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

class TestCheckpointManager:
    def test_latest_pointer_atomic(self, tmp_path):
        from deepspeed_tpu.runtime.checkpoint import (read_latest_tag,
                                                      write_latest_atomic)

        write_latest_atomic(str(tmp_path), "global_step1")
        write_latest_atomic(str(tmp_path), "global_step2")
        assert read_latest_tag(str(tmp_path)) == "global_step2"
        # no torn tmp residue
        assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []

    def test_manifest_verification(self, tmp_path, eight_devices):
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=make_config())
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))
        tag_dir = str(tmp_path / "global_step1")
        ok, why = verify_tag_dir(tag_dir)
        assert ok, why
        tear_checkpoint_dir(tag_dir, mode="corrupt")
        ok, why = verify_tag_dir(tag_dir)
        assert not ok and "mismatch" in why

    def test_torn_newest_falls_back_to_previous_tag(self, tmp_path,
                                                    eight_devices):
        """The acceptance drill: newest checkpoint torn → load steps back to
        the previous verified tag instead of crashing."""
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=make_config())
        train_steps(eng, 2)
        eng.save_checkpoint(str(tmp_path))          # global_step2 (good)
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))          # global_step3 (newest)
        tear_checkpoint_dir(str(tmp_path / "global_step3"), mode="truncate")

        eng2, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                 config=make_config())
        path, _ = eng2.load_checkpoint(str(tmp_path))
        assert path is not None and path.endswith("global_step2")
        assert eng2.global_steps == 2
        rep = eng2.resilience_report()["checkpoint"]
        assert rep["verify_failures"] >= 1
        assert rep["load_fallbacks"] == 1
        # latest was repointed at the good tag
        from deepspeed_tpu.runtime.checkpoint import read_latest_tag

        assert read_latest_tag(str(tmp_path)) == "global_step2"

    def test_keep_last_k_gc(self, tmp_path, eight_devices):
        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(resilience={"checkpoint": {"keep_last_k": 2}}))
        for _ in range(4):
            train_steps(eng, 1)
            eng.save_checkpoint(str(tmp_path))
        tags = sorted(d for d in os.listdir(tmp_path)
                      if os.path.isdir(tmp_path / d))
        assert tags == ["global_step3", "global_step4"]
        assert eng.resilience_report()["checkpoint"]["gc_removed"] == 2
        for t in tags:
            ok, why = verify_tag_dir(str(tmp_path / t))
            assert ok, why

    def test_io_error_retried(self, tmp_path, eight_devices):
        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(resilience={
                "retry": {"max_attempts": 3, "base_delay_s": 0.001},
                "faults": [{"kind": "io_error", "times": 2}]}))
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))  # survives 2 injected IO errors
        assert eng.resilience_report()["checkpoint"]["io_retries"] == 2
        ok, why = verify_tag_dir(str(tmp_path / "global_step1"))
        assert ok, why

    def test_legacy_checkpoint_loads_unverified(self, tmp_path,
                                                eight_devices):
        """Tags saved BEFORE resilience was enabled have no manifest; turning
        verification on must warn-and-load them, not strand the run."""
        legacy_cfg = make_config()
        legacy_cfg["resilience"] = {"enabled": False}
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=legacy_cfg)
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))          # no manifest written

        eng2, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                 config=make_config())
        path, _ = eng2.load_checkpoint(str(tmp_path))
        assert path is not None and eng2.global_steps == 1

    def test_fp16_overflow_calibration_not_aborted(self, eight_devices):
        """fp16 dynamic-scale walk-down overflows are the loss scaler
        working; they must not burn the guard's abort budget."""
        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(
                0, {"dp": 8},
                fp16={"enabled": True, "initial_scale_power": 126},
                bf16={"enabled": False},
                resilience={"max_consecutive_bad_steps": 1}))
        losses = train_steps(eng, 3)  # pre-fix: TooManyBadSteps on step 1
        assert eng.skipped_steps >= 1
        assert float(eng.scaler_state["scale"]) < 2.0 ** 126
        assert np.isfinite(losses[-1])

    def test_sigterm_emergency_save_is_loadable(self, tmp_path,
                                                eight_devices):
        """SIGTERM mid-training → emergency checkpoint at the next step
        boundary → a fresh engine resumes from it."""
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=make_config())
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))  # creates the manager + handler
        os.kill(os.getpid(), signal.SIGTERM)
        train_steps(eng, 1)                 # boundary fires the armed save
        assert eng.resilience_report()["checkpoint"]["emergency_saves"] == 1
        tags = [d for d in os.listdir(tmp_path) if d.startswith("preempt")]
        assert tags == ["preempt_step2"]
        ok, why = verify_tag_dir(str(tmp_path / tags[0]))
        assert ok, why

        eng2, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                 config=make_config())
        path, _ = eng2.load_checkpoint(str(tmp_path))
        assert path is not None and path.endswith("preempt_step2")
        assert eng2.global_steps == 2
        losses = train_steps(eng2, 1, seed=3)
        assert np.isfinite(losses[0])


# ---------------------------------------------------------------------------
# Multi-host coordination (simulated processes)
# ---------------------------------------------------------------------------

class ThreadFleet:
    """Barrier-backed max-reduce over N thread-simulated processes — the test
    stand-in for ``comm.all_reduce_host(code, op=MAX)`` on a real slice."""

    def __init__(self, n):
        self.n = n
        self.barrier = threading.Barrier(n, timeout=30)
        self.vals = [0] * n

    def reducer(self, rank):
        def reduce(code):
            self.vals[rank] = int(code)
            self.barrier.wait()
            out = max(self.vals)
            self.barrier.wait()   # nobody rearms vals before everyone read
            return out
        return reduce

    def run(self, proc):
        """Run ``proc(rank)`` on N threads; re-raise the first failure."""
        errors = []

        def body(rank):
            try:
                proc(rank)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=body, args=(r,))
                   for r in range(self.n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if errors:
            raise errors[0]


def _fake_engine(step=5):
    """The minimal engine surface ``CheckpointManager.save`` touches —
    lets coordination drills run one simulated process per thread without
    paying an XLA compile per 'host'."""
    import jax.numpy as jnp

    return SimpleNamespace(
        params={"w": jnp.arange(4.0)},
        opt_state={"m": jnp.zeros(4)},
        scaler_state={"scale": jnp.float32(1.0), "good_steps": jnp.int32(0)},
        global_steps=step, global_samples=step * 8, micro_steps=step,
        skipped_steps=0, zero_stage=0,
        topology=SimpleNamespace(axis_sizes={}),
        lr_scheduler=None, _offload=None, _pending_ckpt=None,
        config=SimpleNamespace(checkpoint=SimpleNamespace(async_save=False)))


class TestCoordination:
    def test_divergent_preempt_signal_commits_identical_tag(self, tmp_path):
        """The acceptance drill: one simulated process gets the SIGTERM, its
        peer does not — the max-reduce turns the split-brain into a fleet
        SAVE, and every process commits the IDENTICAL tag with the decision
        recorded in its manifest."""
        fleet = ThreadFleet(2)
        tags = [None, None]

        def proc(rank):
            eng = _fake_engine(step=5)
            mgr = CheckpointManager(str(tmp_path / f"host{rank}"))
            coord = ResilienceCoordinator(reduce_fn=fleet.reducer(rank))
            if rank == 0:
                mgr.preempted = True          # only host 0 was preempted
            local = SAVE if mgr.preempted else CONTINUE
            decision = coord.decide(eng.global_steps, local,
                                    "preemption notice" if local else "")
            assert decision == SAVE           # ...but BOTH agree to save
            mgr.preempted = False
            tag = f"preempt_step{eng.global_steps}"
            mgr.save(eng, tag=tag, emergency=True,
                     decision=coord.decision_record())
            tags[rank] = tag

        fleet.run(proc)
        assert tags[0] == tags[1] == "preempt_step5"
        from deepspeed_tpu.runtime.checkpoint import read_latest_tag

        for rank in range(2):
            host = tmp_path / f"host{rank}"
            ok, why = verify_tag_dir(str(host / tags[rank]))
            assert ok, why
            assert read_latest_tag(str(host)) == tags[rank]
            manifest = json.load(open(host / tags[rank] / "manifest.json"))
            # the decision + step are fleet-identical; the reason is local
            # (only the code crosses the wire) — the unsignaled peer records
            # that it acted on a peer's signal
            assert manifest["coordination"]["decision"] == "SAVE"
            assert manifest["coordination"]["step"] == 5
        m0 = json.load(open(tmp_path / "host0" / tags[0] / "manifest.json"))
        m1 = json.load(open(tmp_path / "host1" / tags[1] / "manifest.json"))
        assert m0["coordination"]["reason"] == "preemption notice"
        assert m1["coordination"]["reason"] == "peer signal"

    def test_peer_abort_vote_reaches_everyone(self):
        """An abort signaled on ONE process (watchdog hang, guard budget)
        aborts EVERY process at the same agreement step."""
        fleet = ThreadFleet(3)
        decisions = [None] * 3

        def proc(rank):
            coord = ResilienceCoordinator(reduce_fn=fleet.reducer(rank))
            if rank == 1:
                coord.signal_abort("hang: stuck collective all_reduce_host")
            decisions[rank] = coord.decide(7)

        fleet.run(proc)
        assert decisions == [ABORT, ABORT, ABORT]

    def test_abort_dominates_save(self):
        """One host preempted, another wedged: the fleet must ABORT (the
        wedged host cannot participate in a coherent save)."""
        fleet = ThreadFleet(2)
        decisions = [None, None]

        def proc(rank):
            coord = ResilienceCoordinator(reduce_fn=fleet.reducer(rank))
            (coord.signal_save if rank == 0 else coord.signal_abort)("x")
            decisions[rank] = coord.decide(3)

        fleet.run(proc)
        assert decisions == [ABORT, ABORT]

    def test_interval_holds_signal_until_scheduled_step(self):
        coord = ResilienceCoordinator(reduce_fn=lambda c: c, interval_steps=2)
        coord.signal_save("preempt")
        assert coord.decide(3) == CONTINUE    # off-interval: held, not lost
        assert coord.decide(4) == SAVE        # scheduled boundary: fires
        assert coord.counters["collectives"] == 1

    def test_single_process_decide_rides_comm_hooks(self):
        """Decide goes through ``all_reduce_host`` even at world=1, so the
        fault-injection and retry plumbing applies to the decision plane."""
        set_injector(FaultInjector([{"kind": "failed_collective", "times": 1}]))
        comm.set_retry_policy(RetryPolicy(max_attempts=2, base_delay_s=0.001))
        coord = ResilienceCoordinator()
        coord.signal_abort("drill")
        assert coord.decide(1) == ABORT
        assert comm.get_retry_stats()["retries"] >= 1


# ---------------------------------------------------------------------------
# Async checkpointing (manifest-committed background saves)
# ---------------------------------------------------------------------------

class TestAsyncCheckpoint:
    CFG = {"checkpoint": {"async_save": True}}

    def test_async_save_commits_in_background(self, tmp_path, eight_devices):
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=make_config(resilience=dict(self.CFG)))
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))
        mgr = eng._primary_mgr
        assert mgr.counters["async_saves"] == 1
        mgr.drain()
        from deepspeed_tpu.runtime.checkpoint import read_latest_tag

        ok, why = verify_tag_dir(str(tmp_path / "global_step1"))
        assert ok, why
        assert read_latest_tag(str(tmp_path)) == "global_step1"
        assert not (tmp_path / "global_step1" / STAGING_FILE).exists()
        rep = eng.resilience_report()
        # satellite: one call returns the full picture
        assert rep["checkpoint"]["async_saves"] == 1
        assert rep["checkpoint_async"]["commits"] == 1
        assert rep["checkpoint_async"]["last_latency_s"] > 0
        assert "retries" in rep["comm"] and "inflight" in rep["comm"]
        assert rep["coordination"]["counters"]["collectives"] >= 1
        eng.shutdown()

    def test_crash_between_stage_and_commit_falls_back(self, tmp_path,
                                                       eight_devices):
        """The acceptance drill: the commit thread dies between the staged
        data and the manifest — after 'restart', load lands on the PREVIOUS
        verified tag and the staged tag is rejected, not mistaken for a
        legacy pre-manifest checkpoint."""
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=make_config(resilience=dict(self.CFG)))
        train_steps(eng, 2)
        eng.save_checkpoint(str(tmp_path))          # global_step2
        eng._primary_mgr.drain()                    # committed + verified
        train_steps(eng, 1)
        set_injector(FaultInjector(
            [{"kind": "io_error", "site": "async_commit"}]))
        eng.save_checkpoint(str(tmp_path))          # global_step3: stage only
        eng._primary_mgr.drain(raise_on_error=False)
        set_injector(None)
        assert eng._primary_mgr.counters["async_commit_failures"] == 1
        from deepspeed_tpu.runtime.checkpoint import read_latest_tag

        assert (tmp_path / "global_step3" / STAGING_FILE).exists()
        assert not (tmp_path / "global_step3" / "manifest.json").exists()
        assert read_latest_tag(str(tmp_path)) == "global_step2"

        # restart-and-load: the previous verified tag comes back
        eng2, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                 config=make_config(resilience=dict(self.CFG)))
        path, _ = eng2.load_checkpoint(str(tmp_path))
        assert path is not None and path.endswith("global_step2")
        assert eng2.global_steps == 2
        # asking for the staged tag explicitly is refused, not half-loaded
        with pytest.raises(RuntimeError, match="uncommitted async stage"):
            eng2.load_checkpoint(str(tmp_path), tag="global_step3")
        assert eng2.resilience_report()["checkpoint"]["staged_rejected"] == 1
        eng2.shutdown()
        eng.shutdown()

    def test_emergency_save_drains_pending_and_commits_sync(self, tmp_path,
                                                            eight_devices):
        """SIGTERM with an async commit in flight: the emergency save fences
        the committer first and commits synchronously — the grace window
        never races a background thread."""
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=make_config(resilience=dict(self.CFG)))
        train_steps(eng, 1)
        eng.save_checkpoint(str(tmp_path))          # async, maybe in flight
        os.kill(os.getpid(), signal.SIGTERM)
        train_steps(eng, 1)                         # boundary: agreed SAVE
        mgr = eng._primary_mgr
        assert mgr.counters["emergency_saves"] == 1
        assert mgr._pending_async is None
        ok, why = verify_tag_dir(str(tmp_path / "preempt_step2"))
        assert ok, why
        manifest = json.load(open(tmp_path / "preempt_step2" / "manifest.json"))
        assert manifest["coordination"]["decision"] == "SAVE"
        assert manifest["coordination"]["step"] == 2
        eng.shutdown()


# ---------------------------------------------------------------------------
# Heartbeat + hang watchdog
# ---------------------------------------------------------------------------

class TestHeartbeatWatchdog:
    def _cfg(self, tmp_path, faults=None, **hb):
        base = {"enabled": True, "dir": str(tmp_path / "hb"),
                "interval_s": 0.05, "poll_s": 0.05,
                "deadline_s": 30.0, "collective_deadline_s": None}
        base.update(hb)
        res = {"heartbeat": base}
        if faults:
            res["faults"] = faults
        return make_config(resilience=res)

    def test_stall_escalates_to_coordinated_abort(self, tmp_path,
                                                  eight_devices):
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=self._cfg(tmp_path, deadline_s=0.4))
        train_steps(eng, 1)       # arm: stall detection needs one boundary
        time.sleep(0.8)           # wedge the 'step loop'
        with pytest.raises(CoordinatedAbort):
            train_steps(eng, 1)   # next boundary: fleet-agreed ABORT
        rep = eng.resilience_report()
        assert rep["aborted"] is True
        assert rep["heartbeat"]["counters"]["hangs_detected"] == 1
        assert rep["coordination"]["last_reason"].startswith("hang")
        assert "no step boundary" in rep["heartbeat"]["last_cause"]
        # the liveness file is on disk for peers/operators
        hb = json.load(open(tmp_path / "hb" / "heartbeat_0.json"))
        assert hb["rank"] == 0 and hb["step"] >= 1
        eng.shutdown()

    def test_stuck_collective_classified_and_aborted(self, tmp_path,
                                                     eight_devices):
        """A host collective that outlives its deadline (injected
        slow_collective riding the decision reduce) is detected WHILE in
        flight, classified by name, and escalated."""
        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=self._cfg(tmp_path, collective_deadline_s=0.15,
                             faults=[{"kind": "slow_collective",
                                      "delay_s": 0.6}]))
        with pytest.raises(CoordinatedAbort):
            train_steps(eng, 2)
        rep = eng.resilience_report()
        assert rep["heartbeat"]["counters"]["stuck_collectives"] >= 1
        assert "all_reduce_host" in rep["heartbeat"]["last_cause"]
        eng.shutdown()

    def test_startup_compile_does_not_trip_stall_deadline(self, tmp_path,
                                                          eight_devices):
        """XLA compilation before the first boundary routinely exceeds any
        step deadline; the watchdog must stay disarmed until step 1."""
        eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                                config=self._cfg(tmp_path, deadline_s=0.05))
        time.sleep(0.3)           # 'compiling' — way past the deadline
        assert eng._watchdog.hang_detected is False
        losses = train_steps(eng, 1)
        assert np.isfinite(losses[0])
        eng.shutdown()


# ---------------------------------------------------------------------------
# Monitor surfacing (resilience/* event stream)
# ---------------------------------------------------------------------------

class TestMonitorEvents:
    def test_resilience_counters_flow_through_csv_monitor(self, tmp_path,
                                                          eight_devices):
        """ROADMAP item: resilience counters surface through the monitor
        backends — `resilience/*` gauges land in the CSV backend at the
        steps_per_print cadence."""
        eng, *_ = ds.initialize(
            model=TransformerLM(get_preset("tiny")),
            config=make_config(
                steps_per_print=1,
                monitor_config={"csv_monitor": {
                    "enabled": True, "output_path": str(tmp_path / "csv"),
                    "job_name": "drill"}},
                resilience={"faults": [{"kind": "nan_grads", "step": 1}]}))
        train_steps(eng, 2)   # one skipped (injected), two committed
        out = tmp_path / "csv" / "drill"
        names = {p.name for p in out.iterdir()}
        assert "resilience_skipped_steps.csv" in names
        assert "resilience_guard_bad_steps_skipped.csv" in names
        assert "resilience_comm_retries.csv" in names
        rows = (out / "resilience_skipped_steps.csv").read_text().splitlines()
        # header + one row per printed step; the last gauge shows the skip
        assert rows[0].startswith("step,value")
        assert float(rows[-1].split(",")[1]) == 1.0
        eng.shutdown()


# ---------------------------------------------------------------------------
# Elastic agent decision loop
# ---------------------------------------------------------------------------

class TestAgentDecisions:
    ECFG = {"max_train_batch_size": 32, "micro_batch_sizes": [1, 2, 4],
            "min_gpus": 1, "max_gpus": 8, "prefer_larger_batch": True}

    def test_gives_up_on_deterministic_abort(self, tmp_path):
        """Two step-guard aborts at the same step with the same exit code →
        respawning is pointless; the agent stops early with budget left."""
        report = str(tmp_path / "resilience_report.json")

        def spawn(chips, micro, idx):
            json.dump({"aborted": True, "global_steps": 5},
                      open(report, "w"))
            return 17

        agent = ElasticAgent(self.ECFG, max_restarts=5, report_path=report)
        res = agent.run(spawn, chips=8)
        assert not res.succeeded
        assert "deterministic failure" in res.gave_up_reason
        assert len(res.history) == 2  # gave up well under the budget of 5

    def test_respawns_when_progress_made(self, tmp_path):
        """Aborts at ADVANCING steps are worth respawning (data-dependent
        NaN moving past the bad batch via the fallback checkpoint)."""
        report = str(tmp_path / "resilience_report.json")
        steps = iter([3, 6, 9])

        def spawn(chips, micro, idx):
            json.dump({"aborted": True, "global_steps": next(steps)},
                      open(report, "w"))
            return 17 if idx < 2 else 0

        agent = ElasticAgent(self.ECFG, max_restarts=5, report_path=report)
        res = agent.run(spawn, chips=8)
        assert res.succeeded and res.restarts == 2

    def test_hang_abort_always_respawns(self, tmp_path):
        """Hang-triggered coordinated aborts are environmental, not
        deterministic: identical steps + identical exit codes must still get
        their respawn (the wedge was a lost host, not a poisoned batch)."""
        report = str(tmp_path / "resilience_report.json")
        calls = []

        def spawn(chips, micro, idx):
            json.dump({"aborted": True, "global_steps": 5,
                       "coordination": {"last_reason":
                                        "hang: stuck collective"}},
                      open(report, "w"))
            calls.append(idx)
            return 17 if idx < 2 else 0

        agent = ElasticAgent(self.ECFG, max_restarts=5, report_path=report)
        res = agent.run(spawn, chips=8)
        assert res.succeeded and res.restarts == 2  # no early give-up

    def test_restart_cap_stops_hot_loop(self):
        calls = []
        agent = ElasticAgent(self.ECFG, max_restarts=2,
                             respawn_backoff_s=0.001)
        res = agent.run(lambda c, m, i: calls.append(i) or 9, chips=8)
        assert not res.succeeded
        assert len(calls) == 3  # initial + 2 respawns, then the cap
        assert res.gave_up_reason == "restart budget spent"


# ---------------------------------------------------------------------------
# End-to-end recovery (the acceptance scenario)
# ---------------------------------------------------------------------------

TRAINER = textwrap.dedent("""
    import json, os, sys
    chips = int(os.environ["DSTPU_ELASTIC_CHIPS"])
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={chips}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.resilience import TooManyBadSteps

    ckpt = os.environ["DSTPU_CHECKPOINT_DIR"]
    restart = int(os.environ["DSTPU_RESTART_COUNT"])
    # restart 0: tear the step-3 checkpoint as it commits, then lose the
    # host DURING step 4 (the crash fault keys on global_steps, which still
    # reads 3 inside step 4 — before the step-4 save can land).
    # restart 1: clean run, but one NaN step to heal.
    faults = ([{"kind": "torn_checkpoint", "step": 3},
               {"kind": "crash", "step": 3, "hard": True, "exit_code": 43}]
              if restart == 0 else
              [{"kind": "nan_grads", "step": 4}])
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "elasticity": {"enabled": True, "max_train_batch_size": 32,
                       "micro_batch_sizes": [1, 2, 4],
                       "min_gpus": 1, "max_gpus": 8},
        "resilience": {"enabled": True, "faults": faults,
                       "checkpoint": {"keep_last_k": 3}},
        "mesh": {"fsdp": chips}, "steps_per_print": 100})
    if os.path.exists(os.path.join(ckpt, "latest")):
        eng.load_checkpoint(ckpt)
    rec = {"chips": chips, "global_batch": eng.train_batch_size(),
           "micro": eng.train_micro_batch_size_per_gpu(),
           "start_step": eng.global_steps}
    rng = np.random.default_rng(0)
    B = eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size
    while eng.global_steps < 6:
        for _ in range(eng.gradient_accumulation_steps()):
            loss = eng.forward({"input_ids": rng.integers(0, 256, (B, 16))})
            eng.backward(loss)
        eng.step()
        eng.save_checkpoint(ckpt)
    rec["end_step"] = eng.global_steps
    rec["report"] = eng.resilience_report()
    eng.write_resilience_report(ckpt)
    json.dump(rec, open(os.path.join(ckpt, f"run{restart}.json"), "w"))
""")


def test_e2e_crash_torn_checkpoint_recovery(tmp_path):
    """Acceptance: host crash at step 4 + torn step-3 checkpoint. The agent
    respawns at a smaller world size; the trainer's load falls back from the
    torn step-3 tag to the verified step-2 tag, heals one injected NaN step,
    and reaches step 6 with the global batch constant and the report showing
    the crash/fallback/skip counts."""
    script = tmp_path / "trainer.py"
    script.write_text(TRAINER)
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
        + os.pathsep + env.get("PYTHONPATH", ""))
    agent = ElasticAgent(
        {"max_train_batch_size": 32, "micro_batch_sizes": [1, 2, 4],
         "min_gpus": 1, "max_gpus": 8, "prefer_larger_batch": True},
        max_restarts=2, respawn_backoff_s=0.01,
        report_path=os.path.join(ckpt, "resilience_report.json"))
    res = agent.run(subprocess_spawn(str(script), [], env, ckpt), chips=8,
                    lost_per_failure=4)
    assert res.succeeded, [h.exit_code for h in res.history]
    assert res.restarts == 1
    assert [h.exit_code for h in res.history] == [43, 0]
    assert [h.chips for h in res.history] == [8, 4]

    rec = json.load(open(os.path.join(ckpt, "run1.json")))
    # resumed from the VERIFIED step-2 tag, not the torn step-3 one
    assert rec["start_step"] == 2, rec
    assert rec["end_step"] == 6
    assert rec["global_batch"] == res.history[0].global_batch
    report = rec["report"]
    assert report["checkpoint"]["verify_failures"] >= 1
    assert report["checkpoint"]["load_fallbacks"] == 1
    assert report["guard"]["bad_steps_skipped"] == 1  # the healed NaN step
    assert report["skipped_steps"] == 1
    # the agent saw the same report (its respawn-vs-give-up input)
    assert res.history[1].report["checkpoint"]["load_fallbacks"] == 1


def test_signal_counters_survive_thread_contention():
    """dslint burn-down (lock-discipline): ``signal_save``/``signal_abort``
    used to bump ``counters`` BEFORE taking ``_lock`` — a dict-slot ``+=``
    is read/add/store, so concurrent signal threads (SIGTERM handler,
    watchdog, guard) lost increments. The counters are ``guarded_by:
    _lock`` now; under a hostile switch interval every increment must
    land."""
    import sys

    from deepspeed_tpu.resilience.coordinator import ResilienceCoordinator

    coord = ResilienceCoordinator(reduce_fn=lambda c: c)
    n_threads, n_each = 8, 400
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)       # force preemption inside the +=
    try:
        def hammer():
            for _ in range(n_each):
                coord.signal_save("t")
                coord.signal_abort("t")
        ts = [threading.Thread(target=hammer) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert coord.counters["signals_save"] == n_threads * n_each
    assert coord.counters["signals_abort"] == n_threads * n_each
    # the pending escalation itself also made it through intact
    assert coord.decide(0) == ABORT
