"""dslint static-analysis suite (ISSUE 11).

Three layers:

* per-checker fixture snippets — every rule proves at least one TRUE
  POSITIVE (the bug class it exists for) and at least one FALSE-POSITIVE
  GUARD (the nearby-but-correct idiom it must stay quiet on);
* baseline machinery — justification enforcement, fingerprint matching,
  stale reporting;
* the acceptance gates — ``python tools/dslint.py deepspeed_tpu/`` exits 0
  against the checked-in baseline (the tier-1 repo gate), and exits
  NONZERO when a fixture bug of each checker class is injected into a
  scratch file.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from dslint import ALL_CHECKERS, run  # noqa: E402
from dslint.baseline import Baseline, BaselineError  # noqa: E402

pytestmark = pytest.mark.lint


def lint(tmp_path, code, rules=None, name="snippet.py"):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(code))
    return run([str(p)], rules=rules, root=str(tmp_path))


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------
class TestHostSync:
    def test_item_and_cast_inside_jit(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                y = float(x)          # concretizes the tracer
                return y + x.item()   # host sync inside the trace
        """, rules=["host-sync"])
        assert len(fs) == 2
        assert all(f.rule == "host-sync" for f in fs)

    def test_jit_by_assignment_and_np_asarray(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            import numpy as np

            def helper(x):
                return np.asarray(x) + 1

            step = jax.jit(helper)
        """, rules=["host-sync"])
        assert rules_of(fs) == ["host-sync"]

    def test_partial_jit_decorator(self, tmp_path):
        fs = lint(tmp_path, """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def f(x):
                return x.item()
        """, rules=["host-sync"])
        assert rules_of(fs) == ["host-sync"]

    def test_hot_path_flags_and_callee_closure(self, tmp_path):
        # file suffix + qualname matches the configured hot list, and the
        # helper the step calls is hot by closure
        fs = lint(tmp_path, """
            import numpy as np

            class ContinuousBatcher:
                def step(self):
                    return self._emit()

                def _emit(self):
                    return np.asarray(self.logits)
        """, rules=["host-sync"], name="serving/batcher.py")
        assert rules_of(fs) == ["host-sync"]
        assert fs[0].func == "ContinuousBatcher._emit"

    def test_item_outside_jit_is_clean(self, tmp_path):
        # the ISSUE's named false-positive guard
        fs = lint(tmp_path, """
            import numpy as np

            def summarize(arr):
                return arr.mean().item() + float(arr[0])
        """, rules=["host-sync"])
        assert fs == []

    def test_static_casts_inside_jit_are_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                n = int(x.shape[0]) + int(len(x.shape)) + float(1.5)
                return x * n
        """, rules=["host-sync"])
        assert fs == []

    def test_non_hot_file_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import numpy as np

            class ContinuousBatcher:
                def step(self):
                    return np.asarray(self.logits)
        """, rules=["host-sync"], name="somewhere_else.py")
        assert fs == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------
class TestLockDiscipline:
    GUARDED = """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}  #: guarded_by: _lock

            def bad_read(self):
                return len(self._items)

            def good_read(self):
                with self._lock:
                    return len(self._items)

            def helper(self):  #: holds: _lock
                return list(self._items)
    """

    def test_guarded_by_violation_and_exemptions(self, tmp_path):
        fs = lint(tmp_path, self.GUARDED, rules=["lock-discipline"])
        assert len(fs) == 1
        assert fs[0].func == "Registry.bad_read"
        # __init__ assignment, with-lock read, and #: holds: helper are
        # all exempt — exactly one finding

    def test_annotation_on_standalone_line_above(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    #: guarded_by: _lock
                    self._q = []

                def bad(self):
                    self._q.append(1)
        """, rules=["lock-discipline"])
        assert len(fs) == 1 and fs[0].func == "C.bad"

    def test_trailing_comment_does_not_leak_to_next_line(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = []      #: guarded_by: _lock
                    self._free = 0    # unannotated: next line must NOT bind

                def fine(self):
                    return self._free
        """, rules=["lock-discipline"])
        assert fs == []

    def test_lock_order_inversion_reported_both_sites(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def ab(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def ba(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """, rules=["lock-discipline"])
        assert len(fs) == 2
        assert all("inconsistent lock order" in f.message for f in fs)

    def test_consistent_lock_order_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def ab(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def ab2(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
        """, rules=["lock-discipline"])
        assert fs == []


# ---------------------------------------------------------------------------
# resource-lifecycle
# ---------------------------------------------------------------------------
class TestResourceLifecycle:
    def test_unprotected_pool_get_leaks(self, tmp_path):
        fs = lint(tmp_path, """
            class Swapper:
                def swap_out(self, arr):
                    buf = self.pool.get(arr.nbytes)
                    self.submit(buf, arr)        # can raise → buf leaks
                    return self.ticket(buf)
        """, rules=["resource-lifecycle"])
        assert rules_of(fs) == ["resource-lifecycle"]

    def test_unprotected_acquire_leaks(self, tmp_path):
        fs = lint(tmp_path, """
            class Engine:
                def attach(self, toks):
                    blocks = self.cache.acquire(toks)
                    self.state.wire(blocks)      # can raise → refs leak
                    self.finish(blocks)
        """, rules=["resource-lifecycle"])
        assert rules_of(fs) == ["resource-lifecycle"]

    def test_try_finally_release_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            class Swapper:
                def swap_out(self, arr):
                    buf = self.pool.get(arr.nbytes)
                    try:
                        self.submit(buf, arr)
                    finally:
                        self.pool.put(buf)
        """, rules=["resource-lifecycle"])
        assert fs == []

    def test_protected_handoff_idiom_is_clean(self, tmp_path):
        # acquire; try: handoff except: release; raise — the engine's
        # prefix_attach pattern
        fs = lint(tmp_path, """
            class Engine:
                def attach(self, toks):
                    blocks = self.cache.acquire(toks)
                    if not blocks:
                        return 0
                    try:
                        seq = self.state.wire(blocks)
                    except BaseException:
                        self.allocator.free(blocks)
                        raise
                    return seq
        """, rules=["resource-lifecycle"])
        assert fs == []

    def test_contextmanager_acquire_is_clean(self, tmp_path):
        # the ISSUE's named false-positive guard: release handled by the
        # contextmanager helper
        fs = lint(tmp_path, """
            class Worker:
                def work(self, arr):
                    with self.pool.get(arr.nbytes) as buf:
                        self.submit(buf, arr)
        """, rules=["resource-lifecycle"])
        assert fs == []

    def test_demote_acquire_submit_pair_flagged(self, tmp_path):
        # ISSUE 12 regression: the KV-tier demote path's shape — a pinned
        # buffer acquired, then a fallible copy + AIO ticket submit before
        # anything owns the buffer. An exception in either leaks it.
        fs = lint(tmp_path, """
            class TierStore:
                def demote(self, key, parts):
                    buf = self.pool.get(parts.nbytes)
                    buf.data[:parts.nbytes] = parts.tobytes()
                    ticket = self.swapper.swap_out(key, buf.data)
                    self.entries[key] = (buf, ticket)
        """, rules=["resource-lifecycle"])
        assert rules_of(fs) == ["resource-lifecycle"]

    def test_demote_guarded_pair_is_clean(self, tmp_path):
        # the shipped idiom: copy + submit under try, buffer returned on
        # the exception path before the original failure propagates
        fs = lint(tmp_path, """
            class TierStore:
                def demote(self, key, parts):
                    buf = self.pool.get(parts.nbytes)
                    try:
                        buf.data[:parts.nbytes] = parts.tobytes()
                        ticket = self.swapper.swap_out(key, buf.data)
                    except BaseException:
                        self.pool.put(buf)
                        raise
                    self.entries[key] = (buf, ticket)
        """, rules=["resource-lifecycle"])
        assert fs == []

    def test_plain_dict_and_queue_get_are_clean(self, tmp_path):
        fs = lint(tmp_path, """
            class Router:
                def route(self, key):
                    route = self.table.get(key)
                    cmd = self.inbox.get(timeout=1.0)
                    self.handle(route, cmd)
        """, rules=["resource-lifecycle"])
        assert fs == []

    def test_immediate_return_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            class Pooled:
                def lease(self, n):
                    return self.pool.get(n)
        """, rules=["resource-lifecycle"])
        assert fs == []


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------
class TestRecompileHazard:
    def test_jit_and_call_in_one_expression(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def quantize(leaves):
                return [jax.jit(lambda w: w * 2)(leaf) for leaf in leaves]
        """, rules=["recompile-hazard"])
        assert len(fs) >= 2         # jit-and-call AND per-element wrapper
        assert all(f.rule == "recompile-hazard" for f in fs)

    def test_jit_inside_loop(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def fit(fns, xs):
                out = []
                for fn in fns:
                    g = jax.jit(fn)
                    out.append(g(xs))
                return out
        """, rules=["recompile-hazard"])
        assert rules_of(fs) == ["recompile-hazard"]

    def test_unhashable_static_arg(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            def build(f, x):
                g = jax.jit(f, static_argnums=(1,))
                return g(x, [128, 256])
        """, rules=["recompile-hazard"])
        assert rules_of(fs) == ["recompile-hazard"]
        assert "unhashable" in fs[0].message

    def test_bound_once_and_hashable_static_are_clean(self, tmp_path):
        fs = lint(tmp_path, """
            import jax

            class Engine:
                def __init__(self, model):
                    self._step = jax.jit(model.forward,
                                         static_argnums=(1,))

                def step(self, x):
                    return self._step(x, (128, 256))
        """, rules=["recompile-hazard"])
        assert fs == []


# ---------------------------------------------------------------------------
# control-flow
# ---------------------------------------------------------------------------
class TestControlFlow:
    def test_identical_arg_self_recursion(self, tmp_path):
        # the PR 7 _cancel_quiet delegation typo, distilled
        fs = lint(tmp_path, """
            class Frontend:
                def _cancel_quiet(self, uid):
                    try:
                        self._cancel_quiet(uid)
                    except Exception:
                        pass
        """, rules=["control-flow"])
        assert rules_of(fs) == ["control-flow"]
        assert "infinite recursion" in fs[0].message

    def test_swallowed_base_exception_in_worker_loop(self, tmp_path):
        fs = lint(tmp_path, """
            class Worker:
                def _run(self):
                    while not self._stop.is_set():
                        try:
                            self.step()
                        except BaseException:
                            pass
        """, rules=["control-flow"])
        assert rules_of(fs) == ["control-flow"]

    def test_bare_except_in_loop(self, tmp_path):
        fs = lint(tmp_path, """
            def pump(q):
                while True:
                    try:
                        q.drain()
                    except:
                        continue
        """, rules=["control-flow"])
        assert rules_of(fs) == ["control-flow"]

    def test_guarded_or_progressing_recursion_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            class Retrier:
                def call(self, req, attempts):
                    if attempts > 0:
                        return self.call(req, attempts - 1)
                    return None

            def walk(node):
                for child in node.children:
                    walk(child)
        """, rules=["control-flow"])
        assert fs == []

    def test_reassigned_param_recursion_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            def drain(q):
                q = q.next_view()
                drain(q)
        """, rules=["control-flow"])
        assert fs == []

    def test_exception_hygiene_guards(self, tmp_path):
        fs = lint(tmp_path, """
            def ok_loops(q):
                while True:
                    try:
                        q.drain()
                    except Exception:      # correct spelling: clean
                        pass
                    try:
                        q.pump()
                    except BaseException:  # re-raises: clean
                        q.note()
                        raise

            def outside_loop(q):
                try:
                    q.drain()
                except BaseException:      # not in a worker loop: clean
                    pass
        """, rules=["control-flow"])
        assert fs == []


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------
class TestBaseline:
    def test_justification_is_mandatory(self, tmp_path):
        p = tmp_path / "bl.txt"
        p.write_text("a.py::host-sync::f::x.item()\n")
        with pytest.raises(BaselineError):
            Baseline.load(str(p))
        p.write_text("a.py::host-sync::f::x.item() --   \n")
        with pytest.raises(BaselineError):
            Baseline.load(str(p))

    def test_fingerprint_matching_and_stale(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                return x.item()
        """, rules=["host-sync"])
        assert len(findings) == 1
        fp = findings[0].fingerprint
        p = tmp_path / "bl.txt"
        p.write_text(f"{fp} -- deliberate fixture sync\n"
                     f"gone.py::host-sync::g::y.item() -- healed long ago\n")
        bl = Baseline.load(str(p))
        new, suppressed = bl.split(findings)
        assert new == [] and len(suppressed) == 1
        assert bl.stale_entries() == [
            "gone.py::host-sync::g::y.item()"]

    def test_fingerprint_survives_line_drift(self, tmp_path):
        before = lint(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                return x.item()
        """, rules=["host-sync"], name="a.py")
        after = lint(tmp_path, """
            import jax

            # a new comment block pushing everything down
            # by several lines must not break the baseline


            @jax.jit
            def f(x):
                return x.item()
        """, rules=["host-sync"], name="b.py")
        # same fingerprint modulo the path component
        fa = before[0].fingerprint.split("::", 1)[1]
        fb = after[0].fingerprint.split("::", 1)[1]
        assert fa == fb
        assert before[0].line != after[0].line


# ---------------------------------------------------------------------------
# acceptance gates (CLI, subprocess — exactly what CI and humans run)
# ---------------------------------------------------------------------------
CLI = os.path.join(TOOLS, "dslint.py")

# ---------------------------------------------------------------------------
# event-span (ISSUE 13)
# ---------------------------------------------------------------------------
class TestEventSpan:
    def test_unclosed_begin_before_fallible_work(self, tmp_path):
        fs = lint(tmp_path, """
            class Stepper:
                def step(self):
                    self._ebus.begin("batcher", "step")
                    self.engine.put()            # can raise → span leaks
                    self._ebus.end("batcher", "step")
        """, rules=["event-span"])
        assert rules_of(fs) == ["event-span"]

    def test_raw_emit_begin_phase_flagged(self, tmp_path):
        fs = lint(tmp_path, """
            class Stepper:
                def step(self, bus):
                    bus.emit("B", "engine", "put")
                    self.dispatch()              # can raise → span leaks
                    bus.emit("E", "engine", "put")
        """, rules=["event-span"])
        assert rules_of(fs) == ["event-span"]

    def test_guard_nested_begin_flagged(self, tmp_path):
        # the dominant real emit idiom nests the begin under an
        # `if tracing:` guard — the scan must follow the enclosing
        # blocks out, not just the function's top-level statements
        fs = lint(tmp_path, """
            class Stepper:
                def step(self):
                    if self.tracing:
                        self._ebus.begin("batcher", "step")
                        self.engine.put()    # can raise → span leaks
                        self._ebus.end("batcher", "step")
        """, rules=["event-span"])
        assert rules_of(fs) == ["event-span"]

    def test_guarded_begin_with_fallible_work_after_guard_flagged(
            self, tmp_path):
        fs = lint(tmp_path, """
            class Stepper:
                def step(self):
                    if self.tracing:
                        self._ebus.begin("batcher", "step")
                    self.engine.put()        # can raise → span leaks
                    if self.tracing:
                        self._ebus.end("batcher", "step")
        """, rules=["event-span"])
        assert rules_of(fs) == ["event-span"]

    def test_guarded_trailing_begin_is_clean(self, tmp_path):
        # begin at the END of its guard with nothing fallible after the
        # guard either: the open-at-exit lifecycle handoff, nested
        fs = lint(tmp_path, """
            class Ticket:
                def __init__(self, bus, name):
                    self.name = name
                    if bus.enabled:
                        bus.async_begin("aio", "swap_op", 1)
        """, rules=["event-span"])
        assert fs == []

    def test_try_finally_end_is_clean(self, tmp_path):
        fs = lint(tmp_path, """
            class Stepper:
                def step(self):
                    self._ebus.begin("batcher", "step")
                    try:
                        self.engine.put()
                    finally:
                        self._ebus.end("batcher", "step")
        """, rules=["event-span"])
        assert fs == []

    def test_span_contextmanager_is_clean(self, tmp_path):
        # the blessed idiom: with-block IS the finally
        fs = lint(tmp_path, """
            class Stepper:
                def step(self, bus):
                    with bus.span("batcher", "step"):
                        self.engine.put()
        """, rules=["event-span"])
        assert fs == []

    def test_async_open_at_exit_handoff_is_clean(self, tmp_path):
        # cross-function b/e lifecycle (submit opens, terminal closes):
        # a trailing async_begin with nothing fallible after it is the
        # intended idiom, not a leak
        fs = lint(tmp_path, """
            class Manager:
                def submit(self, req, bus):
                    self.queue.append(req)
                    bus.async_begin("request", "request", req.trace_id)
                    return req.uid

                def finish(self, req, bus):
                    bus.async_end("request", "request", req.trace_id)
        """, rules=["event-span"])
        assert fs == []

    def test_non_bus_begin_is_ignored(self, tmp_path):
        # txn.begin() on a database handle is not an event emit
        fs = lint(tmp_path, """
            class Store:
                def write(self, txn, rows):
                    txn.begin()
                    self.insert(rows)
                    txn.commit()
        """, rules=["event-span"])
        assert fs == []


INJECTED_BUGS = {
    "host-sync": """
        import jax

        @jax.jit
        def f(x):
            return x.item()
    """,
    "lock-discipline": """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  #: guarded_by: _lock

            def bad(self):
                self._q.append(1)
    """,
    "resource-lifecycle": """
        class S:
            def leak(self):
                buf = self.pool.get(4096)
                self.submit(buf)
                return self.ticket(buf)
    """,
    "recompile-hazard": """
        import jax

        def per_call(f, x):
            return jax.jit(f)(x)
    """,
    "control-flow": """
        class F:
            def cancel(self, uid):
                try:
                    self.cancel(uid)
                except Exception:
                    pass
    """,
    "event-span": """
        class S:
            def step(self, bus):
                bus.begin("batcher", "step")
                self.engine.put()
                bus.end("batcher", "step")
    """,
}


def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, CLI] + args, cwd=cwd,
                          capture_output=True, text=True, timeout=300)


def test_repo_is_clean_against_checked_in_baseline():
    """THE tier-1 gate: the whole package vs tools/dslint_baseline.txt.
    A new finding means fix it or add a JUSTIFIED baseline entry."""
    res = _cli(["deepspeed_tpu/"])
    assert res.returncode == 0, (
        "dslint found NEW findings (fix them or add a justified baseline "
        "entry in tools/dslint_baseline.txt):\n" + res.stdout + res.stderr)


def test_repo_gate_runs_every_rule():
    res = _cli(["deepspeed_tpu/", "--json"])
    data = json.loads(res.stdout)
    assert set(data["rules"]) == set(ALL_CHECKERS)
    assert data["files_analyzed"] > 100
    assert data["findings"] == []


@pytest.mark.parametrize("rule", sorted(INJECTED_BUGS))
def test_injected_fixture_bug_fails_the_cli(rule, tmp_path):
    """Acceptance: one fixture bug per checker class injected into a
    scratch file makes the CLI exit nonzero and name the rule."""
    scratch = tmp_path / "scratch.py"
    scratch.write_text(textwrap.dedent(INJECTED_BUGS[rule]))
    res = _cli([str(scratch), "--json"])
    assert res.returncode == 1, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert rule in {f["rule"] for f in data["findings"]}


def test_changed_mode_smoke():
    """--changed analyzes only git-touched files and honors the baseline
    (pre-commit mode). The working tree may be mid-edit here, so accept
    clean or findings — but never a usage/crash exit."""
    res = _cli(["--changed"])
    assert res.returncode in (0, 1), res.stdout + res.stderr
    assert "across" in res.stdout


def test_unjustified_baseline_is_a_hard_error(tmp_path):
    scratch = tmp_path / "scratch.py"
    scratch.write_text(textwrap.dedent(INJECTED_BUGS["host-sync"]))
    bl = tmp_path / "bl.txt"
    bl.write_text("x.py::host-sync::f::return x.item()\n")
    res = _cli([str(scratch), "--baseline", str(bl)])
    assert res.returncode == 2
    assert "justification" in res.stderr


def test_parse_error_is_reported_not_crashed(tmp_path):
    scratch = tmp_path / "broken.py"
    scratch.write_text("def f(:\n")
    res = _cli([str(scratch), "--json"])
    assert res.returncode == 1
    data = json.loads(res.stdout)
    assert [f["rule"] for f in data["findings"]] == ["parse-error"]
