"""Causal event tracing + flight recorder + perf-trend ledger (ISSUE 13).

Four layers:

* event-bus semantics — disabled no-op, deterministic sampling, span
  pairing on every exit path, ring boundedness under an event storm,
  cross-thread appends;
* trace export + grammar — the exporter repairs ring-evicted halves of
  B/E and async pairs, and ``validate_trace`` enforces the drill grammar
  (every B matched on its tid, async ids balanced);
* the flight recorder — dump contents, the exactly-once ``key=`` guard,
  and the bounded-ledger fix (a uid evicted from ``RequestManager.done``
  still resolves through the recorder's retained terminal spans);
* the perf-trend ledger — append/read round-trip and the
  ``bench_trend`` regression gate's verdicts + exit codes.

Slow wrappers at the bottom run ``tools/trace_drill.py`` (storm trace,
abort dump, disabled-no-events) and the ``obs_drill`` tracing-overhead
budget; the CLIs are the invariant authority.
"""

import json
import os
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from deepspeed_tpu.observability import (configure_tracing,  # noqa: E402
                                         flight_dump, get_bus,
                                         get_flight_recorder,
                                         set_flight_recorder, trace_export,
                                         validate_trace)
from deepspeed_tpu.observability.events import EventBus  # noqa: E402
from deepspeed_tpu.observability.trace import FlightRecorder  # noqa: E402

pytestmark = pytest.mark.obs


@pytest.fixture()
def traced(tmp_path):
    """Tracing on for the test, reliably off (and clean) after it —
    tier-1 runs everything in one process."""
    bus = configure_tracing(enabled=True, ring_size=512, sample=1,
                            dump_dir=str(tmp_path / "flight"),
                            retain_terminal=8)
    bus.clear()
    yield bus
    configure_tracing(enabled=False)
    bus.clear()


# ---------------------------------------------------------------------------
# event bus
# ---------------------------------------------------------------------------
class TestEventBus:
    def test_disabled_records_nothing(self):
        bus = EventBus(enabled=False)
        bus.instant("c", "n")
        bus.begin("c", "n")
        with bus.span("c", "s"):
            pass
        assert bus.total_events() == 0
        assert bus.mint_trace() is None

    def test_enabled_records_typed_events(self):
        bus = EventBus(enabled=True, ring_size=64)
        t = bus.mint_trace()
        assert t is not None
        bus.async_begin("request", "request", t, args={"uid": 1})
        bus.instant("c", "mark")
        bus.async_end("request", "request", t)
        evs = bus.events()
        assert [e.ph for e in evs] == ["b", "i", "e"]
        assert evs[0].trace_id == t and evs[0].tid == threading.get_ident()
        assert evs[0].ts <= evs[1].ts <= evs[2].ts

    def test_sampling_is_deterministic(self):
        bus = EventBus(enabled=True, sample=4)
        minted = [bus.mint_trace() for _ in range(16)]
        kept = [t for t in minted if t is not None]
        assert len(kept) == 4                 # exactly every 4th id
        assert all(t % 4 == 0 for t in kept)

    def test_span_closes_on_exception(self):
        bus = EventBus(enabled=True)
        with pytest.raises(ValueError):
            with bus.span("c", "op"):
                raise ValueError("boom")
        evs = bus.events()
        assert [e.ph for e in evs] == ["B", "E"]
        assert "boom" in evs[1].args["error"]

    def test_ring_bounded_under_10k_storm(self):
        bus = EventBus(enabled=True, ring_size=256)
        for i in range(10_000):
            bus.instant("storm", "evt", args={"i": i})
        assert bus.total_events() == 256
        # the ring keeps the NEWEST events
        assert bus.events()[-1].args["i"] == 9_999
        assert bus.events()[0].args["i"] == 10_000 - 256

    def test_cross_thread_appends_and_snapshot(self):
        bus = EventBus(enabled=True, ring_size=4096)
        stop = threading.Event()

        def writer(k):
            i = 0
            while not stop.is_set():
                bus.instant("t", "evt", args={"k": k, "i": i})
                i += 1

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)]
        [t.start() for t in threads]
        try:
            for _ in range(50):               # snapshots race the writers
                evs = bus.events()
                assert all(e.cat == "t" for e in evs)
        finally:
            stop.set()
            [t.join(timeout=5) for t in threads]
        assert bus.total_events() <= 4096

    def test_configure_mutates_in_place(self, tmp_path):
        cached = get_bus()                    # a call site's cached ref
        assert cached.enabled is False
        configure_tracing(enabled=True, ring_size=128,
                          dump_dir=str(tmp_path))
        try:
            assert cached.enabled is True and cached.ring_size == 128
            assert get_flight_recorder() is not None
        finally:
            configure_tracing(enabled=False)
        assert cached.enabled is False and get_flight_recorder() is None
        cached.clear()


# ---------------------------------------------------------------------------
# export + grammar
# ---------------------------------------------------------------------------
class TestTraceExport:
    def test_export_is_grammar_valid(self):
        bus = EventBus(enabled=True)
        t = bus.mint_trace()
        bus.async_begin("request", "request", t)
        with bus.span("batcher", "step"):
            bus.instant("engine", "mark")
        bus.async_end("request", "request", t)
        doc = trace_export(bus)
        assert validate_trace(doc) == []
        assert len(doc["traceEvents"]) == 5
        assert doc["otherData"]["enabled"] is True

    def test_orphans_are_repaired(self):
        bus = EventBus(enabled=True)
        bus.end("c", "stray")                 # E with no B: dropped
        bus.begin("c", "open")                # B with no E: closed
        bus.async_end("a", "x", 7)            # stray async e: dropped
        bus.async_begin("a", "y", 8)          # open async b: closed
        doc = trace_export(bus)
        assert validate_trace(doc) == []
        phs = sorted(e["ph"] for e in doc["traceEvents"])
        assert phs == ["B", "E", "b", "e"]
        synth = [e for e in doc["traceEvents"]
                 if e.get("args", {}).get("synthetic_end")]
        assert len(synth) == 2

    def test_validator_catches_violations(self):
        base = {"cat": "c", "name": "n", "ts": 1, "pid": 1, "tid": 1}
        assert validate_trace({}) != []
        assert validate_trace(
            {"traceEvents": [{**base, "ph": "E"}]})        # E w/o B
        assert validate_trace(
            {"traceEvents": [{**base, "ph": "b"}]})        # b w/o id or e
        assert validate_trace(
            {"traceEvents": [{**base, "ph": "Z"}]})        # unknown phase
        assert validate_trace(
            {"traceEvents": [{**base, "ph": "i", "ts": -5}]})  # bad ts
        ok = [{**base, "ph": "B"}, {**base, "ph": "E", "ts": 2},
              {**base, "ph": "b", "id": 1},
              {**base, "ph": "e", "id": 1, "ts": 3}]
        assert validate_trace({"traceEvents": ok}) == []


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_dump_carries_events_and_terminals(self, tmp_path):
        bus = EventBus(enabled=True)
        rec = FlightRecorder(bus, str(tmp_path), retain_terminal=4)
        bus.instant("resilience", "bad_step", args={"step": 3})
        rec.record_terminal(11, {"uid": 11, "state": "completed"})
        path = rec.dump("unit", extra={"why": "test"})
        assert path and os.path.exists(path)
        doc = json.load(open(path))
        assert doc["reason"] == "unit" and doc["extra"] == {"why": "test"}
        assert validate_trace(doc["trace"]) == []
        assert doc["terminal_spans"]["11"]["state"] == "completed"
        names = [e["name"] for e in doc["trace"]["traceEvents"]]
        assert "bad_step" in names

    def test_key_dedups_one_incident(self, tmp_path):
        rec = FlightRecorder(EventBus(enabled=True), str(tmp_path))
        p1 = rec.dump("abort", key="abort-step5")
        p2 = rec.dump("abort", key="abort-step5")   # second layer, same
        p3 = rec.dump("abort", key="abort-step6")   # a NEW incident
        assert p1 and p2 is None and p3
        assert rec.dumps == 2

    def test_terminal_retention_is_bounded(self, tmp_path):
        rec = FlightRecorder(EventBus(), str(tmp_path), retain_terminal=3)
        for uid in range(10):
            rec.record_terminal(uid, {"uid": uid})
        assert rec.terminal_trace(0) is None
        assert sorted(rec.terminal_spans()) == [7, 8, 9]

    def test_flight_dump_helper_without_recorder(self):
        set_flight_recorder(None)
        assert flight_dump("nothing") is None


# ---------------------------------------------------------------------------
# bounded terminal ledger + recorder fallback (the ISSUE 13 fix)
# ---------------------------------------------------------------------------
class TestBoundedLedger:
    def _manager(self, max_done):
        from deepspeed_tpu.serving.manager import RequestManager

        return RequestManager(max_queue_depth=64, max_done_history=max_done)

    def test_eviction_keeps_traces_resolvable(self, traced):
        mgr = self._manager(max_done=2)
        uids = [mgr.submit([1, 2, 3]) for _ in range(6)]
        for u in uids:
            assert mgr.cancel(u)
        assert len(mgr.done) == 2             # ledger bounded
        for u in uids:                        # ALL uids still answer
            assert mgr.resolve(u) == "cancelled"
            tr = mgr.trace(u)
            assert tr is not None and tr["state"] == "cancelled"

    def test_eviction_without_recorder_is_bounded_but_forgets(self):
        mgr = self._manager(max_done=2)
        uids = [mgr.submit([1, 2, 3]) for _ in range(4)]
        for u in uids:
            mgr.cancel(u)
        assert len(mgr.done) == 2
        assert mgr.resolve(uids[-1]) == "cancelled"
        assert mgr.resolve(uids[0]) is None   # documented: no recorder

    def test_request_track_events_balance(self, traced):
        mgr = self._manager(max_done=64)
        u = mgr.submit([1, 2, 3, 4])
        mgr.cancel(u)
        doc = trace_export(traced)
        assert validate_trace(doc) == []
        req = [e for e in doc["traceEvents"] if e["cat"] == "request"]
        assert [e["ph"] for e in req] == ["b", "e"]
        assert req[0]["args"]["uid"] == u
        assert req[1]["args"]["state"] == "cancelled"

    def test_queued_uid_membership_mirror(self):
        # the router's GIL-atomic liveness probe: a uid is ALWAYS in at
        # least one of _queued_uids/active/done across its lifecycle
        mgr = self._manager(max_done=8)
        u = mgr.submit([1, 2])
        assert u in mgr._queued_uids
        req = mgr.queue[0]
        mgr.admit(req)
        assert u not in mgr._queued_uids and u in mgr.active
        mgr.release_fn = lambda uids: None
        mgr.complete(req)
        assert u in mgr.done and u not in mgr._queued_uids


# ---------------------------------------------------------------------------
# serving e2e: causal chain + /v1/trace over HTTP
# ---------------------------------------------------------------------------
def test_traced_serving_chain_and_http_export(tmp_path):
    import urllib.request

    import numpy as np

    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.observability import MetricsRegistry
    from deepspeed_tpu.serving import ContinuousBatcher

    bus = configure_tracing(enabled=True, ring_size=2048, sample=1,
                            dump_dir=str(tmp_path / "flight"))
    bus.clear()
    try:
        eng = InferenceEngineV2(TransformerLM(get_preset("tiny")),
                                max_sequences=8, max_seq_len=128,
                                block_size=16)
        b = ContinuousBatcher(eng, ServingConfig(
            prefill_chunk=32, default_max_new_tokens=4),
            registry=MetricsRegistry())
        rng = np.random.default_rng(0)
        uids = [b.submit(rng.integers(0, 250, 24)) for _ in range(3)]
        b.pump(max_steps=100)
        assert all(b.manager.resolve(u) == "completed" for u in uids)
        # per-request async track spans serving + batcher subsystems, and
        # joins the engine's put spans by uid
        req = [e for e in bus.events(["request"])]
        by_trace = {}
        for e in req:
            if e.args and "subsys" in e.args:
                by_trace.setdefault(e.trace_id, set()).add(
                    e.args["subsys"])
        assert by_trace and all({"serving", "batcher"} <= s
                                for s in by_trace.values())
        eng_uids = set()
        for e in bus.events(["engine"]):
            if e.ph == "B" and e.args:
                eng_uids.update(e.args.get("uids", ()))
        assert set(uids) <= eng_uids
        # the /v1/trace mount serves the same document over HTTP
        srv = b.serve_metrics_http()
        try:
            resp = urllib.request.urlopen(srv.url + "/v1/trace", timeout=10)
            doc = json.loads(resp.read().decode())
        finally:
            b.close()
        assert resp.status == 200
        assert validate_trace(doc) == []
        assert any(e["cat"] == "batcher" and e["name"] == "step"
                   for e in doc["traceEvents"])
    finally:
        configure_tracing(enabled=False)
        bus.clear()


# ---------------------------------------------------------------------------
# perf-trend ledger + bench_trend gate
# ---------------------------------------------------------------------------
class TestBenchLedger:
    def _entry(self, bench, value, sha, t, result=None):
        return {"schema": 1, "bench": bench, "git_sha": sha, "time": t,
                "iso_time": "x", "metric": "m", "value": value,
                "unit": "u", "result": result or {"value": value}}

    def test_append_and_read_roundtrip(self, tmp_path, monkeypatch):
        from bench_ledger import append_ledger, read_ledger

        path = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("DSTPU_BENCH_LEDGER_PATH", path)
        out = append_ledger({"metric": "m", "value": 1.5, "unit": "u"},
                            "bench")
        assert out == path
        # a corrupt line (interrupted append) must not poison the read
        with open(path, "a") as f:
            f.write('{"schema": 1, "bench": "tru\n')
        append_ledger({"metric": "m", "value": 2.0, "unit": "u"}, "bench")
        entries = read_ledger(path)
        assert [e["value"] for e in entries] == [1.5, 2.0]
        assert all(e["git_sha"] for e in entries)

    def test_env_kill_switch(self, tmp_path, monkeypatch):
        from bench_ledger import append_ledger

        path = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("DSTPU_BENCH_LEDGER_PATH", path)
        monkeypatch.setenv("DSTPU_BENCH_LEDGER", "0")
        assert append_ledger({"value": 1}, "bench") is None
        assert not os.path.exists(path)

    def test_trend_passes_within_threshold(self):
        from bench_trend import compare

        entries = [self._entry("bench", 100.0, "a", 1),
                   self._entry("bench", 110.0, "b", 2),
                   self._entry("bench", 104.0, "c", 3)]   # -5.4% vs best
        v = compare(entries, threshold=0.10)
        assert v["ok"] and len(v["comparisons"]) == 1
        assert v["comparisons"][0]["best_prior"] == 110.0

    def test_trend_fails_past_threshold(self):
        from bench_trend import compare

        entries = [self._entry("bench", 100.0, "a", 1),
                   self._entry("bench", 70.0, "b", 2)]    # -30%
        v = compare(entries, threshold=0.15)
        assert not v["ok"]
        assert v["regressions"][0]["latest_sha"] == "b"

    def test_trend_wildcard_compares_per_config(self):
        # each measured config is its own series: runs with DIFFERENT
        # config sets must not be compared as a max across the set
        from bench_trend import compare

        def infer(sha, decode):
            return self._entry(
                "bench_infer", None, sha, 1,
                result={"prefill_tokens_per_sec": 1.0,
                        "decode": {k: {"tokens_per_sec": v}
                                   for k, v in decode.items()}})

        v = compare([infer("a", {"32": 100.0, "128": 50.0}),
                     infer("b", {"32": 90.0, "128": 48.0})],
                    threshold=0.15)
        mets = {c["metric"]: c for c in v["comparisons"]}
        assert mets["decode.32.tokens_per_sec"]["latest"] == 90.0
        assert mets["decode.32.tokens_per_sec"]["best_prior"] == 100.0
        assert mets["decode.128.tokens_per_sec"]["latest"] == 48.0
        assert v["ok"]
        # a config the latest run SKIPPED is "no data", not a regression
        # (and a fast sibling config cannot mask a slow one)
        v2 = compare([infer("a", {"32": 100.0, "128": 14000.0}),
                      infer("b", {"32": 60.0})], threshold=0.15)
        mets2 = {c["metric"] for c in v2["comparisons"]}
        assert "decode.128.tokens_per_sec" not in mets2
        assert not v2["ok"]               # the real 40% drop on "32" gates

    def test_trend_cli_exit_codes(self, tmp_path):
        import subprocess

        ledger = tmp_path / "l.jsonl"
        rows = [self._entry("bench", 100.0, "a", 1),
                self._entry("bench", 50.0, "b", 2)]
        ledger.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        cli = os.path.join(TOOLS, "bench_trend.py")
        r = subprocess.run([sys.executable, cli, "--ledger", str(ledger)],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 1, r.stdout + r.stderr   # 50% drop
        r = subprocess.run([sys.executable, cli, "--ledger", str(ledger),
                            "--threshold", "0.6"],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run([sys.executable, cli, "--ledger",
                            str(tmp_path / "missing.jsonl")],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0                        # no data = no gate

    def test_checked_in_ledger_parses_and_gates(self):
        # the seeded trajectory (round artifacts) must stay loadable and
        # pass its own gate at the shipped threshold
        from bench_ledger import read_ledger
        from bench_trend import compare

        entries = read_ledger()
        assert len(entries) >= 5
        assert compare(entries, threshold=0.15)["ok"]


# ---------------------------------------------------------------------------
# drill wrappers (slow; the CLI is the invariant authority)
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["storm-trace", "abort-dump",
                                      "disabled-no-events"])
def test_trace_drill_scenarios(scenario, tmp_path):
    from trace_drill import run_scenario

    verdict = run_scenario(scenario, workdir=str(tmp_path))
    assert verdict["ok"], json.dumps(verdict, indent=2, default=str)


@pytest.mark.slow
def test_tracing_overhead_budget(tmp_path):
    from obs_drill import run_scenario

    verdict = run_scenario("tracing-overhead", workdir=str(tmp_path))
    assert verdict["ok"], json.dumps(verdict, indent=2, default=str)
