"""Accelerator abstraction tests (reference tests/unit/accelerator/):
selection logic, capability surface, op-builder seam."""

import numpy as np
import pytest

from deepspeed_tpu.accelerator import (CpuAccelerator, DeepSpeedAccelerator,
                                       TpuAccelerator, get_accelerator,
                                       set_accelerator)


@pytest.fixture(autouse=True)
def _reset_accelerator():
    yield
    set_accelerator(None)  # tests must not leak a forced accelerator


def test_auto_detect_matches_backend(eight_devices):
    import jax

    acc = get_accelerator()
    assert isinstance(acc, DeepSpeedAccelerator)
    expected = "cpu" if jax.default_backend() == "cpu" else "tpu"
    assert acc.device_type() == expected
    assert acc.is_available()
    assert acc.device_count() == len(jax.devices())


def test_env_override_and_reset(monkeypatch):
    set_accelerator(None)
    monkeypatch.setenv("DS_ACCELERATOR", "tpu")
    assert isinstance(get_accelerator(), TpuAccelerator)
    # cached: changing env later doesn't flip silently
    monkeypatch.setenv("DS_ACCELERATOR", "cpu")
    assert isinstance(get_accelerator(), TpuAccelerator)
    set_accelerator(None)
    assert isinstance(get_accelerator(), CpuAccelerator)
    set_accelerator(None)
    monkeypatch.setenv("DS_ACCELERATOR", "bogus")
    with pytest.raises(ValueError):
        get_accelerator()


def test_capability_surface(eight_devices):
    acc = get_accelerator()
    assert acc.communication_backend_name() == "xla"
    assert acc.is_bf16_supported()
    import jax.numpy as jnp

    assert jnp.float32 in acc.supported_dtypes()
    assert jnp.bfloat16 in acc.supported_dtypes()
    # memory introspection returns ints (zeros allowed on platforms
    # without stats)
    assert isinstance(acc.memory_allocated(), int)
    assert isinstance(acc.total_memory(), int)
    assert "cpu" in acc.device_name(0) or "TPU" in acc.device_name(0) or \
        "Cpu" in acc.device_name(0)


def test_rng_and_sync(eight_devices):
    import jax

    acc = get_accelerator()
    k1, k2 = acc.manual_seed(7), acc.manual_seed(7)
    a = jax.random.normal(k1, (4,))
    b = jax.random.normal(k2, (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    acc.synchronize()  # must not raise


def test_device_context_places_computation(eight_devices):
    import jax
    import jax.numpy as jnp

    acc = get_accelerator()
    target = acc.devices()[-1]
    with acc.device(acc.device_count() - 1):
        x = jnp.ones((2,)) * 2
    assert list(x.devices()) == [target]
    assert acc.on_accelerator(x)


def test_op_builder_seam():
    acc = get_accelerator()
    from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, CPUAdamBuilder

    assert acc.get_op_builder("CPUAdamBuilder") is CPUAdamBuilder
    assert acc.get_op_builder("cpu_adam") is CPUAdamBuilder
    assert acc.get_op_builder("async_io") is AsyncIOBuilder
    b = acc.create_op_builder("cpu_adam")
    assert isinstance(b, CPUAdamBuilder) and b.is_compatible()
    assert acc.get_op_builder("nope") is None


def test_graph_capture_is_jit(eight_devices):
    acc = get_accelerator()
    fn = acc.graph_capture(lambda x: x * 2)
    assert float(fn(3.0)) == 6.0
