"""Hybrid engine (RLHF) tests — generate under the training engine must match
the standalone inference engine on the same weights, training must keep
working between generations, and rollout collection must return correct
behavior-policy logprobs (analog of the reference's hybrid-engine unit tests)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset


def make_engine(stage=3, mesh=None):
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, "param_persistence_threshold": 0},
        "hybrid_engine": {"enabled": True},
        "mesh": mesh or {"fsdp": 4, "tp": 2},
        "steps_per_print": 100})
    return eng


def test_hybrid_generate_matches_inference_engine(eight_devices):
    """Greedy generation through the hybrid engine == InferenceEngine on the
    same weights (the mode-switch must not change the math)."""
    import jax

    from deepspeed_tpu.inference.engine import InferenceEngine

    eng = make_engine()
    prompts = np.random.default_rng(0).integers(0, 256, (2, 8))
    got = eng.generate(prompts, max_new_tokens=8)
    host_params = jax.tree_util.tree_map(np.asarray, eng.params)
    ref_eng = InferenceEngine(TransformerLM(get_preset("tiny")),
                              params=host_params, topology=eng.topology)
    ref = ref_eng.generate(prompts, max_new_tokens=8)
    np.testing.assert_array_equal(got, ref)


def test_hybrid_train_generate_interleave(eight_devices):
    """The RLHF loop shape: generate → train → generate; the second generation
    must see the updated weights without any explicit mode switch."""
    eng = make_engine()
    prompts = np.random.default_rng(1).integers(0, 256, (2, 8))
    g0 = eng.generate(prompts, max_new_tokens=6, seed=3)
    batch = {"input_ids": np.random.default_rng(2).integers(0, 256, (16, 16))}
    losses = []
    for _ in range(3):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    g1 = eng.generate(prompts, max_new_tokens=6, seed=3)
    assert g0.shape == g1.shape
    assert not np.array_equal(g0, g1), "generation must reflect trained params"
    # prompts are preserved verbatim
    np.testing.assert_array_equal(g1[:, :8], prompts)


def test_rollout_collector_logprobs(eight_devices):
    """Collected logprobs equal a hand computation from full-sequence logits."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.hybrid_engine import RolloutCollector

    eng = make_engine(stage=0, mesh={"dp": 8})
    prompts = np.random.default_rng(3).integers(0, 256, (2, 6))
    roll = RolloutCollector(eng).collect(prompts, max_new_tokens=5,
                                         temperature=0.0)
    seqs = roll["sequences"]
    assert seqs.shape == (2, 11)
    assert roll["response_mask"].all()  # no eos configured
    with jax.sharding.set_mesh(eng.mesh):
        logits = np.asarray(eng.module.logits(eng.params, jnp.asarray(seqs)))
    logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    want = np.take_along_axis(np.asarray(logp)[:, :-1], seqs[:, 1:, None],
                              axis=-1)[..., 0][:, 5:]
    # collected at sampling time from the cached decode logits; the hand calc
    # uses a fresh full-sequence pass — identical math, cache-path numerics
    np.testing.assert_allclose(roll["logprobs"], want, atol=1e-4)


def test_rollout_eos_mask(eight_devices):
    """Post-EOS tokens are masked out of the response."""
    from deepspeed_tpu.runtime.hybrid_engine import RolloutCollector

    eng = make_engine(stage=0, mesh={"dp": 8})
    prompts = np.zeros((1, 4), np.int32)
    # force an early EOS by making eos the greedy argmax token sometimes;
    # instead just exercise the mask math on a synthetic result
    coll = RolloutCollector(eng)
    resp = np.array([[5, 7, 2, 9, 9]])  # eos=2 at position 2
    ended = np.cumsum(resp == 2, axis=1)
    mask = (ended == 0) | ((resp == 2) & (ended == 1))
    np.testing.assert_array_equal(mask, [[True, True, True, False, False]])
    out = coll.collect(prompts, max_new_tokens=4, eos_token_id=2)
    assert out["response_mask"].shape == out["sequences"][:, 4:].shape


def test_hybrid_with_pipeline_raises(eight_devices):
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "hybrid_engine": {"enabled": True},
        "pipeline": {"micro_batches": 2},
        "mesh": {"pp": 2, "dp": 4},
        "steps_per_print": 100})
    with pytest.raises(ValueError, match="forward_with_cache"):
        eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=2)


class TestRolloutEngineAPI:
    """reference runtime/rollout/base.py parity: the dataclass + ABC surface
    and the hybrid-engine implementation over left-padded ragged prompts."""

    def test_left_padded_ragged_generate(self, eight_devices):
        from deepspeed_tpu.runtime.rollout import (HybridEngineRollout,
                                                   RolloutRequest,
                                                   SamplingConfig)

        eng = make_engine(stage=0, mesh={"dp": 8})
        rng = np.random.default_rng(7)
        # two real lengths (4 and 6), left-padded to 6 with token 0
        p0 = rng.integers(1, 256, 4)
        p1 = rng.integers(1, 256, 6)
        ids = np.zeros((2, 6), np.int64)
        ids[0, 2:] = p0
        ids[1] = p1
        mask = np.zeros((2, 6), np.int64)
        mask[0, 2:] = 1
        mask[1] = 1
        roll = HybridEngineRollout(eng)
        batch = roll.generate(RolloutRequest(ids, mask),
                              SamplingConfig(max_new_tokens=5,
                                             temperature=0.0))
        assert batch.batch_size == 2
        assert list(batch.response_start_idx) == [4, 6]
        # prompts preserved verbatim at the FRONT (pads stripped)
        np.testing.assert_array_equal(batch.input_ids[0, :4], p0)
        np.testing.assert_array_equal(batch.input_ids[1, :6], p1)
        # row 0 must equal generating its unpadded prompt directly — pads
        # never entered attention
        direct = eng.generate(p0[None], max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(batch.input_ids[0, :direct.shape[1]],
                                      direct[0])
        assert batch.attention_mask[0, :9].all()
        assert batch.logprobs is not None
        roll.sync_weights(0)  # no-op, must not raise
        roll.shutdown()

    def test_n_samples_and_validation(self, eight_devices):
        import pytest as _pytest

        from deepspeed_tpu.runtime.rollout import (HybridEngineRollout,
                                                   RolloutRequest,
                                                   SamplingConfig)

        eng = make_engine(stage=0, mesh={"dp": 8})
        rng = np.random.default_rng(8)
        ids = rng.integers(1, 256, (2, 5))
        mask = np.ones((2, 5), np.int64)
        batch = HybridEngineRollout(eng).generate(
            RolloutRequest(ids, mask),
            SamplingConfig(max_new_tokens=3, temperature=0.8, top_p=0.9,
                           n_samples_per_prompt=2, top_k=-1))
        assert batch.batch_size == 4  # B * n_samples
        # right-padded prompts are rejected (reference contract: left-padded)
        bad_mask = np.ones((2, 5), np.int64)
        bad_mask[0, 3:] = 0  # zeros at the RIGHT edge
        with _pytest.raises(ValueError, match="LEFT-padded"):
            RolloutRequest(ids, bad_mask)
