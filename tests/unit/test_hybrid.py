"""Hybrid engine (RLHF) tests — generate under the training engine must match
the standalone inference engine on the same weights, training must keep
working between generations, and rollout collection must return correct
behavior-policy logprobs (analog of the reference's hybrid-engine unit tests)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset


def make_engine(stage=3, mesh=None):
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, "param_persistence_threshold": 0},
        "hybrid_engine": {"enabled": True},
        "mesh": mesh or {"fsdp": 4, "tp": 2},
        "steps_per_print": 100})
    return eng


def test_hybrid_generate_matches_inference_engine(eight_devices):
    """Greedy generation through the hybrid engine == InferenceEngine on the
    same weights (the mode-switch must not change the math)."""
    import jax

    from deepspeed_tpu.inference.engine import InferenceEngine

    eng = make_engine()
    prompts = np.random.default_rng(0).integers(0, 256, (2, 8))
    got = eng.generate(prompts, max_new_tokens=8)
    host_params = jax.tree_util.tree_map(np.asarray, eng.params)
    ref_eng = InferenceEngine(TransformerLM(get_preset("tiny")),
                              params=host_params, topology=eng.topology)
    ref = ref_eng.generate(prompts, max_new_tokens=8)
    np.testing.assert_array_equal(got, ref)


def test_hybrid_train_generate_interleave(eight_devices):
    """The RLHF loop shape: generate → train → generate; the second generation
    must see the updated weights without any explicit mode switch."""
    eng = make_engine()
    prompts = np.random.default_rng(1).integers(0, 256, (2, 8))
    g0 = eng.generate(prompts, max_new_tokens=6, seed=3)
    batch = {"input_ids": np.random.default_rng(2).integers(0, 256, (16, 16))}
    losses = []
    for _ in range(3):
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    g1 = eng.generate(prompts, max_new_tokens=6, seed=3)
    assert g0.shape == g1.shape
    assert not np.array_equal(g0, g1), "generation must reflect trained params"
    # prompts are preserved verbatim
    np.testing.assert_array_equal(g1[:, :8], prompts)


def test_rollout_collector_logprobs(eight_devices):
    """Collected logprobs equal a hand computation from full-sequence logits."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.hybrid_engine import RolloutCollector

    eng = make_engine(stage=0, mesh={"dp": 8})
    prompts = np.random.default_rng(3).integers(0, 256, (2, 6))
    roll = RolloutCollector(eng).collect(prompts, max_new_tokens=5,
                                         temperature=0.0)
    seqs = roll["sequences"]
    assert seqs.shape == (2, 11)
    assert roll["response_mask"].all()  # no eos configured
    with jax.sharding.set_mesh(eng.mesh):
        logits = np.asarray(eng.module.logits(eng.params, jnp.asarray(seqs)))
    logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    want = np.take_along_axis(np.asarray(logp)[:, :-1], seqs[:, 1:, None],
                              axis=-1)[..., 0][:, 5:]
    # collected at sampling time from the cached decode logits; the hand calc
    # uses a fresh full-sequence pass — identical math, cache-path numerics
    np.testing.assert_allclose(roll["logprobs"], want, atol=1e-4)


def test_rollout_eos_mask(eight_devices):
    """Post-EOS tokens are masked out of the response."""
    from deepspeed_tpu.runtime.hybrid_engine import RolloutCollector

    eng = make_engine(stage=0, mesh={"dp": 8})
    prompts = np.zeros((1, 4), np.int32)
    # force an early EOS by making eos the greedy argmax token sometimes;
    # instead just exercise the mask math on a synthetic result
    coll = RolloutCollector(eng)
    resp = np.array([[5, 7, 2, 9, 9]])  # eos=2 at position 2
    ended = np.cumsum(resp == 2, axis=1)
    mask = (ended == 0) | ((resp == 2) & (ended == 1))
    np.testing.assert_array_equal(mask, [[True, True, True, False, False]])
    out = coll.collect(prompts, max_new_tokens=4, eos_token_id=2)
    assert out["response_mask"].shape == out["sequences"][:, 4:].shape


def test_hybrid_with_pipeline_raises(eight_devices):
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "hybrid_engine": {"enabled": True},
        "pipeline": {"micro_batches": 2},
        "mesh": {"pp": 2, "dp": 4},
        "steps_per_print": 100})
    with pytest.raises(ValueError, match="forward_with_cache"):
        eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=2)
