"""Serving-resilience unit tests (``deepspeed_tpu/serving``).

Fast tests pin the request-lifecycle contracts directly: the
:class:`RequestManager` ledger (every uid resolves; typed retryable
``ShedError`` refusals), the satellite invariant that a deadline landing
MID-chunked-prefill releases every KV block through the engine's own flush
path (asserted via ``SequenceManager`` + allocator accounting), the typed
:class:`CapacityError` overload surface on ``InferenceEngineV2.put``, and
the ``serving/*`` monitor stream + ``serving_report()`` acceptance shape.

The end-to-end overload/failure scenarios live in ``tools/serve_drill.py``;
the ``slow``-marked wrappers at the bottom run them under pytest the way
``test_chaos_drill.py`` wraps the training drills.
"""

import os

import numpy as np
import pytest

from deepspeed_tpu.config.config import MonitorConfig, ServingConfig
from deepspeed_tpu.serving import (COMPLETED, EXPIRED, QUEUED, SHED,
                                   ContinuousBatcher, RequestManager,
                                   ShedError)

pytestmark = pytest.mark.serving

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


# ---------------------------------------------------------------------------
# RequestManager: ledger + typed refusals (no engine needed)
# ---------------------------------------------------------------------------

class TestRequestManager:
    def test_queue_full_raises_typed_retryable_shed(self):
        mgr = RequestManager(max_queue_depth=2, retry_after_s=2.5)
        for _ in range(2):
            mgr.submit([1, 2, 3])
        with pytest.raises(ShedError) as ei:
            mgr.submit([1, 2, 3])
        e = ei.value
        assert isinstance(e, RuntimeError)      # legacy catch-surface holds
        assert e.reason == "queue_full" and e.retryable
        # the hint is load-aware: base 2.5 scaled UP by the full queue
        assert e.retry_after_s > 2.5
        assert mgr.counters["rejected"] == 1

    def test_retry_after_hint_scales_with_pressure(self):
        """Satellite: ``Retry-After`` reflects load. Idle → the configured
        base; full queue → larger; repeated rejects (shed rate) → larger
        still, monotonically."""
        mgr = RequestManager(max_queue_depth=4, retry_after_s=1.0)
        assert mgr.current_retry_after() == 1.0      # idle = base
        for _ in range(4):
            mgr.submit([1])
        full = mgr.current_retry_after()
        assert full > 1.0                            # queue fullness
        hints = []
        for _ in range(6):
            with pytest.raises(ShedError) as ei:
                mgr.submit([1])
            hints.append(ei.value.retry_after_s)
        assert hints[0] > full                       # reject adds shed rate
        assert hints == sorted(hints)                # pressure only grows
        assert hints[-1] <= 4.0                      # bounded at 4x base

    def test_queue_depth_by_priority_breakdown(self):
        mgr = RequestManager()
        for prio in (0, 5, 0, 2):
            mgr.submit([1], priority=prio)
        assert mgr.queue_depth_by_priority() == {0: 2, 5: 1, 2: 1}
        rep = mgr.report()
        assert rep["queue_depth_by_priority"] == {0: 2, 5: 1, 2: 1}
        assert rep["retry_after_s"] > 0

    def test_closed_manager_refuses_with_draining(self):
        mgr = RequestManager()
        mgr.close("preemption")
        with pytest.raises(ShedError) as ei:
            mgr.submit([1])
        assert ei.value.reason == "draining" and ei.value.retryable

    def test_every_uid_resolves_and_inflight_release_goes_through_flush(self):
        released = []
        now = [0.0]
        mgr = RequestManager(release_fn=released.append,
                             clock=lambda: now[0])
        u_queued = mgr.submit([1, 2], deadline_s=5.0)
        u_active = mgr.submit([3, 4])
        u_done = mgr.submit([5, 6])
        for uid in (u_active, u_done):
            mgr.admit(mgr.result(uid))
        mgr.complete(mgr.result(u_done))
        mgr.shed(mgr.result(u_active), "kv_pressure")
        now[0] = 10.0                       # the queued request's deadline
        expired = mgr.expire()
        assert [r.uid for r in expired] == [u_queued]
        assert mgr.resolve(u_queued) == EXPIRED
        assert mgr.resolve(u_active) == SHED
        assert mgr.resolve(u_done) == COMPLETED
        assert mgr.resolve(999) is None
        # only ADMITTED work holds engine resources: the completed and the
        # shed request released through flush, the queued one never held any
        assert released == [[u_done], [u_active]]
        assert mgr.counters == {"submitted": 3, "rejected": 0, "admitted": 2,
                                "completed": 1, "shed": 1, "expired": 1,
                                "cancelled": 0, "paused": 0, "resumed": 0,
                                "adopted": 0, "rebalanced": 0,
                                "reprefills": 0}

    def test_shed_order_is_lowest_priority_then_newest(self):
        now = [0.0]
        mgr = RequestManager(clock=lambda: now[0])
        lo_old = mgr.submit([1], priority=0)
        now[0] = 1.0
        hi = mgr.submit([1], priority=5)
        now[0] = 2.0
        lo_new = mgr.submit([1], priority=0)
        order = [r.uid for r in mgr.queued_by_shed_order()]
        assert order == [lo_new, lo_old, hi]
        assert mgr.resolve(hi) == QUEUED


# ---------------------------------------------------------------------------
# engine-backed contracts
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_engine():
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    return InferenceEngineV2(TransformerLM(get_preset("tiny")),
                             max_sequences=8, max_seq_len=128, block_size=16)


def test_put_overload_raises_typed_capacity_error(tiny_engine):
    from deepspeed_tpu.inference import CapacityError

    demand = tiny_engine.max_seq_len + 8    # can never fit one sequence
    with pytest.raises(CapacityError) as ei:
        tiny_engine.put([999], [np.zeros(demand, np.int32)])
    e = ei.value
    assert isinstance(e, RuntimeError)      # compatibility base class
    assert e.uids == [999] and e.token_demand == [demand]
    assert 999 not in tiny_engine.state.sequences   # refused, not leaked


def test_deadline_expiry_mid_chunked_prefill_releases_all_kv(tiny_engine):
    """Satellite invariant: a request whose deadline lands while its prompt
    is only PARTIALLY prefilled must give back every KV block and its slot
    — asserted via the SequenceManager/allocator accounting itself."""
    alloc = tiny_engine.state.allocator
    free0 = alloc.free_blocks
    live0 = set(tiny_engine.state.sequences)
    now = [0.0]
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=4)
    b = ContinuousBatcher(tiny_engine, cfg, clock=lambda: now[0])
    uid = b.submit(np.arange(96) % 250, deadline_s=5.0)   # 3 chunks of 32
    assert b.step()                          # admit + first prefill chunk
    req = b.manager.active[uid]
    assert 0 < req.prefilled < req.prompt_len
    assert alloc.free_blocks < free0         # chunk really holds blocks
    now[0] = 10.0                            # deadline passes mid-prefill
    b.step()
    assert b.manager.resolve(uid) == EXPIRED
    done = b.manager.done[uid]
    assert 0 < done.prefilled < done.prompt_len   # expired MID-prefill
    assert alloc.free_blocks == free0             # no pool leak
    assert set(tiny_engine.state.sequences) == live0  # slot given back


def test_from_deepspeed_config_consumes_serving_section(tiny_engine):
    from deepspeed_tpu.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(train_batch_size=8, serving={
        "enabled": True, "max_queue_depth": 7, "prefill_chunk": 16})
    b = ContinuousBatcher.from_deepspeed_config(tiny_engine, cfg)
    assert b.cfg.max_queue_depth == 7 and b.manager.max_queue_depth == 7
    disabled = DeepSpeedTpuConfig(train_batch_size=8)
    with pytest.raises(ValueError, match="serving.enabled"):
        ContinuousBatcher.from_deepspeed_config(tiny_engine, disabled)


def test_unadmittable_head_is_shed_terminal_not_livelocked(tiny_engine):
    """A head-of-line request that fits ``max_seq_len`` but can NEVER fit
    the KV budget must be shed terminally (``oversize``) — and ``pump()``
    must terminate instead of spinning on an unadmittable head."""
    cfg = ServingConfig(prefill_chunk=32, kv_high_watermark=0.05,
                        kv_low_watermark=0.04)   # budget: 3 of 64 blocks
    b = ContinuousBatcher(tiny_engine, cfg)
    uid = b.submit(np.arange(60) % 250, max_new_tokens=8)  # needs 5 blocks
    b.pump(max_steps=10)                         # must return, not spin
    assert b.manager.resolve(uid) == SHED
    done = b.manager.done[uid]
    assert done.error.reason == "oversize" and not done.error.retryable


def test_admission_budgets_projected_demand_not_live_occupancy(tiny_engine):
    """Admitting N requests in one sweep must charge each one's worst-case
    KV demand against the budget — live occupancy alone would admit them
    all and strand them mid-generation under kv_pressure sheds."""
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=4,
                        kv_high_watermark=0.10,  # budget: 6.4 of 64 blocks
                        kv_low_watermark=0.05)
    b = ContinuousBatcher(tiny_engine, cfg)
    uids = [b.submit(np.arange(60) % 250) for _ in range(2)]  # 4 blocks each
    b.step()
    assert len(b.manager.active) == 1            # joint worst case > budget
    assert b.manager.resolve(uids[1]) == QUEUED  # waiting, not shed
    b.pump(max_steps=60)
    assert all(b.manager.resolve(u) == COMPLETED for u in uids)
    assert b.manager.counters["shed"] == 0       # nobody was stranded


def test_serving_report_and_monitor_stream(tiny_engine, tmp_path):
    """Acceptance shape: ``serving_report()`` carries the lifecycle counters
    + queue/KV occupancy, and the SAME counters stream through a real
    monitor backend (CSV) under the ``serving/*`` prefix."""
    from deepspeed_tpu.monitor.monitor import MonitorMaster

    mon = MonitorMaster(MonitorConfig(csv_monitor={
        "enabled": True, "output_path": str(tmp_path), "job_name": "serve"}))
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=4,
                        monitor_interval=1)
    b = ContinuousBatcher(tiny_engine, cfg, monitor=mon)
    uids = [b.submit(np.arange(20) % 250) for _ in range(3)]
    b.pump(max_steps=50)
    rep = b.serving_report()
    assert all(b.manager.resolve(u) == COMPLETED for u in uids)
    for key in ("admitted", "shed", "expired", "completed"):
        assert key in rep["counters"]
    assert rep["counters"]["admitted"] == rep["counters"]["completed"] == 3
    assert rep["queue_depth"] == 0
    assert 0.0 <= rep["kv"]["occupancy"] <= 1.0
    assert rep["latency_ms"]["p99"] >= rep["latency_ms"]["p50"] >= 0.0
    # the same counters, as serving/* events, through the CSV backend
    outdir = tmp_path / "serve"
    for tag in ("serving_admitted", "serving_shed", "serving_expired",
                "serving_completed", "serving_queue_depth",
                "serving_kv_occupancy", "serving_health",
                "serving_step_p99_ms"):
        assert (outdir / f"{tag}.csv").exists(), tag
    last = (outdir / "serving_completed.csv").read_text().strip(
        ).splitlines()[-1]
    assert float(last.split(",")[1]) == 3.0


def test_per_priority_queue_depth_gauges(tiny_engine):
    """Satellite: the queue-depth breakdown lands in the registry as
    ``serving/queue_depth{priority=}`` children next to the unlabeled
    total, and a priority class that empties is zeroed, not stale."""
    from deepspeed_tpu.observability import MetricsRegistry

    reg = MetricsRegistry()
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=2,
                        max_active_requests=1)
    b = ContinuousBatcher(tiny_engine, cfg, registry=reg)
    uids = [b.submit(np.arange(12) % 250, priority=p) for p in (0, 0, 7)]
    assert b.step()                   # admits the head; two stay queued
    fam = reg.get("serving/queue_depth")
    series = {dict(i.labels).get("priority"): i.value
              for i in fam.series.values()}
    assert series[None] == 2.0        # unlabeled total alongside children
    assert series["0"] == 1.0 and series["7"] == 1.0
    assert b.serving_report()["queue_depth_by_priority"] == {0: 1, 7: 1}
    b.pump(max_steps=60)
    assert all(b.manager.resolve(u) == COMPLETED for u in uids)
    series = {dict(i.labels).get("priority"): i.value
              for i in fam.series.values()}
    assert series["0"] == 0.0 and series["7"] == 0.0


def test_prefix_aware_admission_admits_mostly_cached_request():
    """Prefix-aware admission: with a warm cache, a request whose prompt is
    ~85% resident counts only its uncached share against the KV budget —
    it admits immediately while an equal-size COLD request must wait for
    in-flight work to finish. Cache-held blocks never count as load."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    eng = InferenceEngineV2(TransformerLM(get_preset("tiny")),
                            max_sequences=8, max_seq_len=128, block_size=16,
                            prefix_cache=True)
    # warm the cache: 96-token prompt -> 6 published blocks (80 attachable
    # under the len-1 cap)
    shared = np.arange(96) % 250
    eng.put([900], [shared])
    eng.flush([900])
    # budget = 0.3 * 64 = 19.2 blocks. Cold demand ceil((96+8)/16) = 7;
    # warm demand = ceil((96-80+8)/16) = 2 NEW blocks (its 5 attached
    # blocks count once, as pinned pool use, after it admits: A(7) +
    # warm(5+2) = 14 projected -> +7 cold would cross the budget, +2 warm
    # does not; peak occupancy 14/64 stays under the pressure watermark)
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=8,
                        kv_high_watermark=0.30, kv_low_watermark=0.20)
    b = ContinuousBatcher(eng, cfg)
    # cache-held blocks are reclaimable capacity, not occupancy
    assert b.reclaimable_blocks == 6 and b.kv_occupancy == 0.0
    a = b.submit((np.arange(96) + 7) % 250)    # cold A: 7 of 9.6 blocks
    b.step()
    assert b.manager.resolve(a) in ("prefilling", "decoding")
    warm = b.submit(shared)                    # 2 more blocks: fits
    cold = b.submit((np.arange(96) + 31) % 250)  # 7 more: must wait
    b.step()
    assert b.manager.resolve(warm) in ("prefilling", "decoding")
    assert b.manager.resolve(cold) == QUEUED
    assert b.counters["prefix_hit_requests"] == 1
    assert b.counters["prefix_hit_tokens"] == 80
    b.pump(max_steps=200)                      # blocks free -> cold admits
    for uid in (a, warm, cold):
        assert b.manager.resolve(uid) == COMPLETED
    assert b.manager.counters["shed"] == 0
    eng.prefix_cache.clear()
    alloc = eng.state.allocator
    assert alloc.free_blocks == alloc.num_blocks


# ---------------------------------------------------------------------------
# SLO tiers + preemptible requests (pause/resume through the KV tier store)
# ---------------------------------------------------------------------------

def _slo_batcher(**serving):
    """fp32 engine (bit-identical greedy across pause/resume) + a batcher
    with the SLO block enabled."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    eng = InferenceEngineV2(
        TransformerLM(get_preset("tiny", dtype="float32")),
        max_sequences=8, max_seq_len=128, block_size=16)
    cfg = ServingConfig(**{
        "prefill_chunk": 32, "default_max_new_tokens": 8,
        "slo": {"enabled": True, "preempt": True}, **serving})
    return ContinuousBatcher(eng, cfg)


@pytest.mark.slo
class TestSLOPreemption:
    def test_pause_resume_greedy_bit_identical_fp32(self):
        """Tentpole invariant: pause -> demote through the tier store ->
        promote -> resume reproduces the EXACT greedy token sequence of an
        unpreempted run (fp32; KV bytes round-trip unquantized)."""
        b = _slo_batcher()
        rng = np.random.default_rng(7)
        prompt = list(rng.integers(0, 250, 40))
        base_uid = b.submit(prompt, max_new_tokens=8, tier="batch")
        b.pump(max_steps=50)
        base = list(b.manager.result(base_uid).generated)
        assert len(base) == 8

        uid = b.submit(prompt, max_new_tokens=8, tier="batch")
        for _ in range(4):
            b.step()                       # prefill + a few decode tokens
        req = b.manager.active[uid]
        mid = len(req.generated)
        assert 0 < mid < 8                 # genuinely mid-decode
        assert b.engine.pause_request(uid)
        b.manager.pause(req)
        # demoted: no device blocks for the uid, entries parked in the store
        assert uid not in b.engine.state.sequences
        assert b.engine.is_paused(uid)
        assert b.engine.paused_blocks(uid) > 0
        b.pump(max_steps=60)               # _resume_paused brings it back
        res = b.manager.result(uid)
        assert b.manager.resolve(uid) == COMPLETED
        assert list(res.generated) == base  # bit-identical greedy
        assert res.pause_count == 1
        alloc = b.engine.state.allocator
        assert alloc.free_blocks == alloc.num_blocks
        assert b.engine._tier_store.entries() == 0   # no parked leftovers
        assert b.manager.counters["paused"] == 1
        assert b.manager.counters["resumed"] == 1
        b.engine.close()

    def test_preempt_mid_chunked_prefill_releases_everything(self):
        """A victim caught MID-chunked-prefill pauses without leaking a
        block or a slot, resumes into PREFILLING, and still matches the
        unpreempted greedy output."""
        b = _slo_batcher(default_max_new_tokens=4)
        rng = np.random.default_rng(3)
        prompt = list(rng.integers(0, 250, 96))    # 3 chunks of 32
        base_uid = b.submit(prompt, tier="batch")
        b.pump(max_steps=40)
        base = list(b.manager.result(base_uid).generated)

        alloc = b.engine.state.allocator
        free0 = alloc.free_blocks
        uid = b.submit(prompt, tier="batch")
        b.step()                                   # exactly one chunk in KV
        req = b.manager.active[uid]
        assert req.state == "prefilling" and req.prefilled == 32
        assert b.engine.pause_request(uid)
        b.manager.pause(req)
        # the device side is fully released while paused
        assert uid not in b.engine.state.sequences
        assert alloc.free_blocks == free0
        b.pump(max_steps=60)
        assert b.manager.resolve(uid) == COMPLETED
        assert list(b.manager.result(uid).generated) == base
        assert alloc.free_blocks == alloc.num_blocks
        assert b.engine._tier_store.entries() == 0
        b.engine.close()

    def test_double_preempt_starvation_guard(self):
        """A request that was preempted may not be preempted again before
        it makes progress — two back-to-back ``preempt_storm`` steps pause
        it once, and only post-resume progress re-arms the guard."""
        from deepspeed_tpu.resilience import FaultInjector, set_injector

        b = _slo_batcher()
        try:
            rng = np.random.default_rng(5)
            victim = b.submit(list(rng.integers(0, 250, 40)),
                              max_new_tokens=8, tier="batch")
            other = b.submit(list(rng.integers(0, 250, 40)),
                             max_new_tokens=8, tier="latency")
            for _ in range(3):
                b.step()
            req = b.manager.active[victim]
            assert req.pause_allowed()             # never paused yet
            set_injector(FaultInjector([{"kind": "preempt_storm",
                                         "times": 2}]))
            b.step()                               # storm #1: pauses victim
            assert b.manager.counters["paused"] == 1
            assert req.pause_count == 1
            assert not req.pause_allowed()         # no progress since pause
            b.step()                               # storm #2: guard holds
            assert b.manager.counters["paused"] == 1   # NOT paused again
            # nobody was shed by the storms — preemption is not data loss
            assert b.manager.counters["shed"] == 0
            set_injector(None)
            b.pump(max_steps=80)
            assert b.manager.resolve(victim) == COMPLETED
            assert b.manager.resolve(other) == COMPLETED
            # once it decoded past the pause point the guard re-arms
            assert b.manager.result(victim).progress \
                > b.manager.result(victim).progress_at_last_pause
        finally:
            set_injector(None)
            b.engine.close()

    def test_resume_io_error_sheds_retryably_no_zero_fill(self):
        """Lost/unreadable demoted entries surface as a retryable
        ``resume_io_error`` shed — never a silent zero-filled KV resume —
        and the pool is fully restored."""
        from deepspeed_tpu.resilience import FaultInjector, set_injector

        b = _slo_batcher()
        try:
            rng = np.random.default_rng(11)
            uid = b.submit(list(rng.integers(0, 250, 40)),
                           max_new_tokens=8, tier="batch")
            for _ in range(3):
                b.step()
            assert b.engine.pause_request(uid)
            b.manager.pause(b.manager.active[uid])
            set_injector(FaultInjector([{"kind": "resume_io_error",
                                         "times": 8}]))
            b.pump(max_steps=20)
            req = b.manager.result(uid)
            assert b.manager.resolve(uid) == SHED
            assert req.error.reason == "resume_io_error"
            assert req.error.retryable
            assert b.counters["resume_failures"] >= 1
            alloc = b.engine.state.allocator
            assert alloc.free_blocks == alloc.num_blocks
            assert not b.engine.state.sequences
            assert b.engine._tier_store.entries() == 0
        finally:
            set_injector(None)
            b.engine.close()

    def test_tier_flows_submit_to_request_and_retry_after(self):
        """Satellite: tiers flow through submit; unknown/absent tiers take
        the configured default; the 429 Retry-After hint scales by tier —
        batch backs off harder than latency."""
        mgr = RequestManager(retry_after_s=1.0, default_tier="throughput",
                             retry_after_tier_factor={"batch": 4.0})
        u_lat = mgr.submit([1, 2], tier="latency")
        u_def = mgr.submit([1, 2])
        u_bad = mgr.submit([1, 2], tier="hyperspeed")
        assert mgr.result(u_lat).tier == "latency"
        assert mgr.result(u_def).tier == "throughput"
        assert mgr.result(u_bad).tier == "throughput"   # unknown -> default
        assert mgr.current_retry_after("batch") \
            == 4.0 * mgr.current_retry_after("latency")
        assert mgr.queue_depth_by_tier() == {"latency": 1, "throughput": 2}

    def test_per_tier_admission_budget_waits_never_sheds(self):
        """A tier over its admission budget WAITS while other tiers admit
        past its queued head; when capacity frees it completes — the budget
        is backpressure, not a shed."""
        b = _slo_batcher(
            default_max_new_tokens=4,
            slo={"enabled": True, "preempt": True,
                 "budgets": {"batch": 0.10}})   # batch: ~6 of 64 blocks
        bat = [b.submit(np.arange(60) % 250, tier="batch")
               for _ in range(2)]               # 4 blocks each, 2nd > 6
        lat = b.submit(np.arange(60) % 250, tier="latency")
        b.step()
        assert b.manager.resolve(bat[0]) in ("prefilling", "decoding")
        assert b.manager.resolve(bat[1]) == QUEUED  # over tier budget
        assert b.manager.resolve(lat) in ("prefilling", "decoding",
                                          COMPLETED)  # admitted PAST it
        b.pump(max_steps=80)
        for uid in bat + [lat]:
            assert b.manager.resolve(uid) == COMPLETED
        assert b.manager.counters["shed"] == 0
        b.engine.close()

    def test_preempt_victim_order_prefers_batch_most_remaining_no_deadline(
            self):
        """Victim selection is deadline- and progress-aware: batch tier
        before latency, no-deadline before deadlined, most remaining work
        first."""
        from deepspeed_tpu.serving.request import ServeRequest

        def req(tier, deadline, remaining, uid):
            r = ServeRequest(uid=uid, prompt=[1], submitted_at=0.0,
                             max_new_tokens=remaining, tier=tier,
                             deadline=deadline)
            return r

        lat = req("latency", None, 8, 1)
        bat_big = req("batch", None, 64, 2)
        bat_small = req("batch", None, 4, 3)
        bat_deadline = req("batch", 99.0, 64, 4)
        order = sorted([lat, bat_big, bat_small, bat_deadline],
                       key=ServeRequest.preempt_key)
        # batch before latency; within batch, no-deadline before deadlined,
        # and more remaining work first
        assert [r.uid for r in order] == [2, 3, 4, 1]


# ---------------------------------------------------------------------------
# drill wrappers (slow; the CLI is the invariant authority)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["deadline-storm",
                                      "shed-under-kv-pressure",
                                      "sigterm-drain"])
def test_serve_drill_scenario(scenario, tmp_path):
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    verdict = run_scenario(scenario, workdir=str(tmp_path))
    assert verdict["ok"], verdict


@pytest.mark.slo
@pytest.mark.slow
def test_serve_drill_slo_storm(tmp_path, monkeypatch):
    """Tier-1 authority for the preemption subsystem: zero latency-tier
    sheds under a preempt storm, >= 1 pause -> resume round-trip, streams
    bit-identical to an injection-free replay, pools/store restored."""
    import sys

    sys.path.insert(0, _TOOLS)
    from serve_drill import run_scenario

    monkeypatch.setenv("DSTPU_BENCH_LEDGER", "0")
    verdict = run_scenario("slo-storm", workdir=str(tmp_path))
    assert verdict["ok"], verdict
