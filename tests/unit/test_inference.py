"""Inference tests (pattern: reference ``tests/unit/inference/`` + ``v2/ragged``
behavior tests): cached decode must match full-sequence forward; the continuous
batching engine must serve interleaved prefill/decode correctly."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import BlockedAllocator, InferenceEngine, InferenceEngineV2, SequenceManager
from deepspeed_tpu.models import TransformerLM, get_preset


def jnp_f(x):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(x, np.float32))


def jnp_np(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(get_preset("tiny"))
    params = model.init(jax.random.key(0))
    return model, params


def test_cached_forward_matches_full(tiny_lm):
    model, params = tiny_lm
    ids = np.random.default_rng(0).integers(0, 256, (2, 12)).astype(np.int32)
    full = np.asarray(model.logits(params, ids), np.float32)
    cache = model.init_kv_cache(2, 32)
    logits, cache = model.forward_with_cache(params, ids, cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32), full, atol=3e-2)
    assert np.all(np.asarray(cache["pos"]) == 12)


def test_incremental_decode_matches_full(tiny_lm):
    model, params = tiny_lm
    ids = np.random.default_rng(1).integers(0, 256, (1, 8)).astype(np.int32)
    full = np.asarray(model.logits(params, ids), np.float32)
    cache = model.init_kv_cache(1, 16)
    outs = []
    for t in range(8):
        lg, cache = model.forward_with_cache(params, ids[:, t:t + 1], cache)
        outs.append(np.asarray(lg[:, 0], np.float32))
    inc = np.stack(outs, axis=1)
    np.testing.assert_allclose(inc, full, atol=3e-2)


def test_generate_greedy_deterministic(tiny_lm):
    model, params = tiny_lm
    eng = InferenceEngine(model, params=params, config={"mesh": {}})
    prompt = np.random.default_rng(2).integers(0, 256, (2, 4))
    out1 = eng.generate(prompt, max_new_tokens=6)
    out2 = eng.generate(prompt, max_new_tokens=6)
    assert out1.shape == (2, 10)
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[:, :4], prompt)


def test_blocked_allocator():
    alloc = BlockedAllocator(num_blocks=10, block_size=4)
    a = alloc.allocate(3)
    assert alloc.free_blocks == 7
    alloc.free(a)
    assert alloc.free_blocks == 10
    with pytest.raises(RuntimeError):
        alloc.allocate(11)


def test_sequence_manager_capacity():
    sm = SequenceManager(max_sequences=2, max_seq_len=16, block_size=4)
    assert sm.can_schedule(1, 8)
    sm.schedule(1, 8)
    sm.commit(1)
    assert not sm.can_schedule(1, 16)  # would exceed max_seq_len
    sm.schedule(2, 4)
    sm.commit(2)
    assert not sm.can_schedule(3, 4)  # no free slots
    sm.flush(1)
    assert sm.can_schedule(3, 4)


def test_continuous_batching_matches_sequential(tiny_lm):
    """Interleaved ragged scheduling must reproduce the isolated decode results."""
    model, params = tiny_lm
    rng = np.random.default_rng(3)
    p1 = rng.integers(0, 256, 6)
    p2 = rng.integers(0, 256, 3)

    # reference: each prompt alone through the cached path
    def solo(prompt):
        cache = model.init_kv_cache(1, 32)
        lg, _ = model.forward_with_cache(params, prompt[None].astype(np.int32), cache)
        return np.asarray(lg[0, len(prompt) - 1], np.float32)

    eng = InferenceEngineV2(model, params=params, max_sequences=4, max_seq_len=32)
    # prefill uid 1, then interleave uid 2's prefill with uid 1's decode
    r1 = eng.put([1], [p1])
    next1 = int(np.argmax(r1[1]))
    r = eng.put([2, 1], [p2, np.array([next1])])
    np.testing.assert_allclose(np.asarray(r[2], np.float32), solo(p2), atol=3e-2)

    # uid 1's step must equal running [p1, next1] through a fresh cache
    cache = model.init_kv_cache(1, 32)
    seq = np.concatenate([p1, [next1]])[None].astype(np.int32)
    lg, _ = model.forward_with_cache(params, seq, cache)
    np.testing.assert_allclose(np.asarray(r[1], np.float32),
                               np.asarray(lg[0, -1], np.float32), atol=3e-2)

    # flush frees capacity
    eng.flush([1, 2])
    assert eng.state.allocator.free_blocks == eng.state.allocator.num_blocks


def test_paged_matches_dense_engine(tiny_lm):
    """The paged blocked-KV engine must reproduce the dense-cache engine's
    logits across interleaved prefill/decode scheduling."""
    model, params = tiny_lm
    rng = np.random.default_rng(4)
    p1 = rng.integers(0, 256, 7)
    p2 = rng.integers(0, 256, 5)
    e_paged = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=32, block_size=8, paged=True)
    e_dense = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=32, block_size=8, paged=False)
    for eng in (e_paged, e_dense):
        r1 = eng.put([1], [p1])
        r2 = eng.put([2, 1], [p2, np.array([7])])
        r3 = eng.put([1, 2], [np.array([3]), np.array([11])])
        eng._r = (r1, r2, r3)
    for a, b in zip(e_paged._r, e_dense._r):
        for uid in a:
            np.testing.assert_allclose(np.asarray(a[uid], np.float32),
                                       np.asarray(b[uid], np.float32), atol=3e-2)


def test_paged_pool_smaller_than_dense(tiny_lm):
    """HBM footprint must follow allocated blocks, not max_seqs x max_seq_len:
    a pool sized for half the dense capacity still serves short sequences."""
    model, params = tiny_lm
    eng = InferenceEngineV2(model, params=params, max_sequences=8,
                            max_seq_len=64, block_size=8, num_blocks=16)
    dense_blocks = 8 * (64 // 8)
    assert eng.cache["k"].shape[1] == 16 + 1 < dense_blocks
    # 5 sequences x 2 blocks each fit with 6 blocks spare
    for uid in range(5):
        eng.put([uid], [np.arange(16) % 250])
    assert eng.state.allocator.free_blocks == 16 - 5 * 2
    # a 64-token sequence (8 blocks) cannot be scheduled until a flush frees
    assert not eng.query(99, 64)
    eng.flush([0, 1])
    assert eng.state.allocator.free_blocks == 16 - 3 * 2
    assert eng.query(99, 64)


def test_paged_block_reuse_after_flush(tiny_lm):
    """Blocks freed by flush are re-allocated and re-written correctly."""
    model, params = tiny_lm
    rng = np.random.default_rng(5)
    eng = InferenceEngineV2(model, params=params, max_sequences=2,
                            max_seq_len=32, block_size=8, num_blocks=8)
    p = rng.integers(0, 256, 9)
    eng.put([1], [p])
    eng.flush([1])
    # same prompt through the recycled blocks must give the same logits
    q = rng.integers(0, 256, 9)
    ra = eng.put([2], [q])
    cache = model.init_kv_cache(1, 32)
    lg, _ = model.forward_with_cache(params, q[None].astype(np.int32), cache)
    np.testing.assert_allclose(np.asarray(ra[2], np.float32),
                               np.asarray(lg[0, -1], np.float32), atol=3e-2)


def test_paged_engine_tp2(tiny_lm, eight_devices):
    """v2 paged step under tensor parallelism must match the single-device
    engine (reference: v2 model sharding, engine_v2 TP allreduce)."""
    model, params = tiny_lm
    rng = np.random.default_rng(6)
    p1 = rng.integers(0, 256, 6)
    e_tp = InferenceEngineV2(model, params=params, max_sequences=2,
                             max_seq_len=32, block_size=8, mesh={"tp": 2})
    e_1 = InferenceEngineV2(model, params=params, max_sequences=2,
                            max_seq_len=32, block_size=8)
    ra = e_tp.put([1], [p1]); rb = e_1.put([1], [p1])
    np.testing.assert_allclose(np.asarray(ra[1], np.float32),
                               np.asarray(rb[1], np.float32), atol=3e-2)
    ra = e_tp.put([1], [np.array([9])]); rb = e_1.put([1], [np.array([9])])
    np.testing.assert_allclose(np.asarray(ra[1], np.float32),
                               np.asarray(rb[1], np.float32), atol=3e-2)


def test_paged_attention_window_parity():
    """Sliding-window paged attention (mistral/qwen2 serving): kernel output
    matches the dense-gather reference with the same window mask."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.paged_attention import (paged_attention,
                                                   xla_paged_attention)

    rng = jax.random.key(0)
    B, t, H, K, d, bs, nb = 2, 4, 4, 2, 16, 8, 6
    kq, kk, kv, kt = jax.random.split(rng, 4)
    q = jax.random.normal(kq, (B, t, H, d), jnp.float32)
    k_pool = jax.random.normal(kk, (nb + 1, bs, K, d), jnp.float32)
    v_pool = jax.random.normal(kv, (nb + 1, bs, K, d), jnp.float32)
    # slot 0 deep (pos 20), slot 1 shallow (pos 3); disjoint physical blocks
    tables = jnp.asarray([[0, 1, 2], [3, 4, 5]], jnp.int32)
    pos = jnp.asarray([20, 3], jnp.int32)
    for window in (1, 6, 17, 1000):
        out = paged_attention(q, k_pool, v_pool, tables, pos, window=window,
                              interpret=True)
        ref = xla_paged_attention(q, k_pool, v_pool, tables, pos,
                                  window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, err_msg=f"window={window}")
    # window=None unchanged vs plain causal
    out = paged_attention(q, k_pool, v_pool, tables, pos, interpret=True)
    ref = xla_paged_attention(q, k_pool, v_pool, tables, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_windowed_family_serves_through_paged_engine():
    """A sliding-window (mistral/qwen2-style) model must serve through the
    paged v2 engine with the same logits as the full forward windowed mask —
    past-window context must NOT leak into the attention."""
    cfg = get_preset("tiny", sliding_window=6)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 256, 16)
    eng = InferenceEngineV2(model, params=params, max_sequences=2,
                            max_seq_len=32, block_size=8, paged=True)
    r = eng.put([1], [prompt])
    # reference: full forward with the window applied
    full = np.asarray(model.logits(params, prompt[None].astype(np.int32)),
                      np.float32)
    np.testing.assert_allclose(np.asarray(r[1], np.float32), full[0, -1],
                               atol=3e-2)
    # decode a few steps; each must match a fresh dense windowed cache run
    seq = list(prompt)
    for _ in range(3):
        nxt = int(np.argmax(np.asarray(r[1])))
        seq.append(nxt)
        r = eng.put([1], [np.array([nxt])])
        full = np.asarray(model.logits(
            params, np.asarray(seq)[None].astype(np.int32)), np.float32)
        np.testing.assert_allclose(np.asarray(r[1], np.float32),
                                   full[0, -1], atol=3e-2)


def test_packed_matches_tile_engine(tiny_lm):
    """The token-packed ragged step must reproduce the dense-tile paged step
    across interleaved prefill/decode scheduling (round-2 gap #2)."""
    model, params = tiny_lm
    rng = np.random.default_rng(7)
    p1 = rng.integers(0, 256, 7)
    p2 = rng.integers(0, 256, 5)
    e_packed = InferenceEngineV2(model, params=params, max_sequences=4,
                                 max_seq_len=32, block_size=8, packed=True)
    e_tile = InferenceEngineV2(model, params=params, max_sequences=4,
                               max_seq_len=32, block_size=8, packed=False)
    assert e_packed.packed and not e_tile.packed
    for eng in (e_packed, e_tile):
        r1 = eng.put([1], [p1])
        r2 = eng.put([2, 1], [p2, np.array([7])])
        r3 = eng.put([1, 2], [np.array([3]), np.array([11])])
        eng._r = (r1, r2, r3)
    for a, b in zip(e_packed._r, e_tile._r):
        for uid in a:
            np.testing.assert_allclose(np.asarray(a[uid], np.float32),
                                       np.asarray(b[uid], np.float32),
                                       atol=3e-2)


def test_packed_flops_scale_with_tokens(tiny_lm):
    """A mixed prefill+decode step's compiled FLOPs must follow total
    scheduled tokens, not max_sequences × t_max: one 64-token prefill + 7
    decodes packs into 128 token rows vs the 8×64 dense tile (4× the rows) —
    reference ragged_wrapper.py packs exactly total_tokens."""
    import jax.numpy as jnp

    from deepspeed_tpu.profiling import profile_fn

    model, params = tiny_lm
    Bs, t_max, bsz = 8, 64, 8
    nb_max = 64 // bsz
    cache = model.init_paged_kv_cache(Bs * nb_max, bsz)
    bt = np.arange(Bs * nb_max, dtype=np.int32).reshape(Bs, nb_max)

    # dense tile: [8, 64] rows
    tile = np.zeros((Bs, t_max), np.int32)
    pos = np.zeros((Bs,), np.int32)
    valid_t = np.zeros((Bs, t_max), bool)
    valid_t[0] = True
    valid_t[1:, 0] = True
    tile_cost = profile_fn(model.forward_with_paged_cache, params,
                           jnp.asarray(tile), cache, jnp.asarray(bt),
                           jnp.asarray(pos), jnp.asarray(valid_t))

    # packed: 64 + 7 = 71 tokens → 128 bucket
    npad = 128
    tok_ids = np.zeros((npad,), np.int32)
    tok_slot = np.zeros((npad,), np.int32)
    tok_pos = np.zeros((npad,), np.int32)
    valid_p = np.zeros((npad,), bool)
    tok_slot[64:71] = np.arange(1, 8)
    tok_pos[:64] = np.arange(64)
    valid_p[:71] = True
    gather = np.zeros((Bs,), np.int32)
    packed_cost = profile_fn(model.forward_with_packed_cache, params,
                             jnp.asarray(tok_ids), cache, jnp.asarray(bt),
                             jnp.asarray(tok_slot), jnp.asarray(tok_pos),
                             jnp.asarray(valid_p), jnp.asarray(gather))
    assert packed_cost["flops"] > 0 and tile_cost["flops"] > 0
    # 128 packed rows vs 512 tile rows + per-row logits head → well under half
    assert packed_cost["flops"] < 0.5 * tile_cost["flops"], (
        packed_cost, tile_cost)


def test_packed_jit_cache_bounded(tiny_lm):
    """Power-of-two bucketing keeps the packed step's jit cache at
    O(log max_batched_tokens) entries regardless of chunk-length variety."""
    model, params = tiny_lm
    eng = InferenceEngineV2(model, params=params, max_sequences=4,
                            max_seq_len=64, block_size=8)
    rng = np.random.default_rng(8)
    for uid, n in enumerate([3, 5, 7, 6]):        # all bucket to 8
        eng.put([uid], [rng.integers(0, 256, n)])
    for uid in range(4):                           # 4 decodes → 8 bucket too
        eng.put([uid], [np.array([uid + 1])])
    eng.put([0, 1], [rng.integers(0, 256, 9), np.array([2])])  # mixed step
    # 3 layout buckets — (tile-only 32), (decode-only 8), (mixed 8+32) — + 1:
    # the first call's freshly-placed cache signs differently from the
    # steady-state donated cache (an extra trace-cache entry, no extra XLA
    # compile)
    assert eng._step_packed._cache_size() <= 4, \
        eng._step_packed._cache_size()


def test_decode_batch_matches_sequential_puts(tiny_lm):
    """The fused on-device decode loop (CUDA-graph-replay parity) must
    produce exactly the tokens that per-step greedy put() calls produce."""
    model, params = tiny_lm
    rng = np.random.default_rng(10)
    p1 = rng.integers(0, 256, 6)
    p2 = rng.integers(0, 256, 4)

    def run_sequential():
        eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=32, block_size=8)
        r = eng.put([1, 2], [p1, p2])
        toks = {1: [], 2: []}
        cur = {u: int(np.argmax(r[u])) for u in (1, 2)}
        for _ in range(5):
            r = eng.put([1, 2], [np.array([cur[1]]), np.array([cur[2]])])
            for u in (1, 2):
                cur[u] = int(np.argmax(r[u]))
                toks[u].append(cur[u])
        return toks

    def run_fused():
        eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=32, block_size=8)
        r = eng.put([1, 2], [p1, p2])
        first = {u: int(np.argmax(r[u])) for u in (1, 2)}
        out = eng.decode_batch([1, 2], [first[1], first[2]], steps=5)
        return {u: list(out[u]) for u in (1, 2)}

    seq_toks, fused_toks = run_sequential(), run_fused()
    for u in (1, 2):
        assert seq_toks[u] == fused_toks[u], (u, seq_toks[u], fused_toks[u])


def test_int8_kv_cache_matches_bf16(tiny_lm):
    """The int8 paged pool (per-token dequant scales) must track the
    full-precision engine through prefill, mixed continuation and the fused
    decode loop — within quantization tolerance."""
    model, params = tiny_lm
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, 256, n) for n in (21, 9)]
    cont = rng.integers(0, 256, 5)
    engs = {}
    outs = {}
    for mode in ("bf16", "int8"):
        eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=64, block_size=8, kv_dtype=mode)
        outs[mode] = [eng.put([1, 2], prompts)]          # whole prefill
        outs[mode].append(eng.put([1, 2], [np.array([3]), np.array([4])]))
        outs[mode].append(eng.put([1, 2], [cont, np.array([7])]))  # w/ past
        engs[mode] = eng
    for step_a, step_b in zip(outs["bf16"], outs["int8"]):
        for u in (1, 2):
            a = np.asarray(step_a[u], np.float32)
            b = np.asarray(step_b[u], np.float32)
            # int8 KV error on logits: small relative to logit scale
            assert np.abs(a - b).max() < 0.15 * max(np.abs(a).max(), 1.0), \
                (u, np.abs(a - b).max())
    # fused decode loop runs on the int8 pool
    out = engs["int8"].decode_batch([1, 2], [1, 2], steps=4)
    assert all(len(out[u]) == 4 for u in (1, 2))


def test_int4_kv_cache_tracks_bf16(tiny_lm):
    """int4 paged pool (per-head lane-paired nibbles + per-token scales):
    must track the bf16 engine through prefill/continuation/fused decode
    within 4-bit tolerance."""
    model, params = tiny_lm
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, 256, n) for n in (21, 9)]
    cont = rng.integers(0, 256, 5)
    outs = {}
    engs = {}
    for mode in ("bf16", "int4"):
        eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=64, block_size=8, kv_dtype=mode)
        outs[mode] = [eng.put([1, 2], prompts)]
        outs[mode].append(eng.put([1, 2], [np.array([3]), np.array([4])]))
        outs[mode].append(eng.put([1, 2], [cont, np.array([7])]))
        engs[mode] = eng
    assert engs["int4"].cache["k"].shape[-1] \
        == model.cfg.num_kv_heads * model.cfg.head_dim // 2
    for step_a, step_b in zip(outs["bf16"], outs["int4"]):
        for u in (1, 2):
            a = np.asarray(step_a[u], np.float32)
            b = np.asarray(step_b[u], np.float32)
            # 4-bit KV: ~16x coarser than int8 — loose but bounded
            assert np.abs(a - b).max() < 0.6 * max(np.abs(a).max(), 1.0), \
                (u, np.abs(a - b).max())
    out = engs["int4"].decode_batch([1, 2], [1, 2], steps=4)
    assert all(len(out[u]) == 4 for u in (1, 2))


def test_int4_append_roundtrip():
    """bits=4 packed_kv_append_quant: unpacking the pool row reproduces the
    source row within its per-token scale (per-head lane pairing)."""
    from deepspeed_tpu.ops.paged_attention import (_unpack_int4_lanes_xla,
                                                   packed_kv_append_quant)

    L, N, K, d, bs, nb = 2, 6, 2, 16, 8, 4
    rng = np.random.default_rng(5)
    rows = jnp_f(rng.normal(size=(L, N, K, d)))
    pool = jnp_np(np.zeros((L, nb + 1, bs, K * d // 2), np.int8))
    scales = jnp_f(np.zeros((L, nb + 1, 1, 2 * bs)))
    bt = jnp_np(np.arange(8, dtype=np.int32).reshape(2, 4))
    tok_slot = jnp_np(np.array([0] * N, np.int32))
    tok_pos = jnp_np(np.arange(N, dtype=np.int32))
    npool, nsc = packed_kv_append_quant(pool, scales, rows, bt, tok_slot,
                                        tok_pos, 0, bits=4)
    got = np.asarray(_unpack_int4_lanes_xla(npool[:, 0, :N], K, d))
    sc = np.asarray(nsc[:, 0, 0, :N])                       # [L, N]
    recon = got * sc[..., None]
    ref = np.asarray(rows, np.float32).reshape(L, N, K * d)
    err = np.abs(recon - ref).max()
    tol = (np.abs(ref).max() / 7.0) * 0.51 + 1e-6
    assert err <= tol, (err, tol)


def test_decode_batch_sampling(tiny_lm):
    """Sampling inside the fused loop (reference FastGen serves sampled
    tokens): deterministic per seed, greedy at temperature 0, and the
    first sampled token's empirical distribution matches direct
    sample_token draws from the same logits."""
    from deepspeed_tpu.inference.engine import sample_token

    model, params = tiny_lm
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 256, 6)
    B = 8

    eng = InferenceEngineV2(model, params=params, max_sequences=B,
                            max_seq_len=64, block_size=8)
    uids = list(range(B))
    r = eng.put(uids, [prompt] * B)        # identical context per row
    logits = np.asarray(r[0], np.float32)  # [V] — same for every row
    first = int(np.argmax(logits))

    # determinism + greedy equivalence
    s1 = eng.decode_batch(uids, [first] * B, steps=3, temperature=0.8,
                          top_k=16, seed=7)
    eng.flush(uids)
    eng.put(uids, [prompt] * B)
    s2 = eng.decode_batch(uids, [first] * B, steps=3, temperature=0.8,
                          top_k=16, seed=7)
    for u in uids:
        assert list(s1[u]) == list(s2[u]), "same seed must reproduce"

    # distribution: first sampled token across rows x seeds vs direct
    # sample_token draws from the same logits, under top_k=8 (bounded
    # support makes small-sample statistics meaningful)
    draws = []
    for seed in range(6):
        eng.flush(uids)
        eng.put(uids, [prompt] * B)
        out = eng.decode_batch(uids, [first] * B, steps=1, temperature=0.7,
                               top_k=8, seed=seed)
        draws += [int(out[u][0]) for u in uids]
    # direct draws from the same next-token logits (the row after `first`
    # is appended — recompute via a put of `first`)
    eng.flush(uids)
    r2 = eng.put([0], [np.concatenate([prompt, [first]])])
    base_logits = np.asarray(r2[0], np.float32)
    top8 = set(np.argsort(base_logits)[-8:].tolist())
    assert set(draws) <= top8, (set(draws) - top8,
                                "sampled outside the top-k support")
    ref_draws = []
    for seed in range(96):
        tok = sample_token(jnp_f(base_logits)[None], 0.7, 8,
                           jax.random.key(1000 + seed))
        ref_draws.append(int(tok[0]))
    import collections
    ca = collections.Counter(draws)
    cb = collections.Counter(ref_draws)
    tvd = 0.5 * sum(abs(ca[t] / len(draws) - cb[t] / len(ref_draws))
                    for t in top8 | set(ca) | set(cb))
    assert tvd < 0.45, (tvd, ca, cb)


class TestRaggedKernels:
    """Numeric parity of the atom-based serving kernels (reference
    v2/kernels/ragged_ops/blocked_flash + atom_builder) against the dense
    gather implementation."""

    @staticmethod
    def _pools(rng, nbp1=17, bs=8, K=2, d=16):
        kp = jnp_f(rng.normal(size=(nbp1, bs, K, d)))
        vp = jnp_f(rng.normal(size=(nbp1, bs, K, d)))
        bt = np.asarray(rng.permutation(16)[:12].reshape(3, 4), np.int32)
        return kp, vp, jnp_np(bt)

    def test_chunk_atoms_match_reference(self):
        from deepspeed_tpu.ops.paged_attention import (
            ragged_paged_attention, xla_ragged_attention)

        rng = np.random.default_rng(0)
        kp, vp, bt = self._pools(rng)
        tq, A, H, d = 4, 3, 4, 16
        q = jnp_f(rng.normal(size=(A * tq, H, d)))
        ks = jnp_f(rng.normal(size=(A * tq, 2, d)))
        vs = jnp_f(rng.normal(size=(A * tq, 2, d)))
        a_slot = jnp_np(np.array([0, 1, 0], np.int32))
        a_pos0 = jnp_np(np.array([4, 9, 0], np.int32))
        a_len = jnp_np(np.array([4, 1, 0], np.int32))   # incl. pad atom
        for win in (None, 5):
            got = np.asarray(ragged_paged_attention(
                q, ks, vs, kp, vp, bt, a_slot, a_pos0, a_len, tq=tq,
                window=win))
            ref = np.asarray(xla_ragged_attention(
                q, ks, vs, kp, vp, bt, a_slot, a_pos0, a_len, tq,
                window=win))
            np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_decode_atoms_match_reference(self):
        from deepspeed_tpu.ops.paged_attention import (
            ragged_paged_attention, xla_ragged_attention)

        rng = np.random.default_rng(1)
        kp, vp, bt = self._pools(rng)
        q = jnp_f(rng.normal(size=(4, 4, 16)))
        ks = jnp_f(rng.normal(size=(4, 2, 16)))
        vs = jnp_f(rng.normal(size=(4, 2, 16)))
        s1 = jnp_np(np.array([0, 1, 2, 0], np.int32))
        p1 = jnp_np(np.array([8, 3, 0, 15], np.int32))  # incl. pos0=0
        l1 = jnp_np(np.array([1, 1, 1, 0], np.int32))   # incl. pad row
        got = np.asarray(ragged_paged_attention(q, ks, vs, kp, vp, bt,
                                                s1, p1, l1, tq=1))
        ref = np.asarray(xla_ragged_attention(q, ks, vs, kp, vp, bt,
                                              s1, p1, l1, 1))
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_packed_kv_append_scatter(self):
        from deepspeed_tpu.ops.paged_attention import packed_kv_append

        rng = np.random.default_rng(2)
        _, _, bt = self._pools(rng)
        L, nbp1, bs, K, d = 2, 17, 8, 2, 16
        pool = jnp_f(np.zeros((L, nbp1, bs, K, d)))
        rows = jnp_f(rng.normal(size=(L, 5, K, d)))
        ts = jnp_np(np.array([0, 0, 2, 1, 1], np.int32))
        tp = jnp_np(np.array([10, 11, 3, 0, 1], np.int32))
        va = jnp_np(np.array([1, 1, 1, 0, 0], bool))
        out = np.asarray(packed_kv_append(pool, rows, bt, ts, tp, va))
        btn = np.asarray(bt)
        b, o = int(btn[0, 10 // bs]), 10 % bs
        np.testing.assert_allclose(out[1, b, o], np.asarray(rows[1, 0]))
        b, o = int(btn[2, 0]), 3
        np.testing.assert_allclose(out[0, b, o], np.asarray(rows[0, 2]))
        # invalid rows dropped: total mass == the three valid rows' mass
        np.testing.assert_allclose(np.abs(out).sum(),
                                   float(jnp_f(np.abs(
                                       np.asarray(rows[:, :3]))).sum()),
                                   rtol=1e-6)


def test_joint_capacity_rejected_before_any_scheduling(tiny_lm):
    """Per-uid capacity checks can each pass while the aggregate demand
    exceeds the pool; the engine must reject the batch atomically instead
    of failing mid-prompt with sequences half-prefilled (review finding)."""
    model, params = tiny_lm
    rng = np.random.default_rng(11)
    # pool fits ONE 64-token prompt (8 blocks) but not two
    eng = InferenceEngineV2(model, params=params, max_sequences=4,
                            max_seq_len=600, block_size=8, num_blocks=10)
    p = rng.integers(0, 256, 64)
    with pytest.raises(RuntimeError, match="cannot schedule"):
        eng.put([1, 2], [p, p])
    # nothing was scheduled or allocated
    assert eng.state.allocator.free_blocks == 10
    assert not eng.state.sequences
    # a single prompt still fits
    eng.put([1], [p])
    assert eng.state.sequences[1].seen_tokens == 64


class TestWeightQuantServing:
    """int8/int4 weight serving through the linear() seam (reference
    ``init_inference(dtype=torch.int8)`` + the cutlass mixed-GEMM path):
    the engine swaps matmul leaves for packed QuantizedWeight nodes and
    every forward path (prefill, packed put, fused decode loop) consumes
    them via the fused dequant-matmul kernel."""

    @staticmethod
    def _model():
        from deepspeed_tpu.models import TransformerConfig

        cfg = TransformerConfig(vocab_size=512, hidden_size=128,
                                num_layers=2, num_heads=4, max_seq_len=256,
                                arch="llama", tie_embeddings=False)
        model = TransformerLM(cfg)
        return model, model.init(jax.random.key(0))

    @pytest.mark.parametrize("wd", ["int8", "int4"])
    def test_quant_engine_serves(self, wd):
        model, params = self._model()
        eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=256, block_size=32,
                                weight_dtype=wd)
        prompt = np.random.default_rng(0).integers(0, 512, 48)
        first = eng.put([1], [prompt])[1]
        assert np.isfinite(np.asarray(first, np.float32)).all()
        toks = eng.decode_batch([1], [int(np.argmax(first))], steps=6)[1]
        assert toks.shape == (6,)
        # the packed tree must actually be smaller than the served bf16 tree
        dense = InferenceEngineV2(model, params=params, max_sequences=4,
                                  max_seq_len=256, block_size=32)

        def nbytes(tree):
            return sum(leaf.nbytes
                       for leaf in jax.tree_util.tree_leaves(tree))

        ratio = nbytes(eng.params) / nbytes(dense.params)
        assert ratio < (0.75 if wd == "int8" else 0.55), ratio

    def test_int8_matches_dequant_reference(self):
        """Same effective (rounded) weights served dense vs packed must give
        matching logits — isolates the kernel from the quantization loss."""
        from deepspeed_tpu.ops.quant_matmul import (
            dequantize_matmul_weight, quantize_matmul_weight)

        model, params = self._model()
        eng_q = InferenceEngineV2(model, params=params, max_sequences=4,
                                  max_seq_len=256, block_size=32,
                                  weight_dtype="int8")

        import jax.numpy as jnp

        def rq(w):  # round-trip a stacked [L, Din, F] leaf through int8,
            # replicating the engine's compute-dtype scale storage
            outs = []
            for i in range(w.shape[0]):
                p, s = quantize_matmul_weight(w[i].astype(np.float32), bits=8)
                s = s.astype(jnp.bfloat16).astype(jnp.float32)
                outs.append(dequantize_matmul_weight(p, s, 8, w.shape[1]))
            return jnp.stack(outs).astype(w.dtype)

        ref = jax.tree_util.tree_map(lambda p: p, params)
        for grp in ("attn", "mlp"):
            for name in InferenceEngineV2._QUANT_LEAVES:
                if name in ref["layers"][grp]:
                    ref["layers"][grp][name] = rq(ref["layers"][grp][name])
        p, s = quantize_matmul_weight(
            np.asarray(ref["lm_head"], np.float32), bits=8)
        s = s.astype(jnp.bfloat16).astype(jnp.float32)
        ref["lm_head"] = dequantize_matmul_weight(
            p, s, 8, ref["lm_head"].shape[0]).astype(ref["lm_head"].dtype)
        eng_d = InferenceEngineV2(model, params=ref, max_sequences=4,
                                  max_seq_len=256, block_size=32)
        prompt = np.random.default_rng(1).integers(0, 512, 40)
        lq = np.asarray(eng_q.put([1], [prompt])[1], np.float32)
        ld = np.asarray(eng_d.put([1], [prompt])[1], np.float32)
        # identical effective weights; the residual spread is bf16
        # accumulation order (kernel sums per 128-row group, XLA in one dot)
        np.testing.assert_allclose(lq, ld, atol=0.2, rtol=0.2)
        assert float(np.mean(np.abs(lq - ld))) < 2e-2

    def test_v1_engine_int8_dtype(self):
        """``init_inference(dtype='int8')`` parity surface: the v1 engine's
        generate() serves packed weights through the same seam."""
        import deepspeed_tpu as ds

        model, params = self._model()
        eng = ds.init_inference(model=model, dtype="int8", params=params)
        ids = np.random.default_rng(3).integers(0, 512, (1, 16))
        out = eng.generate(ids, max_new_tokens=4)
        assert out.shape == (1, 20)
        from deepspeed_tpu.models.transformer import QuantizedWeight

        assert isinstance(eng.params["layers"]["attn"]["wqkv"],
                          QuantizedWeight)
        assert isinstance(eng.params["lm_head_q"], QuantizedWeight)

    def test_moe_model_quant_serves(self):
        """MoE expert stacks ([L, E, D, F]) quantize to int8 leaf pairs
        (w_*_q packed + w_*_s scales — reference cutlass moe_gemm W8A16)
        consumed by the grouped-GEMM dequant seam; they are never
        gate|up-fused. Served logits must stay close to the bf16 engine's
        (expert weights carry most of a MoE model's read bandwidth)."""
        from deepspeed_tpu.models import TransformerConfig

        cfg = TransformerConfig(vocab_size=512, hidden_size=128,
                                num_layers=2, num_heads=4, max_seq_len=256,
                                arch="llama", num_experts=4, top_k=2)
        model = TransformerLM(cfg)
        params = model.init(jax.random.key(0))
        prompt = np.random.default_rng(4).integers(0, 512, 40)
        ref_eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                    max_seq_len=256, block_size=32)
        ref = np.asarray(ref_eng.put([1], [prompt])[1], np.float32)
        del ref_eng
        eng = InferenceEngineV2(model, params=params,
                                max_sequences=4, max_seq_len=256,
                                block_size=32, weight_dtype="int8")
        from deepspeed_tpu.models.transformer import QuantizedWeight

        mlp = eng.params["layers"]["mlp"]
        assert isinstance(eng.params["layers"]["attn"]["wqkv"],
                          QuantizedWeight)
        assert "w_gateup" not in mlp and "w_gate" not in mlp
        assert str(mlp["w_gate_q"].dtype) == "int8"
        assert mlp["w_gate_q"].shape == (2, 4, 128, mlp["w_gate_s"].shape[-1])
        assert str(mlp["w_down_q"].dtype) == "int8"
        # the dequant seam must reconstruct the dense stack to int8 accuracy
        from deepspeed_tpu.moe.sharded_moe import _expert_weight

        import jax.numpy as jnp

        dense = params["layers"]["mlp"]["w_gate"][0]      # [E, D, F]
        recon = np.asarray(_expert_weight(
            {k: v[0] for k, v in mlp.items() if k.startswith("w_gate")},
            "w_gate", jnp.float32), np.float32)
        wrel = (np.abs(recon - np.asarray(dense, np.float32)).max()
                / np.abs(np.asarray(dense)).max())
        assert wrel < 0.02, f"expert dequant off: {wrel}"
        # end-to-end only loosely: on a RANDOM-INIT router, int8 noise in h
        # flips top-2 expert selection (near-uniform router logits), which
        # swings logits far beyond the per-path quantization error — a
        # trained MoE's routing margins make this a non-issue
        first = eng.put([1], [prompt])[1]
        rel = (np.abs(np.asarray(first, np.float32) - ref).max()
               / (np.abs(ref).max() + 1e-9))
        assert rel < 0.6, f"int8-expert logits diverged: rel={rel}"
        toks = eng.decode_batch([1], [int(np.argmax(first))], steps=4)[1]
        assert toks.shape == (4,)

    def test_quant_engine_tp2(self, eight_devices):
        model, params = self._model()
        eng = InferenceEngineV2(model, params=params, max_sequences=4,
                                max_seq_len=256, block_size=32,
                                weight_dtype="int8", mesh={"tp": 2})
        prompt = np.random.default_rng(2).integers(0, 512, 32)
        first = eng.put([1], [prompt])[1]
        toks = eng.decode_batch([1], [int(np.argmax(first))], steps=4)[1]
        assert toks.shape == (4,)


def test_init_inference_checkpoint_surfaces(tmp_path, eight_devices):
    """init_inference(checkpoint=...) loads engine checkpoints (given the
    model) and HF checkpoint dirs (self-describing) — round-2 weak #7."""
    import deepspeed_tpu as ds

    # engine checkpoint route
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
        "steps_per_print": 100})
    b = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 16))}
    loss = eng.forward(b); eng.backward(loss); eng.step()
    ck = str(tmp_path / "engine_ck")
    eng.save_checkpoint(ck)
    ieng = ds.init_inference(model=TransformerLM(get_preset("tiny")),
                             checkpoint=ck, config={"mesh": {}})
    trained = np.asarray(jax.tree_util.tree_leaves(eng.params)[0])
    loaded = np.asarray(jax.tree_util.tree_leaves(ieng.params)[0])
    np.testing.assert_allclose(loaded, trained, rtol=1e-6)
    out = ieng.generate(np.random.default_rng(1).integers(0, 256, (1, 4)),
                        max_new_tokens=3)
    assert out.shape == (1, 7)

    # HF checkpoint route (model auto-built)
    import torch
    import transformers as tr

    torch.manual_seed(0)
    hf = tr.LlamaForCausalLM(tr.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32))
    hf_dir = str(tmp_path / "hf_ck")
    hf.save_pretrained(hf_dir)
    ieng2 = ds.init_inference(checkpoint=hf_dir, config={"mesh": {}})
    ids = np.random.default_rng(2).integers(0, 128, (1, 8))
    out2 = np.asarray(ieng2.generate(ids, max_new_tokens=3))
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids), max_new_tokens=3,
                          do_sample=False).numpy()
    np.testing.assert_array_equal(out2, ref)


def test_quantize_jit_wrapper_count_does_not_scale_with_leaves(monkeypatch):
    """dslint burn-down (recompile-hazard): ``quantize_serving_params``
    built ``jax.jit(q_stacked)`` INSIDE the per-leaf loop (and the head
    lambda inline), so every leaf traced+compiled against a fresh empty
    cache. The wrappers are now bound once before the loops — exactly
    three ``jax.jit`` calls regardless of how many leaves quantize, and
    same-geometry leaves share one compilation."""
    from deepspeed_tpu.inference.quant import quantize_serving_params

    model, params = TestWeightQuantServing._model()
    dense = InferenceEngineV2(model, params=params, max_sequences=2,
                              max_seq_len=256, block_size=32)
    real_jit = jax.jit
    calls = []

    def counting_jit(*a, **k):
        calls.append(a)
        return real_jit(*a, **k)
    monkeypatch.setattr(jax, "jit", counting_jit)
    q = quantize_serving_params(params, dense.cfg, 8, dense.mesh)
    monkeypatch.undo()
    # q_stacked + expert-layer vmap + lm-head lambda; with >3 quantizable
    # leaves in this model, the old per-leaf jit would exceed this
    assert len(calls) == 3, [getattr(a[0], "__name__", a[0]) for a in calls]
    from deepspeed_tpu.models.transformer import QuantizedWeight
    n_quant = sum(isinstance(leaf, QuantizedWeight)
                  for leaf in jax.tree_util.tree_leaves(
                      q, is_leaf=lambda x: isinstance(x, QuantizedWeight)))
    assert n_quant > len(calls)     # more leaves quantized than jits built
