"""Pipeline-parallel tests (pattern: reference ``tests/unit/v1/pipe/`` — pipeline
training matches the non-pipeline baseline)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.runtime.pipe import PipelineModule


def _cfg(mesh, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": mesh,
        "steps_per_print": 100,
    }
    cfg.update(over)
    return cfg


def _train(eng, steps, seed=0):
    rng = np.random.default_rng(seed)
    fixed = {"input_ids": rng.integers(
        0, 256, (eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size, 16))}
    losses = []
    for _ in range(steps):
        loss = eng.forward(fixed)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


def test_pipeline_matches_single(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng_ref, *_ = ds.initialize(model=model, config=_cfg({"dp": 8}))
    ref = _train(eng_ref, 3, seed=5)

    model_pp = TransformerLM(get_preset("tiny"))
    eng_pp, *_ = ds.initialize(model=model_pp, config=_cfg(
        {"pp": 2, "dp": 4}, pipeline={"micro_batches": 2}))
    assert isinstance(eng_pp.module, PipelineModule)
    pp = _train(eng_pp, 3, seed=5)
    # CPU backend: pipeline computes fp32 (XLA:CPU bf16 workaround, see pipe.py)
    # while the reference engine is bf16 → ~1% drift is precision, not schedule.
    np.testing.assert_allclose(pp, ref, rtol=2e-2)


def test_pipeline_with_zero(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "fsdp": 4}, zero_optimization={"stage": 1}))
    losses = _train(eng, 4)
    assert losses[-1] < losses[0]


def test_pipeline_with_zero3_fsdp_tp(eight_devices):
    """pp x fsdp x tp with ZeRO-3 (the dryrun's dense mesh): the per-tick
    embedding gather must run over the once-replicated table — gathers over
    an auto-fsdp-sharded operand inside the pp-manual region trip GSPMD's
    group-math check (spmd_partitioner_util.cc:495 regression guard)."""
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "fsdp": 2, "tp": 2}, zero_optimization={"stage": 3}))
    losses = _train(eng, 3)
    assert losses[-1] < losses[0]


def test_pipeline_stage_divisibility():
    model = TransformerLM(get_preset("tiny"))  # 2 layers
    with pytest.raises(ValueError, match="divisible"):
        PipelineModule(model, num_stages=3)


def test_pipeline_with_sp_tp_ulysses(eight_devices):
    """The pp x sp x tp triple trains via engine-selected Ulysses attention
    (sp+tp re-entered manually inside the pp region — the composition the
    round-1 dryrun could not run)."""
    import dataclasses

    model = TransformerLM(dataclasses.replace(get_preset("tiny"),
                                              attention_impl="ulysses"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "sp": 2, "tp": 2}, pipeline={"micro_batches": 2}))
    losses = _train(eng, 3)
    assert losses[-1] < losses[0]


def test_pipeline_ring_raises(eight_devices):
    """ring attention inside the pipeline region must fail loudly (nested
    manual ppermute has no transpose), pointing at ulysses."""
    import dataclasses

    model = TransformerLM(dataclasses.replace(get_preset("tiny"),
                                              attention_impl="ring"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "sp": 2, "dp": 2}, pipeline={"micro_batches": 2}))
    batch = {"input_ids": np.zeros((8, 16), np.int32)}
    with pytest.raises(NotImplementedError, match="ulysses"):
        eng.forward(batch)


def test_gpipe_vocab_parallel_head_flops(eight_devices):
    """The stage-owned head (reference pipe/module.py:698) must remove the
    pp-x replicated logits matmul: compiled FLOPs with the vocab-parallel
    head (vocab % pp == 0) vs the replicated fallback (vocab % pp != 0) on
    a head-dominant config."""
    import dataclasses

    import jax
    from jax.sharding import Mesh

    from deepspeed_tpu.profiling import profile_fn

    mesh = Mesh(np.array(eight_devices[:4]).reshape(4, 1), ("pp", "dp"))
    flops = {}
    for vocab in (4096, 4098):       # 4098 % 4 != 0 -> replicated fallback
        cfg = dataclasses.replace(get_preset("tiny"), vocab_size=vocab,
                                  num_layers=4)
        model = TransformerLM(cfg)
        params = model.init(jax.random.key(0))
        pm = PipelineModule(model, 4, micro_batches=4, schedule="gpipe")
        b = {"input_ids": np.zeros((4, 64), np.int32)}
        with jax.sharding.set_mesh(mesh):
            stats = profile_fn(jax.value_and_grad(pm.loss_fn), params, b)
        flops[vocab] = stats.get("flops", 0)
    if 0 in flops.values():
        pytest.skip("backend reports no cost analysis")
    assert flops[4096] < 0.65 * flops[4098], flops


def test_1f1b_vocab_parallel_head_flops(eight_devices):
    """The 1F1B per-tick vocab-parallel head (static closing-microbatch
    trick) must cut the replicated head FLOPs the same way the GPipe
    stage-owned head does: compiled FLOPs with DSTPU_PP_VP_HEAD=1 vs =0 on
    a head-dominant config."""
    import dataclasses
    import os

    import jax
    from jax.sharding import Mesh

    from deepspeed_tpu.profiling import profile_fn

    mesh = Mesh(np.array(eight_devices[:4]).reshape(4, 1), ("pp", "dp"))
    cfg = dataclasses.replace(get_preset("tiny"), vocab_size=4096,
                              num_layers=4)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    flops = {}
    for vp in ("1", "0"):
        pm = PipelineModule(model, 4, micro_batches=4, schedule="1f1b")
        b = {"input_ids": np.zeros((4, 64), np.int32)}
        os.environ["DSTPU_PP_VP_HEAD"] = vp
        try:
            with jax.sharding.set_mesh(mesh):
                stats = profile_fn(
                    lambda p, bb: pm.loss_and_grad(p, bb, 1.0), params, b)
        finally:
            os.environ.pop("DSTPU_PP_VP_HEAD", None)
        flops[vp] = stats.get("flops", 0)
    if 0 in flops.values():
        pytest.skip("backend reports no cost analysis")
    assert flops["1"] < 0.65 * flops["0"], flops


class Test1F1B:
    """Hand-scheduled 1F1B (reference TrainSchedule schedule.py:189) against
    the autodiff GPipe path: same math, flat-in-M memory."""

    def test_1f1b_loss_and_grads_match_gpipe(self, eight_devices):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        model = TransformerLM(get_preset("tiny"))
        params = model.init(jax.random.key(0))
        b = {"input_ids": np.random.default_rng(1).integers(
            0, 256, (8, 16)).astype(np.int32)}
        mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("pp", "dp"))

        pm_g = PipelineModule(model, 2, micro_batches=4, schedule="gpipe")
        with jax.sharding.set_mesh(mesh):
            loss_g, grads_g = jax.jit(jax.value_and_grad(pm_g.loss_fn))(
                params, b)
        flat_g = jax.tree_util.tree_leaves_with_path(grads_g)
        for save in (False, True):       # recompute vs saved-activations bwd
            pm_f = PipelineModule(model, 2, micro_batches=4, schedule="1f1b",
                                  save_activations=save)
            with jax.sharding.set_mesh(mesh):
                loss_f, grads_f = jax.jit(
                    lambda p, bb: pm_f.loss_and_grad(p, bb, 1.0))(params, b)
            np.testing.assert_allclose(float(loss_f), float(loss_g),
                                       rtol=2e-3)
            flat_f = {jax.tree_util.keystr(k): v
                      for k, v in jax.tree_util.tree_leaves_with_path(grads_f)}
            for k, vg in flat_g:
                vf = flat_f[jax.tree_util.keystr(k)]
                np.testing.assert_allclose(
                    np.asarray(vf, np.float32), np.asarray(vg, np.float32),
                    rtol=5e-2, atol=5e-4,
                    err_msg=f"save={save} {jax.tree_util.keystr(k)}")

    @pytest.mark.slow  # ~2 min of compiles; the peak-memory ratio it pins
    # down is XLA-cost-model sensitive (borderline on older CPU backends)
    def test_1f1b_memory_flat_in_microbatches(self, eight_devices):
        """GPipe's live state grows with M (stacked outputs + all saved
        stage inputs); 1F1B's rolling buffer is bounded by the stage count.
        Compare compiled peak temp memory at M=2 vs M=8."""
        import jax
        from jax.sharding import Mesh

        from deepspeed_tpu.profiling import profile_fn

        model = TransformerLM(get_preset("tiny"))
        params = model.init(jax.random.key(0))
        mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("pp", "dp"))

        def peak(schedule, M, save=False, vp="0"):
            import os

            pm = PipelineModule(model, 2, micro_batches=M, schedule=schedule,
                                save_activations=save)
            b = {"input_ids": np.zeros((8 * M, 64), np.int32)}
            os.environ["DSTPU_PP_VP_HEAD"] = vp
            try:
                with jax.sharding.set_mesh(mesh):
                    if schedule == "gpipe":
                        fn = jax.value_and_grad(pm.loss_fn)
                    else:
                        fn = lambda p, bb: pm.loss_and_grad(p, bb, 1.0)
                    stats = profile_fn(fn, params, b)
            finally:
                os.environ.pop("DSTPU_PP_VP_HEAD", None)
            return stats.get("peak_bytes", 0.0)

        g2, g8 = peak("gpipe", 2), peak("gpipe", 8)
        # buffer-policy flatness is measured with the vocab-parallel head
        # off: the vp head trades some per-tick temp (psum'd activation +
        # local-vocab logits) for pp-fold fewer head FLOPs — a different
        # axis than the rolling stage-input ring this test pins down
        f2, f8 = peak("1f1b", 2), peak("1f1b", 8)
        s2, s8 = peak("1f1b", 2, save=True), peak("1f1b", 8, save=True)
        v2, v8 = peak("1f1b", 2, vp="1"), peak("1f1b", 8, vp="1")
        if 0.0 in (g2, g8, f2, f8, s2, s8, v2, v8):
            pytest.skip("backend reports no memory analysis")
        # batch grows 4x in both; GPipe additionally stacks M outputs.
        # 1F1B's per-M growth must stay well below GPipe's — in BOTH
        # backward policies (the saved-activation ring is bounded by the
        # in-flight count, not by M).
        assert (f8 / f2) < 0.75 * (g8 / g2), (f2, f8, g2, g8)
        assert (s8 / s2) < 0.75 * (g8 / g2), (s2, s8, g2, g8)
        # and with the vp head on, growth still undercuts GPipe's
        assert (v8 / v2) < 0.9 * (g8 / g2), (v2, v8, g2, g8)
