"""Pipeline-parallel tests (pattern: reference ``tests/unit/v1/pipe/`` — pipeline
training matches the non-pipeline baseline)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.runtime.pipe import PipelineModule


def _cfg(mesh, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": mesh,
        "steps_per_print": 100,
    }
    cfg.update(over)
    return cfg


def _train(eng, steps, seed=0):
    rng = np.random.default_rng(seed)
    fixed = {"input_ids": rng.integers(
        0, 256, (eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size, 16))}
    losses = []
    for _ in range(steps):
        loss = eng.forward(fixed)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


def test_pipeline_matches_single(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng_ref, *_ = ds.initialize(model=model, config=_cfg({"dp": 8}))
    ref = _train(eng_ref, 3, seed=5)

    model_pp = TransformerLM(get_preset("tiny"))
    eng_pp, *_ = ds.initialize(model=model_pp, config=_cfg(
        {"pp": 2, "dp": 4}, pipeline={"micro_batches": 2}))
    assert isinstance(eng_pp.module, PipelineModule)
    pp = _train(eng_pp, 3, seed=5)
    # CPU backend: pipeline computes fp32 (XLA:CPU bf16 workaround, see pipe.py)
    # while the reference engine is bf16 → ~1% drift is precision, not schedule.
    np.testing.assert_allclose(pp, ref, rtol=2e-2)


def test_pipeline_with_zero(eight_devices):
    model = TransformerLM(get_preset("tiny"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "fsdp": 4}, zero_optimization={"stage": 1}))
    losses = _train(eng, 4)
    assert losses[-1] < losses[0]


def test_pipeline_stage_divisibility():
    model = TransformerLM(get_preset("tiny"))  # 2 layers
    with pytest.raises(ValueError, match="divisible"):
        PipelineModule(model, num_stages=3)


def test_pipeline_with_sp_tp_ulysses(eight_devices):
    """The pp x sp x tp triple trains via engine-selected Ulysses attention
    (sp+tp re-entered manually inside the pp region — the composition the
    round-1 dryrun could not run)."""
    import dataclasses

    model = TransformerLM(dataclasses.replace(get_preset("tiny"),
                                              attention_impl="ulysses"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "sp": 2, "tp": 2}, pipeline={"micro_batches": 2}))
    losses = _train(eng, 3)
    assert losses[-1] < losses[0]


def test_pipeline_ring_raises(eight_devices):
    """ring attention inside the pipeline region must fail loudly (nested
    manual ppermute has no transpose), pointing at ulysses."""
    import dataclasses

    model = TransformerLM(dataclasses.replace(get_preset("tiny"),
                                              attention_impl="ring"))
    eng, *_ = ds.initialize(model=model, config=_cfg(
        {"pp": 2, "sp": 2, "dp": 2}, pipeline={"micro_batches": 2}))
    batch = {"input_ids": np.zeros((8, 16), np.int32)}
    with pytest.raises(NotImplementedError, match="ulysses"):
        eng.forward(batch)
