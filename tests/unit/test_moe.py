"""MoE tests (pattern: reference ``tests/unit/moe/test_moe.py`` — gating invariants +
tiny MoE model training)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.moe import moe_mlp_block, top1_gating, topk_gating


def test_topk_gating_invariants():
    S, E, k = 64, 4, 2
    logits = jax.random.normal(jax.random.key(0), (S, E))
    dispatch, combine, aux, stats = topk_gating(logits, k=k, capacity_factor=2.0)
    C = dispatch.shape[-1]
    # each token dispatched at most k times, each slot holds at most one token
    assert dispatch.shape == (S, E, C)
    assert float(dispatch.sum(axis=(1, 2)).max()) <= k + 1e-6
    assert float(dispatch.sum(axis=0).max()) <= 1 + 1e-6  # slot occupancy
    # combine weights match dispatch support and sum to <= 1 per token
    assert np.all((np.asarray(combine) > 0) <= (np.asarray(dispatch) > 0))
    per_token = np.asarray(combine.sum(axis=(1, 2)))
    assert per_token.max() <= 1 + 1e-5
    assert float(aux) > 0


def test_capacity_drops_tokens():
    S, E = 64, 2
    # all tokens want expert 0 → capacity must drop most
    logits = jnp.stack([jnp.ones(S), -jnp.ones(S)], axis=1)
    dispatch, _, _, stats = top1_gating(logits, capacity_factor=0.5, min_capacity=4)
    kept = float(dispatch.sum())
    assert kept <= max(int(np.ceil(S / E * 0.5)), 4) + 1e-6


def test_moe_block_shapes_and_grads():
    cfg = get_preset("tiny-moe")
    model = TransformerLM(cfg, moe_fn=moe_mlp_block)
    params = model.init(jax.random.key(0))
    E = cfg.num_experts
    assert params["layers"]["mlp"]["w_up"].shape[1] == E
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (2, 16))}
    loss, grads = jax.value_and_grad(model.loss_fn)(params, batch)
    assert np.isfinite(float(loss))
    # router must receive gradient (aux loss + combine weights)
    rg = np.asarray(grads["layers"]["mlp"]["router"])
    assert np.abs(rg).sum() > 0


def test_moe_ep_training(eight_devices):
    """tiny MoE model trains on an ep×fsdp mesh (AutoEP-style EP×DP algebra)."""
    cfg = get_preset("tiny-moe")
    model = TransformerLM(cfg, moe_fn=moe_mlp_block)
    eng, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"ep": 4, "fsdp": 2},
        "steps_per_print": 100,
    })
    rng = np.random.default_rng(0)
    fixed = {"input_ids": rng.integers(0, 256, (2 * eng.topology.dp_world_size, 16))}
    losses = []
    for _ in range(4):
        loss = eng.forward(fixed)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
