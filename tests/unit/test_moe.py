"""MoE tests (pattern: reference ``tests/unit/moe/test_moe.py`` — gating invariants +
tiny MoE model training)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.moe import moe_mlp_block, top1_gating, topk_gating


def test_topk_gating_invariants():
    S, E, k = 64, 4, 2
    logits = jax.random.normal(jax.random.key(0), (S, E))
    dispatch, combine, aux, stats = topk_gating(logits, k=k, capacity_factor=2.0)
    C = dispatch.shape[-1]
    # each token dispatched at most k times, each slot holds at most one token
    assert dispatch.shape == (S, E, C)
    assert float(dispatch.sum(axis=(1, 2)).max()) <= k + 1e-6
    assert float(dispatch.sum(axis=0).max()) <= 1 + 1e-6  # slot occupancy
    # combine weights match dispatch support and sum to <= 1 per token
    assert np.all((np.asarray(combine) > 0) <= (np.asarray(dispatch) > 0))
    per_token = np.asarray(combine.sum(axis=(1, 2)))
    assert per_token.max() <= 1 + 1e-5
    assert float(aux) > 0


def test_capacity_drops_tokens():
    S, E = 64, 2
    # all tokens want expert 0 → capacity must drop most
    logits = jnp.stack([jnp.ones(S), -jnp.ones(S)], axis=1)
    dispatch, _, _, stats = top1_gating(logits, capacity_factor=0.5, min_capacity=4)
    kept = float(dispatch.sum())
    assert kept <= max(int(np.ceil(S / E * 0.5)), 4) + 1e-6


def test_moe_block_shapes_and_grads():
    cfg = get_preset("tiny-moe")
    model = TransformerLM(cfg, moe_fn=moe_mlp_block)
    params = model.init(jax.random.key(0))
    E = cfg.num_experts
    assert params["layers"]["mlp"]["w_up"].shape[1] == E
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (2, 16))}
    loss, grads = jax.value_and_grad(model.loss_fn)(params, batch)
    assert np.isfinite(float(loss))
    # router must receive gradient (aux loss + combine weights)
    rg = np.asarray(grads["layers"]["mlp"]["router"])
    assert np.abs(rg).sum() > 0


def test_moe_ep_training(eight_devices):
    """tiny MoE model trains on an ep×fsdp mesh (AutoEP-style EP×DP algebra)."""
    cfg = get_preset("tiny-moe")
    model = TransformerLM(cfg, moe_fn=moe_mlp_block)
    eng, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"ep": 4, "fsdp": 2},
        "steps_per_print": 100,
    })
    rng = np.random.default_rng(0)
    fixed = {"input_ids": rng.integers(0, 256, (2 * eng.topology.dp_world_size, 16))}
    losses = []
    for _ in range(4):
        loss = eng.forward(fixed)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


class TestGroupedDispatch:
    def test_grouped_matches_capacity_when_no_drops(self, eight_devices):
        """With capacity high enough that nothing drops, the grouped
        (ragged_dot) path computes the same function as the capacity einsum."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.moe import grouped_moe_mlp_block, moe_mlp_block

        class Cfg:
            top_k = 2
            capacity_factor = 8.0  # no drops
            min_capacity = 4

        rng = jax.random.split(jax.random.key(0), 5)
        D, F, E = 16, 32, 4
        w = {"router": jax.random.normal(rng[0], (D, E)) * 0.1,
             "w_gate": jax.random.normal(rng[1], (E, D, F)) / 4,
             "w_up": jax.random.normal(rng[2], (E, D, F)) / 4,
             "w_down": jax.random.normal(rng[3], (E, F, D)) / 6}
        h = jax.random.normal(rng[4], (2, 16, D))
        yc, auxc = moe_mlp_block(h, w, Cfg())
        yg, auxg = jax.jit(grouped_moe_mlp_block, static_argnums=2)(h, w, Cfg())
        np.testing.assert_allclose(np.asarray(yg), np.asarray(yc),
                                   rtol=2e-3, atol=2e-5)
        np.testing.assert_allclose(float(auxg), float(auxc), rtol=1e-5)

    def test_grouped_is_dropless(self, eight_devices):
        """At a starvation capacity the einsum path drops tokens; the grouped
        path computes all of them (the cutlass moe_gemm property)."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.moe import grouped_moe_mlp_block, moe_mlp_block
        from deepspeed_tpu.moe.sharded_moe import topk_gating

        class Tight:
            top_k = 2
            capacity_factor = 0.1
            min_capacity = 1

        rng = jax.random.split(jax.random.key(1), 5)
        D, F, E = 16, 32, 4
        w = {"router": jax.random.normal(rng[0], (D, E)) * 0.1,
             "w_gate": jax.random.normal(rng[1], (E, D, F)) / 4,
             "w_up": jax.random.normal(rng[2], (E, D, F)) / 4,
             "w_down": jax.random.normal(rng[3], (E, F, D)) / 6}
        h = jax.random.normal(rng[4], (1, 64, D))
        x = np.asarray(h.reshape(-1, D))
        logits = jnp.asarray(x) @ w["router"]
        _, _, _, stats = topk_gating(logits, k=2, capacity_factor=0.1,
                                     min_capacity=1)
        assert float(stats["drop_fraction"]) > 0.1  # einsum path drops
        yg, _ = grouped_moe_mlp_block(h, w, Tight())
        # every token got its full top-2 contribution: output differs from the
        # dropping path and is finite everywhere
        yc, _ = moe_mlp_block(h, w, Tight())
        assert np.isfinite(np.asarray(yg)).all()
        assert not np.allclose(np.asarray(yg), np.asarray(yc))

    def test_grouped_trains(self, eight_devices):
        """End to end under the engine with moe_dispatch='grouped'."""
        import dataclasses

        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset
        from deepspeed_tpu.moe import moe_block_for

        cfg = dataclasses.replace(get_preset("tiny-moe"),
                                  moe_dispatch="grouped")
        model = TransformerLM(cfg, moe_fn=moe_block_for(cfg))
        eng, *_ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
            "steps_per_print": 100})
        b = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 32))}
        losses = []
        for _ in range(4):
            loss = eng.forward(b)
            eng.backward(loss)
            eng.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

class TestGroupedEP:
    """Expert-parallel dropless dispatch (reference ``_AllToAll``
    moe/sharded_moe.py:97 + cutlass moe_gemm, as a padded a2a over ``ep``)."""

    @staticmethod
    def _weights(key, D=16, F=32, E=8):
        rng = jax.random.split(key, 5)
        w = {"router": jax.random.normal(rng[0], (D, E)) * 0.1,
             "w_gate": jax.random.normal(rng[1], (E, D, F)) / 4,
             "w_up": jax.random.normal(rng[2], (E, D, F)) / 4,
             "w_down": jax.random.normal(rng[3], (E, F, D)) / 6}
        return w, rng[4]

    @staticmethod
    def _ep_mesh(devices, ep=4, dp=2):
        from jax.sharding import Mesh

        return Mesh(np.array(devices[:ep * dp]).reshape(ep, dp), ("ep", "dp"))

    def test_ep_matches_single_shard(self, eight_devices):
        from deepspeed_tpu.moe import grouped_moe_mlp_block

        class Cfg:
            top_k = 2
            moe_ep_capacity_factor = 0.0

        w, hk = self._weights(jax.random.key(0))
        h = jax.random.normal(hk, (4, 16, 16))
        y1, aux1 = grouped_moe_mlp_block(h, w, Cfg())
        with jax.sharding.set_mesh(self._ep_mesh(eight_devices)):
            y2, aux2 = jax.jit(grouped_moe_mlp_block, static_argnums=2)(
                h, w, Cfg())
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y1),
                                   rtol=2e-3, atol=1e-5)
        np.testing.assert_allclose(float(aux2), float(aux1), rtol=1e-5)

    def test_ep_dropless_under_total_imbalance(self, eight_devices):
        """All tokens route to the experts of ONE ep shard — the worst-case
        a2a load — and the default capacity still computes every pair."""
        from deepspeed_tpu.moe import grouped_moe_mlp_block

        class Cfg:
            top_k = 2
            moe_ep_capacity_factor = 0.0

        w, hk = self._weights(jax.random.key(1))
        # bias the router so experts 0/1 (both on ep shard 0) win everywhere
        w["router"] = w["router"] * 0.0 + jnp.array(
            [8.0, 7.0] + [-8.0] * 6)[None, :]
        h = jax.random.normal(hk, (4, 16, 16))
        y1, _ = grouped_moe_mlp_block(h, w, Cfg())
        with jax.sharding.set_mesh(self._ep_mesh(eight_devices)):
            y2, _ = jax.jit(grouped_moe_mlp_block, static_argnums=2)(
                h, w, Cfg())
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y1),
                                   rtol=2e-3, atol=1e-5)

    def test_ep_capacity_factor_bounds_payload(self, eight_devices):
        """With a finite moe_ep_capacity_factor the a2a buffer shrinks and
        overflow pairs are dropped (documented trade): output stays finite
        and differs from the dropless result under total imbalance."""
        from deepspeed_tpu.moe import grouped_moe_mlp_block

        class Tight:
            top_k = 2
            moe_ep_capacity_factor = 1.0   # balanced-load capacity only

        w, hk = self._weights(jax.random.key(2))
        w["router"] = w["router"] * 0.0 + jnp.array(
            [8.0, 7.0] + [-8.0] * 6)[None, :]
        h = jax.random.normal(hk, (4, 16, 16))
        y_dropless, _ = grouped_moe_mlp_block(h, w, type(
            "C", (), {"top_k": 2, "moe_ep_capacity_factor": 0.0}))
        with jax.sharding.set_mesh(self._ep_mesh(eight_devices)):
            y_tight, _ = jax.jit(grouped_moe_mlp_block, static_argnums=2)(
                h, w, Tight())
        assert np.isfinite(np.asarray(y_tight)).all()
        assert not np.allclose(np.asarray(y_tight), np.asarray(y_dropless))

    def test_mixtral_serves_under_ep(self, eight_devices, tmp_path):
        """Imported Mixtral generates on an ep=2 mesh with greedy decode
        matching HF exactly — expert parallelism WITH the released routing
        (the round-2 gap: grouped dispatch used to refuse ep>1)."""
        import torch
        from transformers import MixtralConfig, MixtralForCausalLM

        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.models.hf import load_hf_checkpoint

        torch.manual_seed(0)
        cfg = MixtralConfig(vocab_size=128, hidden_size=32,
                            intermediate_size=64, num_hidden_layers=2,
                            num_attention_heads=4, num_key_value_heads=2,
                            num_local_experts=4, num_experts_per_tok=2,
                            max_position_embeddings=64)
        hf = MixtralForCausalLM(cfg)
        hf.save_pretrained(str(tmp_path))
        model, params = load_hf_checkpoint(str(tmp_path), dtype="float32")
        eng = InferenceEngine(model, config={"mesh": {"ep": 2, "dp": 4}},
                              params=params)
        ids = np.random.default_rng(0).integers(0, 128, (4, 8))
        out = np.asarray(eng.generate(ids, max_new_tokens=4))
        with torch.no_grad():
            ref = hf.generate(torch.tensor(ids), max_new_tokens=4,
                              do_sample=False).numpy()
        np.testing.assert_array_equal(out, ref)
        # single request: decode steps have S=1 < ep — the pad path
        out1 = np.asarray(eng.generate(ids[:1], max_new_tokens=4))
        np.testing.assert_array_equal(out1[0], ref[0])

    def test_ep_grouped_trains(self, eight_devices):
        """End to end: moe_dispatch='grouped' now composes with ep>1."""
        import dataclasses

        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset
        from deepspeed_tpu.moe import moe_block_for

        cfg = dataclasses.replace(get_preset("tiny-moe"),
                                  moe_dispatch="grouped")
        model = TransformerLM(cfg, moe_fn=moe_block_for(cfg))
        eng, *_ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}, "mesh": {"ep": 4, "dp": 2},
            "steps_per_print": 100})
        b = {"input_ids": np.random.default_rng(0).integers(0, 256, (4, 32))}
        losses = []
        for _ in range(4):
            loss = eng.forward(b)
            eng.backward(loss)
            eng.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


def test_capacity_moe_decode_ignores_idle_lanes(eight_devices):
    """A capacity-dispatch MoE model served with a mostly-empty batch must
    match the solo reference: pad/idle lanes are masked out of expert
    capacity competition. The real sequence is placed in a LATE slot so the
    idle lanes (all embedding token 0 — identical router picks) precede it in
    the capacity cumsum; without the valid mask they would fill the experts'
    capacity and evict the real tokens' assignments. A 4-token prompt keeps
    every path inside min_capacity, so any post-fix mismatch is eviction,
    not the (inherent) capacity-vs-batch-shape difference."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    cfg = get_preset("tiny-moe")  # moe_dispatch='capacity'
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(9)
    p = rng.integers(0, 256, 4)

    # solo reference through a batch-of-one dense cache
    cache = model.init_kv_cache(1, 32)
    lg, _ = model.forward_with_cache(params, p[None].astype(np.int32), cache)
    ref = np.asarray(lg[0, -1], np.float32)

    for packed in (True, False):
        eng = InferenceEngineV2(model, params=params, max_sequences=8,
                                max_seq_len=32, block_size=8, packed=packed)
        # burn slots 0-3 then free 0-2: uid 5 lands in slot 4 with four
        # idle-lane slots ahead of it in row order
        for uid in (1, 2, 3, 4):
            eng.put([uid], [rng.integers(0, 256, 4)])
        eng.flush([1, 2, 3])
        r = eng.put([5], [p])
        assert eng.state.sequences[5].slot == 4
        np.testing.assert_allclose(np.asarray(r[5], np.float32), ref,
                                   atol=3e-2)


# ---------------------------------------------------------------------------
# dropless grouped kernels: ragged_dot vs the padded one-hot einsum
# ---------------------------------------------------------------------------

class TestDroplessKernels:
    @staticmethod
    def _weights(D, F, E, seed=0):
        rng = jax.random.split(jax.random.key(seed), 4)
        return {"router": jax.random.normal(rng[0], (D, E)) * 0.1,
                "w_gate": jax.random.normal(rng[1], (E, D, F)) / 4,
                "w_up": jax.random.normal(rng[2], (E, D, F)) / 4,
                "w_down": jax.random.normal(rng[3], (E, F, D)) / 6}

    @pytest.mark.parametrize("top_k", [1, 2])
    @pytest.mark.parametrize("shape", [(2, 16), (1, 13), (3, 7)])
    def test_ragged_padded_bit_identity(self, top_k, shape):
        """fp32 outputs of the ragged grouped GEMM and the padded one-hot
        einsum reference are BITWISE identical — including odd token
        counts and B=1 decode shapes — so flipping ``moe.kernel`` can
        never change greedy decode output."""
        from deepspeed_tpu.moe import grouped_moe_mlp_block

        class Cfg:
            pass

        Cfg.top_k = top_k
        w = self._weights(16, 32, 4, seed=top_k)
        h = jax.random.normal(jax.random.key(9), (*shape, 16), jnp.float32)
        jfn = jax.jit(grouped_moe_mlp_block, static_argnums=2,
                      static_argnames=("kernel",))
        yr, ar = jfn(h, w, Cfg, kernel="ragged")
        yp, ap = jfn(h, w, Cfg, kernel="padded")
        np.testing.assert_array_equal(np.asarray(yr), np.asarray(yp))
        assert float(ar) == float(ap)

    def test_dropless_beats_capacity_overflow(self):
        """Regression vs the capacity path: route EVERY token to one
        expert — the capacity einsum drops most of them, the grouped path
        drops none (each token keeps its full top-k contribution)."""
        from deepspeed_tpu.moe import grouped_moe_mlp_block, moe_mlp_block
        from deepspeed_tpu.moe.sharded_moe import topk_gating

        class Tight:
            top_k = 1
            capacity_factor = 1.0
            min_capacity = 1

        D, F, E = 16, 32, 4
        w = self._weights(D, F, E, seed=3)
        # a router column so dominant every token picks expert 2
        # (positive activations so the +50 column cannot sign-flip)
        w["router"] = w["router"].at[:, 2].add(50.0)
        h = jax.random.uniform(jax.random.key(5), (1, 32, D), jnp.float32,
                               0.05, 1.0)
        logits = h.reshape(-1, D) @ w["router"]
        _, _, _, stats = topk_gating(logits, k=1, capacity_factor=1.0,
                                     min_capacity=1)
        # capacity cap = S*f/E = 8 of 32 tokens survive the einsum path
        assert float(stats["drop_fraction"]) >= 0.5
        yg, _ = grouped_moe_mlp_block(h, w, Tight)
        yc, _ = moe_mlp_block(h, w, Tight)
        dropped = np.asarray(jnp.sum(jnp.abs(yc), -1) == 0)
        kept_g = np.asarray(jnp.sum(jnp.abs(yg), -1) > 0)
        assert dropped.sum() >= 16          # the einsum really dropped
        assert kept_g.all()                 # the grouped path kept all

    def test_resolve_kernel_and_fallback_warning(self, monkeypatch, caplog):
        """``moe.kernel: ragged`` degrades to padded with exactly ONE
        logged warning when the grouped GEMM cannot lower; bad names are
        rejected; ``padded`` never consults the probe."""
        import logging

        from deepspeed_tpu.moe import sharded_moe as sm

        with pytest.raises(ValueError):
            sm.resolve_moe_kernel("cutlass")
        assert sm.resolve_moe_kernel("padded") == ("padded", "")
        # this host lowers ragged_dot (the probe is memoized)
        assert sm.resolve_moe_kernel("ragged")[0] == "ragged"
        monkeypatch.setattr(sm, "_SUPPORT_MEMO", (None, "forced by test"))
        monkeypatch.setattr(sm, "_FALLBACK_WARNED", False)
        with caplog.at_level(logging.WARNING):
            k1, why1 = sm.resolve_moe_kernel("ragged")
            k2, _ = sm.resolve_moe_kernel("ragged")
        assert (k1, k2) == ("padded", "padded") and why1 == "forced by test"
        warned = [r for r in caplog.records
                  if "falling back" in r.getMessage()]
        assert len(warned) <= 1

    def test_kernel_config_plumbing(self):
        """The knob exists at every layer: MoEConfig validates it, the
        transformer config carries it, the probe reports this backend."""
        from deepspeed_tpu.config.config import MoEConfig
        from deepspeed_tpu.moe import MOE_KERNELS, moe_kernel_support

        assert MoEConfig(kernel="padded").kernel == "padded"
        assert MoEConfig(a2a_bits=8).a2a_bits == 8
        with pytest.raises(Exception):
            MoEConfig(kernel="blocked")
        with pytest.raises(Exception):
            MoEConfig(a2a_bits=3)
        cfg = get_preset("tiny", num_experts=4, moe_kernel="padded",
                         moe_a2a_bits=8, moe_a2a_slice=2)
        assert (cfg.moe_kernel, cfg.moe_a2a_bits, cfg.moe_a2a_slice) == \
            ("padded", 8, 2)
        assert set(MOE_KERNELS) == {"ragged", "padded"}
        mode, why = moe_kernel_support()
        assert mode in (None, "native") and why
