"""MoE tests (pattern: reference ``tests/unit/moe/test_moe.py`` — gating invariants +
tiny MoE model training)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.moe import moe_mlp_block, top1_gating, topk_gating


def test_topk_gating_invariants():
    S, E, k = 64, 4, 2
    logits = jax.random.normal(jax.random.key(0), (S, E))
    dispatch, combine, aux, stats = topk_gating(logits, k=k, capacity_factor=2.0)
    C = dispatch.shape[-1]
    # each token dispatched at most k times, each slot holds at most one token
    assert dispatch.shape == (S, E, C)
    assert float(dispatch.sum(axis=(1, 2)).max()) <= k + 1e-6
    assert float(dispatch.sum(axis=0).max()) <= 1 + 1e-6  # slot occupancy
    # combine weights match dispatch support and sum to <= 1 per token
    assert np.all((np.asarray(combine) > 0) <= (np.asarray(dispatch) > 0))
    per_token = np.asarray(combine.sum(axis=(1, 2)))
    assert per_token.max() <= 1 + 1e-5
    assert float(aux) > 0


def test_capacity_drops_tokens():
    S, E = 64, 2
    # all tokens want expert 0 → capacity must drop most
    logits = jnp.stack([jnp.ones(S), -jnp.ones(S)], axis=1)
    dispatch, _, _, stats = top1_gating(logits, capacity_factor=0.5, min_capacity=4)
    kept = float(dispatch.sum())
    assert kept <= max(int(np.ceil(S / E * 0.5)), 4) + 1e-6


def test_moe_block_shapes_and_grads():
    cfg = get_preset("tiny-moe")
    model = TransformerLM(cfg, moe_fn=moe_mlp_block)
    params = model.init(jax.random.key(0))
    E = cfg.num_experts
    assert params["layers"]["mlp"]["w_up"].shape[1] == E
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (2, 16))}
    loss, grads = jax.value_and_grad(model.loss_fn)(params, batch)
    assert np.isfinite(float(loss))
    # router must receive gradient (aux loss + combine weights)
    rg = np.asarray(grads["layers"]["mlp"]["router"])
    assert np.abs(rg).sum() > 0


def test_moe_ep_training(eight_devices):
    """tiny MoE model trains on an ep×fsdp mesh (AutoEP-style EP×DP algebra)."""
    cfg = get_preset("tiny-moe")
    model = TransformerLM(cfg, moe_fn=moe_mlp_block)
    eng, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"ep": 4, "fsdp": 2},
        "steps_per_print": 100,
    })
    rng = np.random.default_rng(0)
    fixed = {"input_ids": rng.integers(0, 256, (2 * eng.topology.dp_world_size, 16))}
    losses = []
    for _ in range(4):
        loss = eng.forward(fixed)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


class TestGroupedDispatch:
    def test_grouped_matches_capacity_when_no_drops(self, eight_devices):
        """With capacity high enough that nothing drops, the grouped
        (ragged_dot) path computes the same function as the capacity einsum."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.moe import grouped_moe_mlp_block, moe_mlp_block

        class Cfg:
            top_k = 2
            capacity_factor = 8.0  # no drops
            min_capacity = 4

        rng = jax.random.split(jax.random.key(0), 5)
        D, F, E = 16, 32, 4
        w = {"router": jax.random.normal(rng[0], (D, E)) * 0.1,
             "w_gate": jax.random.normal(rng[1], (E, D, F)) / 4,
             "w_up": jax.random.normal(rng[2], (E, D, F)) / 4,
             "w_down": jax.random.normal(rng[3], (E, F, D)) / 6}
        h = jax.random.normal(rng[4], (2, 16, D))
        yc, auxc = moe_mlp_block(h, w, Cfg())
        yg, auxg = jax.jit(grouped_moe_mlp_block, static_argnums=2)(h, w, Cfg())
        np.testing.assert_allclose(np.asarray(yg), np.asarray(yc),
                                   rtol=2e-3, atol=2e-5)
        np.testing.assert_allclose(float(auxg), float(auxc), rtol=1e-5)

    def test_grouped_is_dropless(self, eight_devices):
        """At a starvation capacity the einsum path drops tokens; the grouped
        path computes all of them (the cutlass moe_gemm property)."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.moe import grouped_moe_mlp_block, moe_mlp_block
        from deepspeed_tpu.moe.sharded_moe import topk_gating

        class Tight:
            top_k = 2
            capacity_factor = 0.1
            min_capacity = 1

        rng = jax.random.split(jax.random.key(1), 5)
        D, F, E = 16, 32, 4
        w = {"router": jax.random.normal(rng[0], (D, E)) * 0.1,
             "w_gate": jax.random.normal(rng[1], (E, D, F)) / 4,
             "w_up": jax.random.normal(rng[2], (E, D, F)) / 4,
             "w_down": jax.random.normal(rng[3], (E, F, D)) / 6}
        h = jax.random.normal(rng[4], (1, 64, D))
        x = np.asarray(h.reshape(-1, D))
        logits = jnp.asarray(x) @ w["router"]
        _, _, _, stats = topk_gating(logits, k=2, capacity_factor=0.1,
                                     min_capacity=1)
        assert float(stats["drop_fraction"]) > 0.1  # einsum path drops
        yg, _ = grouped_moe_mlp_block(h, w, Tight())
        # every token got its full top-2 contribution: output differs from the
        # dropping path and is finite everywhere
        yc, _ = moe_mlp_block(h, w, Tight())
        assert np.isfinite(np.asarray(yg)).all()
        assert not np.allclose(np.asarray(yg), np.asarray(yc))

    def test_grouped_trains(self, eight_devices):
        """End to end under the engine with moe_dispatch='grouped'."""
        import dataclasses

        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset
        from deepspeed_tpu.moe import moe_block_for

        cfg = dataclasses.replace(get_preset("tiny-moe"),
                                  moe_dispatch="grouped")
        model = TransformerLM(cfg, moe_fn=moe_block_for(cfg))
        eng, *_ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
            "steps_per_print": 100})
        b = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 32))}
        losses = []
        for _ in range(4):
            loss = eng.forward(b)
            eng.backward(loss)
            eng.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_grouped_rejects_ep(self, eight_devices):
        import dataclasses

        import deepspeed_tpu as ds
        from deepspeed_tpu.models import TransformerLM, get_preset
        from deepspeed_tpu.moe import moe_block_for

        cfg = dataclasses.replace(get_preset("tiny-moe"),
                                  moe_dispatch="grouped")
        model = TransformerLM(cfg, moe_fn=moe_block_for(cfg))
        with pytest.raises(Exception, match="ep"):
            eng, *_ = ds.initialize(model=model, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0}, "mesh": {"ep": 4, "dp": 2},
                "steps_per_print": 100})
            eng.forward({"input_ids": np.zeros((4, 32), np.int32)})
