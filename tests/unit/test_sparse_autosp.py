"""Block-sparse attention + AutoSP tests (analogs of the reference's
``tests/unit/ops/sparse_attention`` parity tests and sequence/test_autosp)."""

import dataclasses

import jax
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import xla_attention
from deepspeed_tpu.ops.sparse_attention import (bigbird_layout,
                                                block_sparse_attention,
                                                fixed_layout, longformer_layout,
                                                make_sparse_attention_impl)
from deepspeed_tpu.sequence.auto_sp import auto_wrap_model_for_sp, suggest_sp


def _qkv(T=256, H=4, K=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return (jax.random.normal(ks[0], (2, T, H, d)),
            jax.random.normal(ks[1], (2, T, K, d)),
            jax.random.normal(ks[2], (2, T, K, d)))


def _dense_masked(q, k, v, lay, block, causal):
    """Dense reference with the same block mask at element level."""
    import jax.numpy as jnp
    import math

    from deepspeed_tpu.models.transformer import repeat_kv

    k, v = repeat_kv(k, v, q.shape[2])
    T = q.shape[1]
    elem = np.kron(np.asarray(lay, bool), np.ones((block, block), bool))
    if causal:
        elem &= np.tril(np.ones((T, T), bool))
    s = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(q.shape[-1])
    s = jnp.where(jnp.asarray(elem)[None, None], s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@pytest.mark.parametrize("layout_fn,kw", [
    (fixed_layout, {"num_local_blocks": 2, "num_global_blocks": 1}),
    (bigbird_layout, {"num_sliding_window_blocks": 3, "num_global_blocks": 1,
                      "num_random_blocks": 1}),
    (longformer_layout, {"num_sliding_window_blocks": 3,
                         "global_block_indices": (0, 2)}),
])
@pytest.mark.parametrize("causal", [True, False])
def test_block_sparse_matches_dense_masked(layout_fn, kw, causal):
    q, k, v = _qkv(T=256)
    lay = layout_fn(4, **kw)
    got = block_sparse_attention(q, k, v, lay, block=64, causal=causal)
    ref = _dense_masked(q, k, v, lay, 64, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_block_sparse_gqa():
    q, k, v = _qkv(T=128, H=8, K=2)
    lay = fixed_layout(2, num_local_blocks=1)
    got = block_sparse_attention(q, k, v, lay, block=64)
    ref = _dense_masked(q, k, v, lay, 64, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_sparse_impl_in_model_registry(eight_devices):
    """The registry impl runs a model forward end to end."""
    from deepspeed_tpu.models import TransformerLM, TransformerConfig
    from deepspeed_tpu.models.transformer import register_attention_impl

    register_attention_impl("sparse_fixed", make_sparse_attention_impl(
        fixed_layout, block=32, num_local_blocks=2))
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=128,
                            attention_impl="sparse_fixed")
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    loss = model.loss_fn(params, {"input_ids": np.random.default_rng(0)
                                  .integers(0, 128, (2, 128))})
    assert np.isfinite(float(loss))


def test_layouts_shapes():
    assert fixed_layout(8, 2, 1).sum() > 8  # band + globals
    bb = bigbird_layout(8, 3, 1, 1, seed=0)
    assert bb[:, 0].all() and bb[0].all()   # global row/col
    lf = longformer_layout(8, 3, (0, 4))
    assert lf[:, 4].all() and lf[4].all()


def test_suggest_sp_policy():
    # plenty of tokens: take the biggest divisor with heads compatible
    assert suggest_sp(65536, 8, 16, 16, tokens_per_shard=4096) == (8, "ulysses")
    # GQA with 2 kv heads: sp=8 can't do ulysses → ring
    assert suggest_sp(65536, 8, 16, 2, tokens_per_shard=4096) == (8, "ring")
    # short sequences: stay dense
    assert suggest_sp(2048, 8, 16, 16, tokens_per_shard=4096) == (1, "auto")


def test_auto_wrap_refuses_custom_impl():
    from deepspeed_tpu.models import TransformerLM, get_preset

    model = TransformerLM(dataclasses.replace(get_preset("tiny"),
                                              attention_impl="ring"))
    with pytest.raises(ValueError, match="cannot override"):
        auto_wrap_model_for_sp(model, seq_len=32768, max_sp=8)


def test_auto_wrap_model(eight_devices):
    from deepspeed_tpu.models import TransformerLM, get_preset

    model = TransformerLM(dataclasses.replace(get_preset("tiny"),
                                              max_seq_len=32768))
    m2, mesh = auto_wrap_model_for_sp(model, seq_len=32768, max_sp=8)
    assert mesh == {"sp": 8}
    assert m2.cfg.attention_impl in ("ulysses", "ring")
    # params interchangeable (same shapes/config otherwise)
    p = model.init(jax.random.key(0))
    import deepspeed_tpu as ds

    eng, *_ = ds.initialize(model=m2, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"sp": 8, "dp": 1}, "steps_per_print": 100})
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (1, 4096))}
    loss = eng.forward(batch)
    assert np.isfinite(float(loss))
