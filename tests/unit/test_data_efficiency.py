"""Data-efficiency tests — curriculum sampler/truncation through the engine,
variable batch+LR, and random-LTD (analog of the reference's
``tests/unit/runtime/data_efficiency``)."""

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler, DataEfficiencySampler, VariableBatchDataLoader,
    VariableBatchLRSchedule, batch_by_tokens, lr_scale_for_batch)


def test_curriculum_sampler_respects_difficulty():
    """Early steps draw only easy samples; late steps draw from everything."""
    n = 256
    difficulties = np.arange(n)  # sample i has difficulty i
    sched = CurriculumScheduler({
        "min_difficulty": 16, "max_difficulty": 256,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    sampler = DataEfficiencySampler(difficulties, batch_size=8,
                                    scheduler=sched, seed=0)
    it = iter(sampler)
    early = next(it)
    assert difficulties[early].max() <= 16
    sampler.set_step(100)
    late = next(iter(sampler))
    assert difficulties[late].max() > 64  # full pool reachable


def test_curriculum_engine_seqlen_schedule(eight_devices):
    """The engine truncates batches to the schedule: early steps train on
    short sequences, difficulty grows across steps (VERDICT done-criterion)."""
    eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")), config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"dp": 8},
        "data_efficiency": {
            "enabled": True,
            "data_sampling": {"enabled": True, "curriculum_learning": {
                "enabled": True, "min_difficulty": 8, "max_difficulty": 32,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 4,
                                    "difficulty_step": 8}}}},
        "steps_per_print": 100})
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 32))}
    seqlens = []
    for _ in range(5):
        loss = eng.forward(batch)
        # the jitted program saw the truncated batch
        seqlens.append(eng.curriculum_difficulty())
        eng.backward(loss)
        eng.step()
    assert seqlens[0] == 8 and seqlens[-1] == 32
    assert seqlens == sorted(seqlens), "difficulty must be non-decreasing"
    assert np.isfinite(float(loss))


def test_batch_by_tokens_budget():
    rng = np.random.default_rng(0)
    seqlens = rng.integers(16, 257, size=200)
    batches = batch_by_tokens(seqlens, max_tokens=1024)
    covered = np.concatenate(batches)
    assert sorted(covered) == list(range(200))  # partition, no dupes/drops
    for b in batches:
        max_len = seqlens[b].max()
        assert len(b) * max_len <= 1024 or len(b) == 1
        assert len(b) in (1, 2, 4, 8, 16, 32, 64, 128, 256)


def test_variable_batch_lr_schedule():
    sched = VariableBatchLRSchedule(lambda step: 1e-3, base_batch_size=8,
                                    method="linear")
    sched.set_batch_size(16)
    assert float(sched(0)) == pytest.approx(2e-3)
    sched.set_batch_size(4)
    assert float(sched(0)) == pytest.approx(5e-4)
    assert lr_scale_for_batch(32, 8, "sqrt") == pytest.approx(2.0)


def test_variable_batch_loader_trains(eight_devices):
    """Token-budget batches + scaled LR drive the engine end to end."""
    rng = np.random.default_rng(0)
    # bimodal lengths: short docs pack 32/batch, long docs 8/batch
    data = [{"input_ids": rng.integers(0, 256, (8 if i < 32 else 64,))}
            for i in range(64)]
    seqlens = [len(d["input_ids"]) for d in data]

    def collate(samples):
        L = max(len(s["input_ids"]) for s in samples)
        ids = np.zeros((len(samples), L), np.int32)
        for i, s in enumerate(samples):
            ids[i, :len(s["input_ids"])] = s["input_ids"]
        return {"input_ids": ids}

    # bucket sizes divisible by dp=8 so every variable batch shards cleanly
    loader = VariableBatchDataLoader(data, seqlens, max_tokens=512,
                                     collate_fn=collate, base_batch_size=16,
                                     bucket_batch_sizes=[8, 16, 32])
    base_sched = VariableBatchLRSchedule(lambda s: 1e-3, base_batch_size=16)
    eng, *_ = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        optimizer=None,
        lr_scheduler=base_sched,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
                "steps_per_print": 100})
    sizes = set()
    for batch, scale in loader:
        base_sched.set_batch_size(batch["input_ids"].shape[0])
        sizes.add(batch["input_ids"].shape[0])
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
    assert len(sizes) > 1, "expected variable batch sizes"
    assert np.isfinite(float(loss))


def test_random_ltd_engine(eight_devices):
    """Random-LTD: kept-token schedule grows across steps, training converges,
    and keep == T reduces to the dense path."""
    def build(ltd):
        cfg = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
            "steps_per_print": 100}
        if ltd:
            cfg["data_efficiency"] = {
                "enabled": True,
                "data_routing": {"enabled": True, "random_ltd": {
                    "enabled": True, "min_value": 16, "step_size": 8,
                    "interval": 2}}}
        return ds.initialize(model=TransformerLM(get_preset("tiny")),
                             config=cfg)[0]

    eng = build(True)
    batch = {"input_ids": np.random.default_rng(0).integers(0, 256, (16, 32))}
    keeps, losses = [], []
    for _ in range(6):
        loss = eng.forward(batch)
        keeps.append(eng.module._ltd_keep)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    assert keeps[0] == 16 and keeps[-1] > keeps[0], keeps
    assert losses[-1] < losses[0]
    # keep >= T: dense semantics (ltd branch never taken)
    eng2 = build(True)
    eng2._ltd_cfg.min_value = 64  # > T
    eng2._update_random_ltd()
    l2 = float(eng2.forward(batch))
    dense = build(False)
    ld = float(dense.forward(batch))
    np.testing.assert_allclose(l2, ld, rtol=1e-5)


def test_curriculum_bucket_count_guarded(eight_devices):
    """Round-2 weak #6: a fine-grained difficulty schedule would thrash the
    jit cache one compile per distinct sequence length — the engine now
    rejects schedules with more than 64 shape buckets."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    with pytest.raises(ValueError, match="buckets"):
        ds.initialize(model=TransformerLM(get_preset("tiny")), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}, "mesh": {"dp": 8},
            "steps_per_print": 100,
            "data_efficiency": {"enabled": True, "data_sampling": {
                "enabled": True, "curriculum_learning": {
                    "enabled": True, "min_difficulty": 8,
                    "max_difficulty": 1024,
                    "schedule_config": {"difficulty_step": 1,
                                        "total_curriculum_step": 100}}}}})
