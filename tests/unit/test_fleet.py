"""Elastic-fleet tests: replica lifecycle (crash recovery, readmission,
scaling), the warm-start cache, and the new fault-injection sites.

Fast tests exercise the router's elasticity surface and the
:class:`WarmStartCache` directly; the end-to-end crash/scale/swap storms
live in ``tools/elastic_drill.py`` with slow pytest wrappers at the
bottom (``elastic`` + ``slow`` markers, like the chaos/serving drills)."""

import os
import sys
import time

import numpy as np
import pytest

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")
sys.path.insert(0, _TOOLS)

TERMINAL = ("completed", "shed", "expired")


def _make_replica(name, cache=None, key=None, **serving):
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.serving import ContinuousBatcher, Replica

    ekw = dict(max_sequences=8, max_seq_len=128, block_size=16)
    if cache is not None:
        eng, info = cache.build_engine(
            key, lambda: TransformerLM(get_preset("tiny")), engine_kw=ekw)
    else:
        eng = InferenceEngineV2(TransformerLM(get_preset("tiny")), **ekw)
        info = None
    cfg = ServingConfig(**{"prefill_chunk": 32,
                           "default_max_new_tokens": 4, **serving})
    rep = Replica(name, ContinuousBatcher(eng, cfg))
    if info is not None:
        rep.start_info = info
    return rep


def _await(fn, timeout_s=30.0, interval=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


# ---------------------------------------------------------------------------
# fault-injection sites
# ---------------------------------------------------------------------------
@pytest.mark.elastic
class TestReplicaFaultSites:
    def test_new_kinds_accepted(self):
        from deepspeed_tpu.resilience.faults import FaultSpec

        for kind in ("replica_crash", "slow_start", "weight_load_io_error"):
            assert FaultSpec(kind=kind).kind == kind

    def test_replica_crash_site_pinning(self):
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     FaultSpec,
                                                     InjectedCrash)

        inj = FaultInjector([FaultSpec(kind="replica_crash", site="r1")])
        inj.on_replica_loop("r0")            # pinned elsewhere: no fire
        with pytest.raises(InjectedCrash):
            inj.on_replica_loop("r1")
        inj.on_replica_loop("r1")            # occurrence-counted: once

    def test_slow_start_sleeps(self):
        from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec

        inj = FaultInjector([FaultSpec(kind="slow_start", delay_s=0.05)])
        t0 = time.monotonic()
        inj.on_replica_start("r0")
        assert time.monotonic() - t0 >= 0.05
        assert inj.fired and "replica_start" in inj.fired[0]

    def test_weight_load_io_error_sited(self):
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     FaultSpec,
                                                     InjectedIOError)

        inj = FaultInjector([FaultSpec(kind="weight_load_io_error",
                                       site="warm")])
        inj.on_weight_load("publish")        # other site: no fire
        with pytest.raises(InjectedIOError):
            inj.on_weight_load("warm")


# ---------------------------------------------------------------------------
# fleet config
# ---------------------------------------------------------------------------
@pytest.mark.elastic
class TestFleetConfig:
    def test_defaults_valid(self):
        from deepspeed_tpu.config.config import FleetConfig, ServingConfig

        cfg = FleetConfig()
        assert cfg.min_replicas <= cfg.max_replicas
        assert ServingConfig().fleet.min_ready_floor >= 0

    @pytest.mark.parametrize("bad", [
        {"min_replicas": 0},
        {"min_replicas": 4, "max_replicas": 2},
        {"heartbeat_timeout_s": 0.0},
        {"max_respawns": 0},
        {"scale_up_polls": 0},
    ])
    def test_bounds_rejected(self, bad):
        from deepspeed_tpu.config.config import FleetConfig

        with pytest.raises(Exception):
            FleetConfig(**bad)


# ---------------------------------------------------------------------------
# warm-start cache (no engine needed for the weight roundtrip)
# ---------------------------------------------------------------------------
@pytest.mark.elastic
class TestWarmStartCache:
    def _tree(self):
        rng = np.random.default_rng(0)
        return {"wte": rng.standard_normal((8, 4)).astype(np.float32),
                "blocks": [{"w": rng.standard_normal((4, 4))
                            .astype(np.float32)} for _ in range(2)],
                "scale": np.float32(2.5)}

    def test_flatten_roundtrip(self):
        from deepspeed_tpu.serving.coldstart import _flatten, _unflatten

        tree = self._tree()
        rebuilt = _unflatten([(list(map(list, p)), leaf)
                              for p, leaf in _flatten(tree)])
        assert sorted(rebuilt) == sorted(tree)
        np.testing.assert_array_equal(rebuilt["wte"], tree["wte"])
        np.testing.assert_array_equal(rebuilt["blocks"][1]["w"],
                                      tree["blocks"][1]["w"])

    def test_publish_load_roundtrip(self, tmp_path):
        from deepspeed_tpu.serving import WarmStartCache

        cache = WarmStartCache(str(tmp_path))
        tree = self._tree()
        assert cache.publish("k1", tree)
        assert cache.has_params("k1")
        # a SECOND cache instance on the same dir (fresh process stand-in)
        other = WarmStartCache(str(tmp_path))
        out = other.load_params("k1")
        np.testing.assert_array_equal(out["wte"], tree["wte"])
        np.testing.assert_array_equal(out["blocks"][0]["w"],
                                      tree["blocks"][0]["w"])
        assert other.counters["warm_loads"] == 1

    def test_corrupt_manifest_raises_cleanly(self, tmp_path):
        from deepspeed_tpu.serving import WarmStartCache

        cache = WarmStartCache(str(tmp_path))
        cache.publish("k1", self._tree())
        with open(cache.manifest_path("k1"), "w") as f:
            f.write("{not json at all")
        with pytest.raises((OSError, ValueError)):
            cache.load_params("k1")

    def test_torn_swap_file_raises_cleanly(self, tmp_path):
        from deepspeed_tpu.serving import WarmStartCache

        cache = WarmStartCache(str(tmp_path))
        cache.publish("k1", self._tree())
        swap_dir = os.path.join(tmp_path, "weights")   # swapper namespace
        swps = [os.path.join(swap_dir, p) for p in os.listdir(swap_dir)
                if p.endswith(".swp")]
        victim = max(swps, key=os.path.getsize)
        with open(victim, "r+b") as f:
            f.truncate(max(os.path.getsize(victim) // 2, 1))
        fresh = WarmStartCache(str(tmp_path))   # no in-memory meta
        with pytest.raises((OSError, ValueError)):
            fresh.load_params("k1")

    def test_injected_io_error_on_load(self, tmp_path):
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     FaultSpec,
                                                     InjectedIOError,
                                                     set_injector)
        from deepspeed_tpu.serving import WarmStartCache

        cache = WarmStartCache(str(tmp_path))
        cache.publish("k1", self._tree())
        set_injector(FaultInjector(
            [FaultSpec(kind="weight_load_io_error", site="warm")]))
        try:
            with pytest.raises(InjectedIOError):
                cache.load_params("k1")
        finally:
            set_injector(None)
        assert cache.load_params("k1")["wte"].shape == (8, 4)

    def test_evict_module(self):
        from deepspeed_tpu.serving.coldstart import _MODULES, evict_module

        _MODULES["tmp_key"] = object()
        assert evict_module("tmp_key")
        assert not evict_module("tmp_key")


# ---------------------------------------------------------------------------
# router elasticity surface (readmit / add / remove / retired ledger)
# ---------------------------------------------------------------------------
@pytest.mark.elastic
@pytest.mark.serving
class TestRouterElasticity:
    def test_crash_respawn_readmit_resolves_old_uids(self, tmp_path,
                                                     eight_devices):
        from deepspeed_tpu.resilience.faults import (FaultInjector,
                                                     FaultSpec,
                                                     set_injector)
        from deepspeed_tpu.serving import (FleetController, ReplicaRouter,
                                           WarmStartCache, warm_key)
        from deepspeed_tpu.config.config import FleetConfig
        from deepspeed_tpu.models import TransformerLM, get_preset

        cache = WarmStartCache(str(tmp_path))
        key = warm_key(TransformerLM(get_preset("tiny")))
        factory = lambda name: _make_replica(name, cache=cache, key=key)
        router = ReplicaRouter([factory("r0"), factory("r1")]).start()
        fc = FleetController(router, factory,
                             FleetConfig(respawn_backoff_s=0.0))
        try:
            uids = [router.submit([1, 2, 3], max_new_tokens=4)
                    for _ in range(12)]
            set_injector(FaultInjector(
                [FaultSpec(kind="replica_crash", site="r0")]))
            assert _await(lambda: not router.replicas["r0"].alive, 15)
            set_injector(None)
            actions = fc.poll()
            assert actions["recovered"] and \
                actions["recovered"][0]["respawned"]
            assert router.replicas["r0"].alive
            assert router.replicas["r0"].incarnation > 0
            # every pre-crash uid still resolves (retired ledger for the
            # dead incarnation, live ledger for the survivor)
            assert _await(lambda: all(
                router.resolve(u) in TERMINAL for u in uids), 60)
            # loud sheds, not silence, for crash-severed requests
            states = [router.resolve(u) for u in uids]
            assert all(s in TERMINAL for s in states)
            # the respawn takes new traffic
            uid = router.submit([4, 5], max_new_tokens=2)
            assert _await(lambda: router.resolve(uid) in TERMINAL, 30)
            assert router.counters["readmits"] == 1
        finally:
            set_injector(None)
            router.close()
            fc.close()

    def test_add_remove_guards(self):
        import threading

        from deepspeed_tpu.serving import ReplicaRouter

        r0, r1 = _make_replica_stub("r0"), _make_replica_stub("r1")
        router = ReplicaRouter([r0, r1])       # never started: no threads
        with pytest.raises(ValueError):
            router.add_replica(_make_replica_stub("r0"))   # duplicate name
        # fake a live worker so r0 counts as routable
        gate = threading.Event()
        t = threading.Thread(target=gate.wait, daemon=True)
        t.start()
        r0._thread = t
        try:
            with pytest.raises(RuntimeError):
                router.remove_replica("r0")    # still routable
        finally:
            gate.set()
            t.join(timeout=5)
        removed = router.remove_replica("r0")  # dead now: removable
        assert removed is r0 and "r0" not in router.replicas
        with pytest.raises(RuntimeError):
            router.remove_replica("r1")        # never the last replica
        with pytest.raises(KeyError):
            router.remove_replica("nope")

    def test_readmit_requires_ready_and_name_match(self):
        from deepspeed_tpu.serving import ReplicaRouter

        router = ReplicaRouter([_make_replica_stub("r0")])
        with pytest.raises(ValueError):
            router.readmit("r0", _make_replica_stub("other"))
        with pytest.raises(RuntimeError):
            # replacement never started -> not alive
            router.readmit("r0", _make_replica_stub("r0"))

    def test_retired_ledger_is_bounded(self):
        from deepspeed_tpu.serving import ReplicaRouter

        router = ReplicaRouter([_make_replica_stub("r0"),
                                _make_replica_stub("r1")])
        for _ in range(router._max_retired + 5):
            with router._lock:
                router._retire_locked(_make_replica_stub("r0"))
        assert len(router._retired) == router._max_retired


class _FakeRep:
    """Just enough replica surface for FleetController._autoscale."""

    def __init__(self, name, qdepth=0, by_tier=None, active=0):
        self.name = name
        self.incarnation = 0
        self.routable = True
        self.alive = True
        self.stats = {"health": "ready", "queue_depth": qdepth,
                      "active": active, "retry_after": 0.0, "sheds": 0,
                      "drained": False, "beat": time.monotonic()}
        if by_tier is not None:
            self.stats["queue_depth_by_tier"] = by_tier


class _FakeRouter:
    def __init__(self, reps):
        self.replicas = {r.name: r for r in reps}

    def _snapshot(self):
        return list(self.replicas.values())


@pytest.mark.elastic
@pytest.mark.slo
class TestAutoscalerTierAwareness:
    """Satellite: the autoscaler reads per-tier queue depth — batch-tier
    backlog alone must neither trigger scale-up nor hold off scale-down;
    replicas without the breakdown fall back to total depth."""

    def _controller(self, reps):
        from deepspeed_tpu.config.config import FleetConfig
        from deepspeed_tpu.observability import MetricsRegistry
        from deepspeed_tpu.serving.fleet import FleetController

        ctl = FleetController(
            _FakeRouter(reps), lambda name: None,
            config=FleetConfig(scale_up_polls=1, scale_down_idle_polls=1,
                               scale_up_queue_per_replica=4,
                               min_replicas=1, max_replicas=8),
            registry=MetricsRegistry())
        ctl._calls = []
        ctl.scale_up = lambda: ctl._calls.append("up") or "rX"
        ctl.scale_down = lambda: ctl._calls.append("down") or "r0"
        return ctl

    def test_batch_backlog_alone_scales_down_not_up(self):
        reps = [_FakeRep("r0", qdepth=50, by_tier={"batch": 50}),
                _FakeRep("r1", qdepth=0, by_tier={})]
        ctl = self._controller(reps)
        actions = {"scaled_up": None, "scaled_down": None}
        ctl._autoscale(actions)
        # a deep batch backlog is deferred-by-design work: the pool is
        # IDLE for scaling purposes, so it shrinks instead of growing
        assert ctl._calls == ["down"]

    def test_latency_backlog_scales_up(self):
        reps = [_FakeRep("r0", qdepth=50,
                         by_tier={"latency": 40, "batch": 10}),
                _FakeRep("r1", qdepth=0, by_tier={})]
        ctl = self._controller(reps)
        actions = {"scaled_up": None, "scaled_down": None}
        ctl._autoscale(actions)
        assert ctl._calls == ["up"]

    def test_missing_breakdown_falls_back_to_total(self):
        # pre-tier replicas: unknown load is treated as urgent
        reps = [_FakeRep("r0", qdepth=50), _FakeRep("r1", qdepth=0)]
        ctl = self._controller(reps)
        actions = {"scaled_up": None, "scaled_down": None}
        ctl._autoscale(actions)
        assert ctl._calls == ["up"]


class _StubBatcher:
    """The minimal batcher surface Replica touches without a worker."""

    def __init__(self):
        self.health = "starting"
        self.drained = False
        self.manager = None            # only the retired-ledger key needs it

    def close(self):
        pass


def _make_replica_stub(name):
    """An UNSTARTED replica (no engine build) for guard tests."""
    from deepspeed_tpu.serving.router import Replica

    return Replica(name, _StubBatcher())


# ---------------------------------------------------------------------------
# slow end-to-end drill wrappers
# ---------------------------------------------------------------------------
@pytest.mark.elastic
@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["replica-crash-mid-storm",
                                      "burst-autoscale", "rolling-swap",
                                      "cold-start-bench"])
def test_elastic_scenario(scenario, tmp_path, eight_devices):
    from elastic_drill import run_scenario

    verdict = run_scenario(scenario, workdir=str(tmp_path))
    assert verdict["ok"], verdict
