"""Unified observability layer tests (``deepspeed_tpu/observability``).

Pins the acceptance contracts: the Prometheus text exposition parses under
the text-format grammar (a small grammar validator lives in this file),
``/healthz`` / ``/readyz`` flip with the batcher health states, the
``serving/ttft_ms`` + ``serving/tpot_ms`` histograms populate in a real
``ContinuousBatcher`` run with tracing enabled, the profile trigger's
arm/warmup/rate-limit lifecycle, and the registry→bridge delta semantics.
The end-to-end load/overhead/profile drills live in ``tools/obs_drill.py``;
a slow-marked wrapper runs them at the bottom.
"""

import json
import math
import os
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.observability import (MetricsRegistry, MonitorBridge,
                                         ObservabilityServer, ProfileTrigger,
                                         exponential_bounds, probe_status)

pytestmark = pytest.mark.obs

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


# ---------------------------------------------------------------------------
# a small Prometheus text-format (0.0.4) grammar validator
# ---------------------------------------------------------------------------

_METRIC = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf'^({_METRIC})'                                   # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:\\.|[^"\\])*"'     # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:\\.|[^"\\])*")*\})?'  # more labels
    r' (-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$')               # value
_HELP_RE = re.compile(rf"^# HELP ({_METRIC}) .*$")
_TYPE_RE = re.compile(rf"^# TYPE ({_METRIC}) "
                      r"(counter|gauge|histogram|summary|untyped)$")


def validate_prometheus(text: str) -> dict:
    """Parse/validate exposition text; returns {metric: [(labels, value)]}.
    Raises AssertionError with the offending line on any grammar break, and
    checks the histogram invariants (monotone buckets, +Inf == _count)."""
    samples: dict = {}
    typed: dict = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line) or _TYPE_RE.match(line)
            assert m, f"bad comment line: {line!r}"
            if line.startswith("# TYPE"):
                tm = _TYPE_RE.match(line)
                typed[tm.group(1)] = tm.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"bad sample line: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(4)
        samples.setdefault(name, []).append((labels, value))
    # histogram invariants per histogram family
    for fam, kind in typed.items():
        if kind != "histogram":
            continue
        buckets = samples.get(f"{fam}_bucket", [])
        counts = samples.get(f"{fam}_count", [])
        assert buckets and counts, f"histogram {fam} missing series"
        assert f"{fam}_sum" in samples
        by_series: dict = {}
        for labels, value in buckets:
            le = re.search(r'le="([^"]*)"', labels).group(1)
            rest = re.sub(r'le="[^"]*",?', "", labels).strip("{},")
            by_series.setdefault(rest, []).append((le, float(value)))
        for rest, bs in by_series.items():
            vals = [v for _le, v in bs]
            assert vals == sorted(vals), f"{fam} buckets not monotone"
            les = [le for le, _v in bs]
            assert les[-1] == "+Inf", f"{fam} missing +Inf bucket"
            total = [float(v) for labels, v in counts
                     if labels.strip("{}") == rest]
            assert total and total[0] == vals[-1], \
                f"{fam} +Inf bucket != _count"
    return samples


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_monotonic_and_labeled_series(self):
        r = MetricsRegistry()
        c = r.counter("x/reqs", "requests", labels={"kind": "a"})
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        c2 = r.counter("x/reqs", labels={"kind": "b"})
        assert c2.value == 0.0                # distinct series
        assert r.counter("x/reqs", labels={"kind": "a"}) is c  # get-or-create

    def test_type_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("x/n")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("x/n")

    def test_histogram_percentiles_bounded_by_min_max(self):
        r = MetricsRegistry()
        h = r.histogram("x/lat_ms", bounds=exponential_bounds(1.0, 2.0, 10))
        rng = np.random.default_rng(0)
        xs = rng.lognormal(2.0, 0.8, 2000)
        for x in xs:
            h.observe(float(x))
        assert h.count == 2000
        assert math.isclose(h.sum, float(np.sum(xs)), rel_tol=1e-9)
        p50, p95, p99 = (h.percentile(q) for q in (50, 95, 99))
        assert xs.min() <= p50 <= p95 <= p99 <= xs.max()
        # estimates land near the truth (log-linear interpolation within a
        # factor-2 bucket is at worst ~sqrt(2) off; lognormal is smooth)
        assert abs(p50 - float(np.percentile(xs, 50))) \
            <= 0.5 * float(np.percentile(xs, 50))

    def test_empty_histogram_is_zero(self):
        r = MetricsRegistry()
        h = r.histogram("x/empty")
        assert h.percentile(99) == 0.0 and h.count == 0

    def test_histogram_window_sees_a_fresh_regression(self):
        """Lifetime percentiles bury a new regression under old samples;
        the rolled window must report the recent distribution instead."""
        from deepspeed_tpu.observability import HistogramWindow

        r = MetricsRegistry()
        h = r.histogram("x/lat_ms")
        for _ in range(10_000):               # long healthy history
            h.observe(2.0)
        w = HistogramWindow(h)
        w.roll()
        w.roll()                              # window base = now
        for _ in range(100):                  # sustained 10x regression
            h.observe(20.0)
        assert h.percentile(50) < 4.0         # lifetime: still "healthy"
        assert w.percentile(50) > 10.0        # window: regression visible
        assert w.count == 100
        # a window created mid-history never sees earlier samples
        w2 = HistogramWindow(h)
        assert w2.count == 0 and w2.percentile(99) == 0.0

    def test_render_prometheus_parses_and_sanitizes_names(self):
        r = MetricsRegistry()
        r.counter("serving/shed_total", "s", labels={"reason": "kv"}).inc(2)
        r.gauge("serving/kv_occupancy").set(0.5)
        h = r.histogram("serving/ttft_ms", "ttft")
        for v in (1.0, 3.0, 1000.0, 1e9):     # incl. +Inf overflow bucket
            h.observe(v)
        samples = validate_prometheus(r.render_prometheus())
        assert samples["serving_shed_total_total"] == [('{reason="kv"}', "2")]
        assert "serving_ttft_ms_bucket" in samples
        snap = r.snapshot()
        json.dumps(snap)                      # JSON-serializable
        assert snap["serving/ttft_ms"]["series"][0]["count"] == 4


# ---------------------------------------------------------------------------
# exposition + probes
# ---------------------------------------------------------------------------

def _get(url):
    try:
        resp = urllib.request.urlopen(url, timeout=5)
        return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestExposition:
    def test_probe_mapping(self):
        assert probe_status("ready") == {"health": "ready", "live": True,
                                         "ready": True}
        assert probe_status("degraded")["ready"] is True
        assert probe_status("starting")["ready"] is False
        d = probe_status("draining")
        assert d["live"] and not d["ready"]   # finish in-flight, route away
        assert probe_status(None)["ready"] is True

    def test_http_endpoints_flip_with_health(self):
        r = MetricsRegistry()
        r.gauge("x/g").set(1.0)
        state = ["starting"]
        with ObservabilityServer(r, health_fn=lambda: state[0]) as srv:
            assert _get(srv.url + "/healthz")[0] == 200
            assert _get(srv.url + "/readyz")[0] == 503
            state[0] = "ready"
            assert _get(srv.url + "/readyz")[0] == 200
            state[0] = "degraded"
            assert _get(srv.url + "/readyz")[0] == 200
            state[0] = "draining"
            assert _get(srv.url + "/readyz")[0] == 503
            assert _get(srv.url + "/healthz")[0] == 200
            code, body = _get(srv.url + "/metrics")
            assert code == 200
            validate_prometheus(body)
            code, body = _get(srv.url + "/metrics.json")
            assert code == 200 and json.loads(body)["x/g"]
            assert _get(srv.url + "/nope")[0] == 404

    def test_route_error_before_commit_maps_to_500(self):
        srv = ObservabilityServer(MetricsRegistry())
        srv.mount("GET", "/boom", lambda h: 1 / 0)
        with srv:
            code, body = _get(srv.url + "/boom")
            assert code == 500
            assert json.loads(body)["error"]["type"] == "internal"

    def test_route_error_after_commit_drops_connection_not_inject_500(self):
        """A mounted route that dies AFTER committing a chunked response
        must not get a second (500) response written into the stream body
        — the connection is dropped, so the client sees a truncated chunk
        stream rather than a desynced/corrupted one."""
        import http.client

        srv = ObservabilityServer(MetricsRegistry())

        def boom(handler):
            handler.begin_chunked(200, "text/event-stream")
            handler.write_chunk(b"data: one\n\n")
            raise RuntimeError("mid-stream failure")

        srv.mount("GET", "/boom", boom)
        with srv:
            conn = http.client.HTTPConnection(srv.host, srv.port,
                                              timeout=10)
            conn.request("GET", "/boom")
            resp = conn.getresponse()
            assert resp.status == 200
            try:
                raw = resp.read()
            except (http.client.IncompleteRead, ConnectionError) as e:
                raw = getattr(e, "partial", b"")
            conn.close()
            assert b"data: one" in raw             # the committed bytes
            assert b"500" not in raw               # no raw status line
            assert b"internal" not in raw          # no injected error body


# ---------------------------------------------------------------------------
# profile trigger lifecycle (stubbed capture fns; the real-jax.profiler
# path is exercised by tools/obs_drill.py profile-capture)
# ---------------------------------------------------------------------------

class TestProfileTrigger:
    def _trigger(self, tmp_path, **kw):
        events = []
        t = ProfileTrigger(
            str(tmp_path), start_fn=lambda d: events.append(("start", d)),
            stop_fn=lambda: events.append(("stop",)), **kw)
        return t, events

    def test_capture_spans_n_steps_and_is_rate_limited(self, tmp_path):
        now = [0.0]
        t, events = self._trigger(tmp_path, capture_steps=3, warmup_steps=0,
                                  rate_limit_s=100.0, clock=lambda: now[0])
        t.arm()
        assert t.check(1) is None and t.capturing
        t.check(2)
        t.check(3)
        cap = t.check(4)                      # step >= 1+3 → stop
        assert cap and cap.startswith(str(tmp_path))
        assert [e[0] for e in events] == ["start", "stop"]
        assert t.counters["captures"] == 1
        t.arm()                               # inside the rate-limit window
        t.check(5)
        assert not t.capturing
        assert t.counters["suppressed_rate_limit"] == 1
        now[0] = 200.0                        # window passed
        t.arm()
        t.check(6)
        assert t.capturing

    def test_warmup_holds_the_arm_instead_of_dropping_it(self, tmp_path):
        t, events = self._trigger(tmp_path, capture_steps=1, warmup_steps=3,
                                  rate_limit_s=0.0)
        t.arm()
        for s in (1, 2, 3):                   # compile territory: held
            t.check(s)
            assert not t.capturing
        t.check(4)
        assert t.capturing                    # fired on the first safe step

    def test_trigger_file_is_consumed(self, tmp_path):
        t, events = self._trigger(tmp_path, capture_steps=1, warmup_steps=0,
                                  rate_limit_s=0.0)
        open(t.trigger_file, "w").close()
        t.check(1)
        assert t.capturing
        assert not os.path.exists(t.trigger_file)

    def test_start_failure_is_contained(self, tmp_path):
        t = ProfileTrigger(str(tmp_path), warmup_steps=0,
                           start_fn=lambda d: 1 / 0,
                           stop_fn=lambda: None)
        t.arm()
        assert t.check(1) is None             # no raise into the step loop
        assert not t.capturing
        assert t.counters["capture_errors"] == 1


# ---------------------------------------------------------------------------
# bridge delta semantics
# ---------------------------------------------------------------------------

class _SinkMonitor:
    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


def test_bridge_flushes_only_changed_series():
    r = MetricsRegistry()
    sink = _SinkMonitor()
    bridge = MonitorBridge(sink, r, prefix="s/")
    c = r.counter("s/n")
    r.counter("other/ignored").inc()          # outside the prefix
    h = r.histogram("s/lat_ms")
    c.inc(2)
    h.observe(4.0)
    n = bridge.flush(step=1)
    tags = {t for t, _v, _s in sink.events}
    assert ("s/n", 2.0, 1) in sink.events
    assert {"s/lat_ms_count", "s/lat_ms_p50", "s/lat_ms_p95",
            "s/lat_ms_p99"} <= tags
    assert not any(t.startswith("other/") for t in tags)
    assert bridge.flush(step=2) == 0          # nothing changed → no events
    c.inc()
    assert bridge.flush(step=3) == 1          # only the changed counter
    assert n >= 5


def test_comms_logger_exports_per_op_totals():
    from deepspeed_tpu.comm.logger import CommsLogger

    r = MetricsRegistry()
    cl = CommsLogger(enabled=True)
    cl.append("all_reduce", 1024, 0.001)
    cl.append("all_reduce", 1024)
    cl.append("all_gather_into_tensor", 512, 0.002)
    cl.export_to_registry(r)
    cl.export_to_registry(r)                  # idempotent: deltas, not totals
    assert r.counter("comm/all_reduce_calls").value == 2
    assert r.counter("comm/all_reduce_bytes").value == 2048
    assert r.counter("comm/all_gather_into_tensor_bytes").value == 512
    assert cl.total_latency_s() == pytest.approx(0.003)
    cl.append("all_reduce", 8)
    cl.export_to_registry(r)
    assert r.counter("comm/all_reduce_calls").value == 3


# ---------------------------------------------------------------------------
# serving integration: spans → SLO histograms → /metrics (acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_engine():
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    return InferenceEngineV2(TransformerLM(get_preset("tiny")),
                             max_sequences=8, max_seq_len=128, block_size=16)


def test_batcher_populates_slo_histograms_and_probes(tiny_engine):
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.serving import ContinuousBatcher

    r = MetricsRegistry()
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=4,
                        trace_requests=True)
    b = ContinuousBatcher(tiny_engine, cfg, registry=r)
    uids = [b.submit(np.arange(20) % 250) for _ in range(3)]
    b.pump(max_steps=50)
    assert all(b.manager.resolve(u) == "completed" for u in uids)
    # acceptance: ttft + tpot + queue-wait histograms populate
    ttft = r.get("serving/ttft_ms").series[()]
    tpot = r.get("serving/tpot_ms").series[()]
    qw = r.get("serving/queue_wait_ms").series[()]
    assert ttft.count == 3                    # one first token per request
    assert tpot.count == 3 * (4 - 1)          # 3 decode gaps per request
    assert qw.count == 3
    assert r.counter("serving/requests",
                     labels={"terminal": "completed"}).value == 3
    # per-request span: the trace survives in the terminal ledger
    span = b.request_trace(uids[0])
    assert span["ttft_ms"] is not None and span["tpot_ms"] is not None
    assert span["generated_tokens"] == 4
    assert span["queue_wait_ms"] >= 0.0
    # slo section of the report mirrors the same histograms
    rep = b.serving_report()
    assert rep["slo_ms"]["ttft"]["samples"] == 3
    assert rep["latency_ms"]["samples"] == b.counters["engine_steps"]
    # /metrics + probes over real HTTP, mapped from batcher health
    with b.serve_metrics_http() as srv:
        # a repeat call — even asking for a different bind — returns the
        # running server (with a warning) instead of binding a second one
        assert b.serve_metrics_http(port=srv.port + 1) is srv
        code, body = _get(srv.url + "/metrics")
        assert code == 200
        samples = validate_prometheus(body)
        assert "serving_ttft_ms_bucket" in samples
        assert _get(srv.url + "/readyz")[0] == 200     # READY after steps
        b.begin_drain("test")
        assert _get(srv.url + "/readyz")[0] == 503     # DRAINING → not ready
        assert _get(srv.url + "/healthz")[0] == 200

    b.drain(timeout_s=5.0)


def test_tracing_disabled_gates_spans_only_not_lifecycle_counters(
        tiny_engine):
    """trace_requests=False must disable ONLY the span histograms — the
    terminal/shed/reject counters are one bump per transition and have to
    keep recording or an overload incident goes invisible on /metrics."""
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.serving import ContinuousBatcher

    r = MetricsRegistry()
    cfg = ServingConfig(prefill_chunk=32, default_max_new_tokens=2,
                        trace_requests=False)
    b = ContinuousBatcher(tiny_engine, cfg, registry=r)
    uid = b.submit(np.arange(10) % 250)
    b.pump(max_steps=20)
    assert b.manager.resolve(uid) == "completed"
    for span_hist in ("serving/ttft_ms", "serving/queue_wait_ms",
                      "serving/e2e_ms"):
        assert r.get(span_hist).series[()].count == 0, span_hist
    assert r.get("serving/step_ms").series[()].count > 0  # step timing stays
    assert r.counter("serving/requests",
                     labels={"terminal": "completed"}).value == 1


# ---------------------------------------------------------------------------
# drill wrappers (slow; the CLI is the invariant authority)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["metrics-under-load",
                                      "profile-capture",
                                      "overhead-budget"])
def test_obs_drill_scenario(scenario, tmp_path):
    import sys

    sys.path.insert(0, _TOOLS)
    from obs_drill import run_scenario

    verdict = run_scenario(scenario, workdir=str(tmp_path))
    assert verdict["ok"], verdict
