"""1-bit optimizer tests — the analog of ``tests/unit/v1/onebit/test_onebit.py``:
warmup must match dense Adam, the compressed phase must keep converging (error
feedback working), and the compiled step must carry packed-bit (uint8) payloads
on the wire."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, get_preset
from deepspeed_tpu.runtime.onebit import (_sign_compress, _sign_decompress,
                                          compressed_allreduce)


def make_config(opt, mesh, stage=0, **opt_params):
    params = {"lr": 1e-3}
    params.update(opt_params)
    return {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": opt, "params": params},
        "zero_optimization": {"stage": stage},
        "mesh": mesh,
        "steps_per_print": 100,
    }


def run(eng, steps, seed=0):
    rng = np.random.default_rng(seed)
    b = {"input_ids": rng.integers(
        0, 256, (eng.train_micro_batch_size_per_gpu()
                 * eng.topology.dp_world_size, 32))}
    losses = []
    for _ in range(steps):
        loss = eng.forward(b)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


def test_sign_compress_roundtrip():
    x = np.asarray(jax.random.normal(jax.random.key(0), (4, 64)))
    packed, scale = _sign_compress(jnp.asarray(x))
    assert packed.dtype == jnp.uint8 and packed.shape == (4, 8)
    out = np.asarray(_sign_decompress(packed, scale, 64))
    np.testing.assert_array_equal(np.sign(out), np.sign(x))
    # every element decodes to ±scale, scale ≈ mean |x| per row
    np.testing.assert_allclose(np.abs(out),
                               np.broadcast_to(np.asarray(scale), out.shape),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(scale)[:, 0],
                               np.mean(np.abs(x), 1), rtol=1e-5)


def test_compressed_allreduce_error_feedback(eight_devices):
    """Error feedback: the compression residual is carried, so the MEAN of the
    allreduced values over time tracks the true mean (1-bit Adam's convergence
    argument). One step: output must correlate with the true mean sign-wise."""
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(eight_devices), ("dp",))
    n = 1024
    xs = jax.random.normal(jax.random.key(1), (8, n))

    def body(x, ew, es):
        out, ew2, es2 = compressed_allreduce(x[0], ew[0], es[0], "dp")
        return out[None], ew2[None], es2[None]

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=(P("dp"), P("dp"), P("dp")),
                      out_specs=(P("dp"), P("dp"), P("dp")), check_vma=False)
    ew = jnp.zeros((8, n))
    es = jnp.zeros((8, n // 8))
    out, ew2, es2 = jax.jit(f)(xs, ew, es)
    true_mean = np.asarray(xs).mean(0)
    got = np.asarray(out[0])
    # every device gets the same result; signs match the true mean mostly
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(out[7]))
    # one-shot sign agreement for 8 iid normals is ~0.8 (the sign-of-mean vs
    # mean-of-signs gap); error feedback recovers the residual over steps,
    # which the convergence tests assert end-to-end
    agree = (np.sign(got) == np.sign(true_mean)).mean()
    assert agree > 0.7, f"sign agreement {agree}"
    # residuals carried, not dropped
    assert float(jnp.abs(ew2).sum()) > 0 and float(jnp.abs(es2).sum()) > 0


def test_onebit_adam_warmup_matches_dense(eight_devices):
    """During warmup (step <= freeze_step) 1-bit Adam IS dense Adam."""
    dense = ds.initialize(model=TransformerLM(get_preset("tiny")),
                          config=make_config("adamw", {"dp": 8}))[0]
    ob = ds.initialize(model=TransformerLM(get_preset("tiny")),
                       config=make_config("OneBitAdam", {"dp": 8},
                                          freeze_step=100))[0]
    ref = run(dense, 4)
    got = run(ob, 4)
    np.testing.assert_allclose(got, ref, rtol=1e-3)


@pytest.mark.parametrize("opt", ["OneBitAdam", "ZeroOneAdam", "OneBitLamb"])
def test_onebit_compressed_phase_converges(opt, eight_devices):
    eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config(opt, {"dp": 8}, freeze_step=2,
                                           var_freeze_step=2))[0]
    losses = run(eng, 10)
    # compressed phase (steps 3..10) keeps optimizing
    assert losses[-1] < losses[3] < losses[0]


def test_onebit_with_tensor_parallel(eight_devices):
    """dp x tp mesh: error buffers are sized from the LOCAL (tp-sharded) leaf
    and carry an explicit [W, tp, n_local] layout, so the sharding metadata is
    truthful and compression is not diluted by cross-shard zero padding."""
    eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config("OneBitAdam", {"dp": 4, "tp": 2},
                                           freeze_step=2))[0]
    losses = run(eng, 8)
    assert losses[-1] < losses[3] < losses[0]
    n_tp_sharded = 0
    for path, ew in jax.tree_util.tree_flatten_with_path(
            eng.opt_state["e_w"])[0]:
        spec = ew.sharding.spec
        if ew.shape[1] == 2:  # tp-sharded leaf: middle dim = tp size
            assert spec[1] == "tp", f"{path}: tp dim not sharded over tp"
            n_tp_sharded += 1
    assert n_tp_sharded > 0, "no tp-sharded error buffers found"


def test_onebit_fused_matches_imperative(eight_devices):
    a = ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config("OneBitAdam", {"dp": 8},
                                         freeze_step=2))[0]
    b_eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                          config=make_config("OneBitAdam", {"dp": 8},
                                             freeze_step=2))[0]
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, (16, 32))}
    for _ in range(5):
        a.fused_train_step(batch)
        loss = b_eng.forward(batch)
        b_eng.backward(loss)
        b_eng.step()
    for x, y in zip(jax.tree_util.tree_leaves(a.params),
                    jax.tree_util.tree_leaves(b_eng.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-4, atol=1e-6)


def test_onebit_bits_on_the_wire(eight_devices):
    """The compiled apply must move uint8 (packed sign) payloads through the
    all-to-all — 1 bit per element, not a dense fp32 reduce."""
    eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config("OneBitAdam", {"dp": 8},
                                           freeze_step=1))[0]
    denom = jnp.float32(1.0)
    with jax.sharding.set_mesh(eng.mesh):
        hlo = eng._onebit_apply.lower(
            eng.params, eng.opt_state, jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, jnp.float32), eng._grad_shapes()
            ) if hasattr(eng, "_grad_shapes") else _zero_grads(eng),
            denom).compile().as_text()
    a2a = [l for l in hlo.splitlines() if "all-to-all" in l]
    assert any("u8" in l for l in a2a), "no packed-bit all-to-all in HLO"


def _zero_grads(eng):
    W = eng.topology.dp_world_size
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros((W,) + np.shape(p), jnp.float32), eng.params)


def test_onebit_rejects_invalid_configs(eight_devices):
    with pytest.raises(ValueError, match="stage"):
        ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config("OneBitAdam", {"fsdp": 8}, stage=2))
    with pytest.raises(ValueError, match="single data-parallel"):
        ds.initialize(model=TransformerLM(get_preset("tiny")),
                      config=make_config("OneBitAdam", {"dp": 2, "fsdp": 4}))
    from deepspeed_tpu.runtime.optimizers import build_optimizer
    with pytest.raises(ValueError, match="1-bit"):
        build_optimizer("OneBitAdam", {"lr": 1e-3})


def test_zeroone_adam_schedules(eight_devices):
    """0/1 Adam policy (zoadam.py): exponential variance-update intervals in
    phase 1, local-step comm skipping with interval doubling (clipped) in
    phase 2 — and training keeps converging across both phase boundaries."""
    eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=make_config("ZeroOneAdam", {"dp": 8},
                                           var_freeze_step=4,
                                           var_update_scaler=2,
                                           local_step_scaler=3,
                                           local_step_clipper=4))[0]
    losses = run(eng, 12)
    st = eng.opt_state
    assert int(st["step"]) == 12
    # phase 1 (steps 1-4): interval 1 doubles after var_update_scaler=2
    # variance updates -> 2; then one more var step at step 4
    assert int(st["var_interval"]) == 2 and int(st["var_counter"]) == 1
    # phase 2 (steps 5-12): 8 frozen steps, interval doubles every 3,
    # clipped at 4: 1 -> 2 (step 7) -> 4 (step 10)
    assert int(st["local_interval"]) == 4 and int(st["local_counter"]) == 2
    # the momentum accumulator exists and training is healthy end-to-end
    assert "u" in st and np.isfinite(losses).all()
    assert losses[-1] < losses[0]
