"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's multi-process-on-one-host distributed test harness
(``tests/unit/common.py:139``): instead of forking processes with NCCL over localhost,
JAX gives us N virtual devices in-process via ``--xla_force_host_platform_device_count``,
and every mesh/sharding/collective path exercises the same SPMD partitioner used on a
real pod. Set ``DSTPU_TEST_TPU=1`` to run against real TPU hardware instead.
"""

import os

import pytest

if os.environ.get("DSTPU_TEST_TPU") != "1":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # sitecustomize may have imported jax already with the TPU plugin registered;
    # flip to CPU before any backend is initialized.
    jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    return jax.devices()[:8]
