#!/usr/bin/env python
"""Offload-pipeline drill CLI: fail the swap data path mid-pipeline and exit
nonzero if the clean-abort invariants break.

The CI-facing face of the overlapped offload data path (``offload/swap.py`` +
the depth-k ``HostOffloadOptimizer`` pipeline): each scenario injects a
deterministic ``io_error`` at a swap site while reads, Adam, and writebacks
are in flight, and asserts what the pipeline promises on failure —

* the error surfaces as ONE clean exception (no hang, no partial success),
* the pinned-buffer pool is fully returned (zero outstanding loans),
* the native AIO queue is drained (no pending ops under a dead step),
* no moment file is torn: every ``.swp`` still reads back at full size with
  finite contents,
* ``close()`` after the abort is safe and idempotent.

    python tools/offload_drill.py --list
    python tools/offload_drill.py --scenario io-error-read
    python tools/offload_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
The slow pytest wrappers live under the ``chaos`` marker.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_optimizer(workdir, leaves=6, n=1 << 14, prefetch_depth=2):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.offload import HostOffloadOptimizer

    rng = np.random.default_rng(0)
    params = {f"l{i}": {"w": jnp.asarray(rng.normal(size=(n // 64, 64)),
                                         jnp.float32)}
              for i in range(leaves)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=p.shape) * 0.01, jnp.float32),
        params)
    opt = HostOffloadOptimizer(params, lr=1e-2, nvme_path=workdir,
                               aio_threads=2, aio_chunk_mb=1,
                               prefetch_depth=prefetch_depth)
    return opt, params, grads


def _fresh_injector():
    from deepspeed_tpu.resilience import set_injector

    set_injector(None)


def _moment_files_intact(opt) -> tuple:
    """Every moment file still reads back full-size and finite (an aborted
    step may leave the VALUES one step behind — consistency is re-established
    from the checkpoint — but no file may be torn/truncated)."""
    import numpy as np

    bad = []
    for skey in opt.master:
        for kind in (".m", ".v"):
            try:
                arr = opt.swapper.swap_in(skey + kind)
            except Exception as e:
                bad.append({"file": skey + kind, "error": repr(e)})
                continue
            if arr.shape != opt.master[skey].shape:
                bad.append({"file": skey + kind, "short_read": list(arr.shape)})
            elif not np.isfinite(arr).all():
                bad.append({"file": skey + kind, "nonfinite": True})
    return (not bad), bad


def _run_io_error(workdir, site: str):
    from deepspeed_tpu.resilience import FaultInjector, set_injector
    from deepspeed_tpu.resilience.faults import InjectedIOError

    opt, params, grads = _make_optimizer(workdir)
    p, skipped = opt.step(grads, params, 0)          # one clean step first
    assert not skipped
    set_injector(FaultInjector(
        [{"kind": "io_error", "site": site, "times": 1}]))
    caught = None
    t0 = time.perf_counter()
    try:
        opt.step(grads, p, 1)                        # fault fires mid-pipeline
    except InjectedIOError as e:
        caught = repr(e)
    finally:
        _fresh_injector()
    abort_s = time.perf_counter() - t0
    pool = opt.swapper.pool.report()
    pending = opt.swapper.pending
    files_ok, bad_files = _moment_files_intact(opt)
    # recovery: with the fault cleared the SAME optimizer object can step
    recovered = False
    try:
        _, skipped = opt.step(grads, p, 2)
        recovered = not skipped
    except Exception as e:
        bad_files.append({"recovery_error": repr(e)})
    opt.close()
    opt.close()                                      # idempotent
    details = {"site": site, "caught": caught, "abort_s": round(abort_s, 3),
               "pool": pool, "native_pending": pending,
               "moment_files_intact": files_ok, "bad_files": bad_files,
               "recovered_next_step": recovered}
    ok = (caught is not None and pool["outstanding"] == 0 and pending == 0
          and files_ok and recovered)
    return ok, details


def scenario_io_error_read(workdir):
    """io_error at swap_read (a prefetch fails mid-pipeline) → clean abort."""
    return _run_io_error(workdir, "swap_read")


def scenario_io_error_write(workdir):
    """io_error at swap_write (a writeback fails mid-pipeline) → clean abort."""
    return _run_io_error(workdir, "swap_write")


def scenario_pool_steady_state(workdir):
    """Five pipelined steps after warmup: the pinned pool must not allocate
    (steady-state reuse) and every loan must return."""
    opt, params, grads = _make_optimizer(workdir)
    p = params
    for s in range(2):                               # warmup
        p, _ = opt.step(grads, p, s)
    baseline = opt.swapper.pool.allocations
    for s in range(2, 7):
        p, _ = opt.step(grads, p, s)
    pool = opt.swapper.pool.report()
    stall = opt._stall_fraction
    opt.close()
    details = {"baseline_allocations": baseline, "pool": pool,
               "pipeline_stall_fraction": round(stall, 4)}
    ok = (pool["allocations"] == baseline and pool["outstanding"] == 0
          and 0.0 <= stall <= 1.0)
    return ok, details


SCENARIOS = {
    "io-error-read": scenario_io_error_read,
    "io-error-write": scenario_io_error_write,
    "pool-steady-state": scenario_pool_steady_state,
}


def run_scenario(name: str, workdir=None) -> dict:
    fn = SCENARIOS[name]
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix=f"offload_drill_{name}_")
    t0 = time.perf_counter()
    try:
        ok, details = fn(workdir)
    except Exception as e:  # a drill crash is a failed drill
        ok, details = False, {"exception": repr(e)}
    finally:
        _fresh_injector()
    if own and ok:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return {"scenario": name, "ok": ok,
            "wall_s": round(time.perf_counter() - t0, 2),
            "workdir": workdir, "details": details}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name, workdir=args.workdir)
        print(json.dumps(verdict, indent=2, default=str))
        if not verdict["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
