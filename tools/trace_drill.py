#!/usr/bin/env python
"""Tracing drill CLI: prove the causal event bus, the Chrome-trace
export, and the crash flight recorder against real loads — exit nonzero
if any invariant fails (the tracing face of ``tools/obs_drill.py``).

Scenarios:

* **storm-trace** — a real-socket HTTP storm against a frontend + replica
  with the prefix-cache KV tier enabled and a pool small enough to force
  demote→promote cycles. Invariants: ``GET /v1/trace`` returns JSON that
  passes the trace-event grammar (every B matched by an E on its tid,
  async ids balanced); every submitted request resolves terminal; at
  least one request's causal chain spans the frontend → serving →
  batcher → engine subsystems; and the warmed shared-prefix request's
  chain reaches the KV tier (a ``promote_attach`` for its uid — the
  "frontend admit → batcher step → engine put → KV-tier op" acceptance
  chain).
* **abort-dump** — an injected NaN burst exhausts the StepGuard budget on
  a tiny training engine with tracing configured. Invariants: EXACTLY one
  flight-recorder dump lands in the dump dir; it embeds a grammar-valid
  trace containing the aborting step's ``resilience`` events
  (``bad_step`` leading up to ``stepguard_abort``).
* **disabled-no-events** — tracing NOT configured: the same serving load
  records zero events, ``trace_export`` is empty, and abort paths write
  no dumps (the "~0 when disabled" contract, behaviorally).

    python tools/trace_drill.py --list
    python tools/trace_drill.py --scenario storm-trace
    python tools/trace_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
Slow pytest wrappers live in ``tests/unit/test_tracing.py``.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _reset_tracing():
    from deepspeed_tpu.observability import configure_tracing, get_bus

    configure_tracing(enabled=False)
    get_bus().clear()


def _make_serving(trace: bool, workdir: str):
    """Frontend + replica over a tier-enabled engine with a small pool."""
    from deepspeed_tpu.config.config import FrontendConfig, ServingConfig
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.observability import MetricsRegistry, configure_tracing
    from deepspeed_tpu.serving import ContinuousBatcher
    from deepspeed_tpu.serving.frontend import ServingFrontend
    from deepspeed_tpu.serving.router import Replica

    if trace:
        configure_tracing(enabled=True, ring_size=8192, sample=1,
                          dump_dir=os.path.join(workdir, "flight"),
                          retain_terminal=64)
    eng = InferenceEngineV2(
        TransformerLM(get_preset("tiny")), max_sequences=4, max_seq_len=128,
        block_size=16, num_blocks=24,
        prefix_cache={"enabled": True,
                      "tiers": {"enabled": True, "host_mb": 0.25}})
    b = ContinuousBatcher(eng, ServingConfig(
        prefill_chunk=64, default_max_new_tokens=4), registry=MetricsRegistry())
    rep = Replica("solo", b).start()
    fe = ServingFrontend(rep, FrontendConfig(), registry=b.metrics.registry)
    fe.start()
    return eng, b, rep, fe


def _post(host, port, prompt, timeout=120):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/generate",
                     body=json.dumps({"prompt": [int(t) for t in prompt],
                                      "max_new_tokens": 4}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def _get_json(host, port, path, timeout=60):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# scenarios: each returns (ok: bool, details: dict)
# ---------------------------------------------------------------------------

def scenario_storm_trace(workdir):
    """HTTP storm with tracing on: grammar-valid /v1/trace, every request
    terminal, >=1 causal chain spanning frontend/serving/batcher/engine,
    and the warm shared-prefix request's chain reaching the KV tier."""
    from deepspeed_tpu.observability import get_bus, validate_trace

    _reset_tracing()
    eng, b, rep, fe = _make_serving(trace=True, workdir=workdir)
    shared = list(range(1, 49))                   # 3 full blocks + tail
    outcomes = []
    lock = threading.Lock()

    def client(prompt):
        st, body = _post(fe.server.host, fe.server.port, prompt)
        with lock:
            outcomes.append((st, body.get("state"), body.get("id")))

    try:
        # phase 1: seed the shared prefix (published on completion)
        client(shared)
        # phase 2: distinct-prefix churn forces the seed's blocks out of
        # HBM into the host tier (4 concurrent clients x 2 rounds)
        for round_ in range(2):
            threads = [threading.Thread(
                target=client,
                args=([1000 + 100 * round_ + 10 * i + j
                       for j in range(48)],))
                for i in range(4)]
            [t.start() for t in threads]
            [t.join(timeout=120) for t in threads]
        demotions = eng._tier_store.counters["host_demotions"]
        # phase 3: the warm request — its prefix now lives in the tier,
        # so the match promotes (the KV-tier link of the causal chain)
        st, warm = _post(fe.server.host, fe.server.port, shared[:-1] + [7])
        warm_uid = warm.get("id")
        # export over the wire: the /v1/trace mount is the product surface
        code, doc = _get_json(fe.server.host, fe.server.port, "/v1/trace")
        errors = validate_trace(doc)
    finally:
        fe.close()
        rep.close()
        eng.close()

    bus = get_bus()
    events = bus.events()
    # per-trace subsystem chains: request-track args.subsys + engine spans
    # joined by uid + kv_tier promote_attach joined by uid
    by_trace = {}
    uid_of = {}
    for e in events:
        if e.cat == "request" and e.args and "subsys" in e.args:
            s = by_trace.setdefault(e.trace_id, set())
            s.add(e.args["subsys"])
            if "uid" in e.args:
                uid_of[e.trace_id] = e.args["uid"]
    eng_uids = set()
    for e in events:
        if e.cat == "engine" and e.ph == "B" and e.args:
            eng_uids.update(e.args.get("uids", ()))
    promo_uids = {e.args["uid"] for e in events
                  if e.cat == "kv_tier" and e.name == "promote_attach"
                  and e.args}
    chains = {}
    for tid, subsys in by_trace.items():
        uid = uid_of.get(tid)
        if uid in eng_uids:
            subsys.add("engine")
        if uid in promo_uids:
            subsys.add("kv_tier")
        chains[tid] = sorted(subsys)
    core = {"frontend", "serving", "batcher", "engine"}
    full_chains = [c for c in chains.values() if core.issubset(set(c))]
    warm_chain = next((set(c) for t, c in chains.items()
                       if uid_of.get(t) == warm_uid), set())
    _reset_tracing()

    details = {
        "requests": len(outcomes) + 1,
        "outcomes": sorted({(st, state) for st, state, _ in outcomes}),
        "warm": {"status": st, "state": warm.get("state"),
                 "chain": sorted(warm_chain)},
        "trace_http_code": code,
        "trace_events": len(doc.get("traceEvents", ())),
        "grammar_errors": errors[:5],
        "host_demotions_after_churn": demotions,
        "chains_with_core4": len(full_chains),
        "example_chain": full_chains[0] if full_chains else None,
        "categories": sorted({e.cat for e in events}),
    }
    ok = (code == 200 and not errors
          and doc.get("traceEvents")
          and all(st == 200 and state == "completed"
                  for st, state, _ in outcomes)
          and warm.get("state") == "completed"
          and demotions > 0
          and len(full_chains) >= 1
          and core.issubset(warm_chain)
          and "kv_tier" in warm_chain)
    return ok, details


def scenario_abort_dump(workdir):
    """Injected NaN burst exhausts the StepGuard budget: exactly ONE
    flight dump, embedding a grammar-valid trace that carries the
    aborting step's resilience events."""
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.observability import validate_trace
    from deepspeed_tpu.resilience import set_injector
    from deepspeed_tpu.resilience.guard import TooManyBadSteps

    _reset_tracing()
    set_injector(None)
    dump_dir = os.path.join(workdir, "flight_abort")
    eng, *_ = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "steps_per_print": 100,
                "observability": {"tracing": {"enabled": True,
                                              "ring_size": 2048,
                                              "dump_dir": dump_dir}},
                "resilience": {"enabled": True,
                               "max_consecutive_bad_steps": 3,
                               "faults": [{"kind": "nan_grads", "step": 2,
                                           "times": 10}]}})
    rng = np.random.default_rng(0)
    # batch sized for the ambient mesh (tier-1 runs with 8 forced host
    # devices; standalone the world is 1)
    B = eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size
    aborted = False
    abort_step = None
    try:
        for _ in range(20):
            loss = eng.forward({"input_ids": rng.integers(0, 256, (B, 16))})
            eng.backward(loss)
            eng.step()
    except TooManyBadSteps:
        aborted = True
        abort_step = int(eng.global_steps)
    finally:
        set_injector(None)
        eng.shutdown()

    dumps = sorted(f for f in (os.listdir(dump_dir)
                               if os.path.isdir(dump_dir) else [])
                   if f.startswith("flight_") and f.endswith(".json"))
    dump_doc, res_events, grammar_errors = None, [], ["no dump"]
    if dumps:
        with open(os.path.join(dump_dir, dumps[0])) as f:
            dump_doc = json.load(f)
        grammar_errors = validate_trace(dump_doc.get("trace", {}))
        res_events = [e for e in dump_doc["trace"]["traceEvents"]
                      if e.get("cat") == "resilience"]
    _reset_tracing()
    bad = [e for e in res_events if e.get("name") == "bad_step"]
    abort_evs = [e for e in res_events
                 if e.get("name") == "stepguard_abort"]
    details = {
        "aborted": aborted, "abort_step": abort_step,
        "dumps": dumps, "n_dumps": len(dumps),
        "reason": dump_doc.get("reason") if dump_doc else None,
        "grammar_errors": grammar_errors[:5],
        "bad_step_events": len(bad),
        "abort_events": [e.get("args") for e in abort_evs],
    }
    ok = (aborted and len(dumps) == 1
          and dump_doc is not None
          and dump_doc.get("reason") == "stepguard_abort"
          and not grammar_errors
          and len(bad) >= 3                      # the burnt budget
          and len(abort_evs) == 1
          and abort_evs[0].get("args", {}).get("step") == abort_step)
    return ok, details


def scenario_disabled_no_events(workdir):
    """Tracing NOT configured: the same serving load records nothing,
    the export is empty, and no flight dump is ever written."""
    from deepspeed_tpu.observability import (flight_dump, get_bus,
                                             get_flight_recorder,
                                             trace_export)

    _reset_tracing()
    eng, b, rep, fe = _make_serving(trace=False, workdir=workdir)
    try:
        st, body = _post(fe.server.host, fe.server.port, list(range(1, 33)))
        code, doc = _get_json(fe.server.host, fe.server.port, "/v1/trace")
    finally:
        fe.close()
        rep.close()
        eng.close()
    dump = flight_dump("should_not_write")
    details = {
        "request": (st, body.get("state")),
        "bus_events": get_bus().total_events(),
        "trace_http_code": code,
        "exported_events": len(doc.get("traceEvents", ())),
        "recorder": get_flight_recorder() is not None,
        "dump_path": dump,
        "enabled_flag": doc.get("otherData", {}).get("enabled"),
    }
    ok = (st == 200 and body.get("state") == "completed"
          and get_bus().total_events() == 0
          and code == 200 and details["exported_events"] == 0
          and details["enabled_flag"] is False
          and dump is None and get_flight_recorder() is None
          and not trace_export()["traceEvents"])
    return ok, details


SCENARIOS = {
    "storm-trace": scenario_storm_trace,
    "abort-dump": scenario_abort_dump,
    "disabled-no-events": scenario_disabled_no_events,
}


def run_scenario(name: str, workdir=None) -> dict:
    """Run one drill; returns the verdict record (also usable from tests)."""
    if name not in SCENARIOS:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {sorted(SCENARIOS)})")
    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix=f"trace_{name.replace('-', '_')}_")
    t0 = time.time()
    try:
        ok, details = SCENARIOS[name](workdir)
    finally:
        _reset_tracing()
    return {"scenario": name, "ok": ok,
            "seconds": round(time.time() - t0, 2),
            "workdir": workdir, "details": details}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name, workdir=args.workdir)
        print(json.dumps(verdict, indent=2, default=str))
        if not verdict["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
