#!/usr/bin/env python
"""Chaos drill CLI: run a short training loop under a named fault scenario
and exit nonzero if the recovery invariants fail.

The CI-facing face of ``deepspeed_tpu/resilience``: each scenario wires the
deterministic fault injector into a real (tiny, CPU-mesh) engine, drives the
failure end to end, and asserts the invariant the resilience layer promises —
no torn ``latest``, no silently-applied NaN step, no wedged-forever hang.

    python tools/chaos_drill.py --list
    python tools/chaos_drill.py --scenario nan-burst
    python tools/chaos_drill.py --scenario preempt-mid-save
    python tools/chaos_drill.py --scenario hung-collective

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
The slow pytest wrappers live in ``tests/unit/test_chaos_drill.py`` under the
``chaos`` marker (excluded from the tier-1 fast suite).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_engine(resilience, workdir):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    eng, *_ = ds.initialize(
        model=TransformerLM(get_preset("tiny")),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "mesh": {"fsdp": 8}, "steps_per_print": 100,
                "resilience": {"enabled": True, **resilience}})
    return eng


def _train(eng, steps, seed=0, until_global_step=None):
    """Run ``steps`` optimizer-step attempts — or, with ``until_global_step``,
    loop until that many steps genuinely COMMITTED (skipped steps don't
    advance ``global_steps``; recovery drills must outlast their skips)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    B = eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size
    losses = []

    def done():
        if until_global_step is not None:
            return eng.global_steps >= until_global_step
        return len(losses) >= steps

    while not done():
        loss = eng.forward({"input_ids": rng.integers(0, 256, (B, 16))})
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


def _fresh_injector():
    from deepspeed_tpu.resilience import set_injector

    set_injector(None)


# ---------------------------------------------------------------------------
# scenarios: each returns (ok: bool, details: dict)
# ---------------------------------------------------------------------------

def scenario_preempt_mid_save(workdir):
    """Async save staged, then the 'host is lost' before the manifest commit.
    Invariant: after restart, load lands on the previous VERIFIED tag —
    ``latest`` never names the torn stage."""
    from deepspeed_tpu.resilience import FaultInjector, set_injector
    from deepspeed_tpu.resilience.manager import STAGING_FILE, verify_tag_dir
    from deepspeed_tpu.runtime.checkpoint import read_latest_tag

    ckpt = os.path.join(workdir, "ckpt")
    eng = _make_engine({"checkpoint": {"async_save": True}}, workdir)
    _train(eng, 2)
    eng.save_checkpoint(ckpt)
    eng._primary_mgr.drain()                         # step-2 tag committed
    _train(eng, 1)
    set_injector(FaultInjector(
        [{"kind": "io_error", "site": "async_commit"}]))
    eng.save_checkpoint(ckpt)                        # stage killed pre-commit
    eng._primary_mgr.drain(raise_on_error=False)
    _fresh_injector()
    eng.shutdown()                                   # 'host lost' here

    eng2 = _make_engine({}, workdir)                 # the respawn
    path, _ = eng2.load_checkpoint(ckpt)
    staged = os.path.join(ckpt, "global_step3")
    ok_prev, why = verify_tag_dir(os.path.join(ckpt, "global_step2"))
    details = {"loaded": path, "resumed_step": eng2.global_steps,
               "latest": read_latest_tag(ckpt),
               "staged_sentinel": os.path.exists(
                   os.path.join(staged, STAGING_FILE)),
               "prev_tag_verified": ok_prev, "prev_tag_reason": why}
    eng2.shutdown()
    ok = (path is not None and path.endswith("global_step2")
          and eng2.global_steps == 2
          and details["latest"] == "global_step2"
          and details["staged_sentinel"] and ok_prev)
    return ok, details


def scenario_nan_burst(workdir):
    """A burst of poisoned-gradient steps inside the healing budget.
    Invariant: every bad step skipped whole (params untouched), training
    finishes the course with finite loss and the exact skip count."""
    import numpy as np

    eng = _make_engine({"max_consecutive_bad_steps": 4,
                        "faults": [{"kind": "nan_grads", "step": 2,
                                    "times": 2}]}, workdir)
    losses = _train(eng, 0, until_global_step=5)
    rep = eng.resilience_report()
    details = {"skipped_steps": eng.skipped_steps,
               "global_steps": eng.global_steps,
               "final_loss": losses[-1],
               "bad_steps_skipped": rep["guard"]["bad_steps_skipped"],
               "aborted": rep["aborted"]}
    eng.shutdown()
    ok = (eng.skipped_steps == 2 and eng.global_steps == 5
          and np.isfinite(losses[-1]) and not rep["aborted"])
    return ok, details


def scenario_hung_collective(workdir):
    """A host collective wedges past its deadline. Invariant: the watchdog
    detects it WHILE in flight, names the collective, and the fleet-agreed
    ABORT reaches the step loop (the elastic agent's respawn signal) instead
    of the process hanging forever."""
    from deepspeed_tpu.resilience import CoordinatedAbort

    hb_dir = os.path.join(workdir, "heartbeats")
    eng = _make_engine({
        "heartbeat": {"enabled": True, "dir": hb_dir, "interval_s": 0.05,
                      "poll_s": 0.05, "deadline_s": 30.0,
                      "collective_deadline_s": 0.15},
        "faults": [{"kind": "slow_collective", "delay_s": 0.6}]}, workdir)
    aborted = False
    try:
        _train(eng, 3)
    except CoordinatedAbort:
        aborted = True
    rep = eng.resilience_report()
    details = {"aborted": aborted,
               "stuck_collectives":
                   rep["heartbeat"]["counters"]["stuck_collectives"],
               "last_cause": rep["heartbeat"]["last_cause"],
               "heartbeat_file": os.path.exists(
                   os.path.join(hb_dir, "heartbeat_0.json"))}
    eng.shutdown()
    ok = (aborted and details["stuck_collectives"] >= 1
          and "all_reduce_host" in details["last_cause"]
          and details["heartbeat_file"])
    return ok, details


SCENARIOS = {
    "preempt-mid-save": scenario_preempt_mid_save,
    "nan-burst": scenario_nan_burst,
    "hung-collective": scenario_hung_collective,
}


def run_scenario(name: str, workdir=None) -> dict:
    """Run one drill; returns the verdict record (also usable from tests)."""
    if name not in SCENARIOS:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {sorted(SCENARIOS)})")
    _fresh_injector()
    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix=f"chaos_{name.replace('-', '_')}_")
    t0 = time.time()
    try:
        ok, details = SCENARIOS[name](workdir)
    finally:
        _fresh_injector()
        from deepspeed_tpu import comm

        comm.set_retry_policy(None)
    return {"scenario": name, "ok": ok, "seconds": round(time.time() - t0, 2),
            "workdir": workdir, "details": details}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name, workdir=args.workdir)
        print(json.dumps(verdict, indent=2, default=str))
        if not verdict["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
