#!/usr/bin/env python
"""Mesh-autotuner drill CLI: prove on the 8-device mesh that

* the winner store round-trips and ``mesh: "auto"`` config parses into the
  resolution path (``store`` scenario — fast),
* the full data-driven loop closes (``mesh-auto`` scenario): measure every
  drill candidate shape exhaustively through the Autotuner's mesh axis,
  calibrate the cost model's link bandwidths from those measurements, and
  check that (a) the cost model's top-2 ranked shapes contain the
  measured-fastest shape, (b) the production flow — rank, measure only the
  top-2 survivors, persist the winner — adopts a shape within 10 % of the
  best exhaustively measured tokens/s, and (c) an engine built with
  ``mesh: "auto"`` actually adopts the persisted winner.

    python tools/scaling_drill.py --list
    python tools/scaling_drill.py --scenario store
    python tools/scaling_drill.py --scenario mesh-auto
    python tools/scaling_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
Slow pytest wrappers live in ``tests/unit/test_scaling.py`` under the
``scaling`` + ``slow`` markers. The measured scaling CURVES (tokens/s/chip
vs world size) are ``bench.py --scaling``'s job, not this drill's — the
drill asserts the decision loop, the bench records the artifact.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TOL_ADOPT = 0.10         # winner must be within 10% of the exhaustive best
TOP_K = 2                # survivors the production flow measures

#: the drill's candidate space — the MULTICHIP shape set at world 8
CANDIDATES = [
    {"dp": 8},
    {"fsdp": 8},
    {"tp": 8},
    {"dp": 4, "sp": 2},
    {"dp": 2, "fsdp": 2, "tp": 2},
    {"pp": 2, "fsdp": 2, "tp": 2},
]


class DrillFailure(AssertionError):
    pass


def check(ok, msg, details):
    if not ok:
        raise DrillFailure(f"{msg}: {json.dumps(details, default=str)}")


def _model_factory(mesh_shape=None):
    """Dense harness model; switches on Ulysses attention when the
    candidate shape has an sp axis (the Autotuner's mesh-aware factory
    contract)."""
    from deepspeed_tpu.autotuning.scaling import build_harness_model

    kind = "dense_sp" if (mesh_shape or {}).get("sp", 1) > 1 else "dense"
    return build_harness_model(kind)


def _base_config():
    return {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"param_persistence_threshold": 0},
        "pipeline": {"micro_batches": 2},   # only consulted when pp > 1
        "steps_per_print": 10 ** 9,
    }


def _make_batch(n):
    import numpy as np

    return {"input_ids": np.random.default_rng(0).integers(
        0, 256, (n, 64)).astype(np.int32)}


def _tune(mesh_candidates, store=None, steps=3):
    from deepspeed_tpu.autotuning import Autotuner

    tuner = Autotuner(
        _model_factory, _base_config(), micro_batch_candidates=(2,),
        zero_stage_candidates=(3,), mesh_candidates=mesh_candidates,
        winner_store=store, steps=steps, make_batch=_make_batch)
    best = tuner.tune()
    return tuner, best


def _mesh_key(m):
    return json.dumps({k: m[k] for k in sorted(m)}) if m else "{}"


# ---------------------------------------------------------------------------
# scenario: store — winner persistence + mesh:"auto" resolution plumbing
# ---------------------------------------------------------------------------

def scenario_store(workdir=None):
    import tempfile

    import deepspeed_tpu as ds
    from deepspeed_tpu.autotuning.mesh_store import (WinnerStore, device_kind,
                                                     resolve_auto_axis_sizes)
    from deepspeed_tpu.parallel.cost_model import (ModelProfile,
                                                   model_signature)

    path = os.path.join(workdir or tempfile.mkdtemp(prefix="dstpu_drill_"),
                        "winners.json")
    store = WinnerStore(path)
    model = _model_factory()
    profile = ModelProfile.from_model(model)
    sig = model_signature(profile)
    kind = device_kind()

    # miss → cost-model fallback (never an error, never an implicit tune)
    fallback = resolve_auto_axis_sizes(8, profile, winner_cache=path,
                                       zero_stage=3)
    check(isinstance(fallback, dict) and fallback,
          "auto resolution returned no mesh on a cache miss", fallback)

    mesh = {"fsdp": 4, "dp": 2}
    store.put(sig, 8, kind, mesh, 123.4, zero_stage=3)
    hit = resolve_auto_axis_sizes(8, profile, winner_cache=path,
                                  zero_stage=3)
    check(hit == mesh, "winner store round-trip lost the mesh",
          {"put": mesh, "got": hit})
    # winners are keyed per zero stage: a stage-3 shape must not be
    # visible to a stage-0 lookup (that run falls through to the cost
    # model, which ranks without the fsdp gather term)
    check(store.get(sig, 8, kind, zero_stage=0) is None,
          "stage-0 lookup returned a stage-3 winner", {"winner": mesh})

    # the engine-level path: mesh:"auto" config adopts the stored winner
    eng = None
    try:
        eng, *_ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3,
                                  "param_persistence_threshold": 0},
            "mesh": "auto",
            "autotuning": {"winner_cache": path},
            "steps_per_print": 10 ** 9})
        adopted = {k: v for k, v in eng.topology.axis_sizes.items()
                   if v > 1}
        check(adopted == mesh, "mesh:'auto' engine ignored the winner",
              {"winner": mesh, "adopted": adopted})
    finally:
        if eng is not None:
            eng.shutdown()
    return {"store": path, "winner": mesh, "fallback": fallback}


# ---------------------------------------------------------------------------
# scenario: mesh-auto — the full measured decision loop
# ---------------------------------------------------------------------------

def scenario_mesh_auto(workdir=None):
    import tempfile

    import deepspeed_tpu as ds
    from deepspeed_tpu.autotuning.mesh_store import WinnerStore, device_kind
    from deepspeed_tpu.parallel.cost_model import (CostModel, ModelProfile,
                                                   collective_volumes,
                                                   fit_bandwidths)

    workdir = workdir or tempfile.mkdtemp(prefix="dstpu_drill_")

    # 1) exhaustive measurement over the candidate space (one protocol:
    #    the Autotuner's own trial loop)
    tuner_full, best_full = _tune(CANDIDATES,
                                  store=WinnerStore(
                                      os.path.join(workdir, "full.json")))
    ok_trials = [r for r in tuner_full.results if r.ok]
    check(best_full is not None and len(ok_trials) >= 4,
          "exhaustive measurement lost too many candidates",
          {"ok": len(ok_trials),
           "errors": [r.error for r in tuner_full.results if not r.ok]})

    profile = ModelProfile.from_model(_model_factory())
    measured = {}           # mesh key -> (mesh, samples/s, volumes)
    for r in ok_trials:
        mesh = r.config["mesh"]
        dpw = mesh.get("dp", 1) * mesh.get("fsdp", 1)
        tokens = 2 * dpw * 64
        vol = collective_volumes(
            profile, mesh, zero_stage=3, tokens=tokens,
            micro_batches=2 if mesh.get("pp", 1) > 1 else 1)
        measured[_mesh_key(mesh)] = (mesh, r.samples_per_sec, tokens, vol)

    # 2) calibrate link bandwidths from the measured trials themselves
    samples = [{"step_s": tokens / 64.0 / sps, **vol}
               for (_, sps, tokens, vol) in measured.values()]
    bw = fit_bandwidths(samples)
    cm = CostModel(bw)

    # 3) rank: predicted tokens/s per candidate; the measured-fastest
    #    shape must sit in the top-2 (the acceptance gate)
    ranked = cm.rank_by_throughput(
        profile, [m for (m, _, _, _) in measured.values()],
        zero_stage=3, micro_batch=2)
    best_measured = max(measured.values(), key=lambda t: t[1])
    top2 = [_mesh_key(m) for m, _ in ranked[:TOP_K]]
    check(_mesh_key(best_measured[0]) in top2,
          "cost-model top-2 does not contain the measured-fastest shape",
          {"ranked": [(m, round(t, 1)) for m, t in ranked],
           "measured": {k: round(v[1], 2) for k, v in measured.items()},
           "calibration": bw.as_dict()})

    # 4) the production flow: measure ONLY the top-2 survivors, persist
    topk_store = WinnerStore(os.path.join(workdir, "winners.json"))
    topk_meshes = [m for m, _ in ranked[:TOP_K]]
    tuner_topk, winner = _tune(topk_meshes, store=topk_store)
    check(winner is not None, "top-K measurement produced no winner",
          {"errors": [r.error for r in tuner_topk.results if not r.ok]})

    # 5) winner within 10% of the exhaustive best (tokens/s == samples/s
    #    here: same seq everywhere); compare on the EXHAUSTIVE table so
    #    run-to-run noise between the two tuner passes doesn't leak in
    win_key = _mesh_key(winner.config["mesh"])
    win_sps = measured[win_key][1] if win_key in measured \
        else winner.samples_per_sec
    ratio = win_sps / best_measured[1]
    check(ratio >= 1.0 - TOL_ADOPT,
          f"adopted mesh more than {TOL_ADOPT:.0%} off the exhaustive best",
          {"winner": winner.config["mesh"], "winner_sps": round(win_sps, 2),
           "best": best_measured[0], "best_sps": round(best_measured[1], 2),
           "ratio": round(ratio, 3)})

    # 6) mesh:"auto" adopts the persisted winner
    eng = None
    try:
        eng, *_ = ds.initialize(model=_model_factory(
            mesh_shape=winner.config["mesh"]), config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3,
                                  "param_persistence_threshold": 0},
            "pipeline": {"micro_batches": 2},
            "mesh": "auto",
            "autotuning": {"winner_cache": topk_store.path},
            "steps_per_print": 10 ** 9})
        adopted = {k: v for k, v in eng.topology.axis_sizes.items()
                   if v > 1}
        check(adopted == winner.config["mesh"],
              "mesh:'auto' engine did not adopt the tuned winner",
              {"winner": winner.config["mesh"], "adopted": adopted})
    finally:
        if eng is not None:
            eng.shutdown()

    return {
        "measured": {k: round(v[1], 2) for k, v in measured.items()},
        "ranked": [( {a: b for a, b in m.items()}, round(t, 1))
                   for m, t in ranked],
        "calibration": bw.as_dict(),
        "winner": winner.config["mesh"],
        "winner_vs_best": round(ratio, 3),
        "store": topk_store.path,
    }


SCENARIOS = {
    "store": scenario_store,
    "mesh-auto": scenario_mesh_auto,
}


def run_scenario(name: str) -> dict:
    fn = SCENARIOS.get(name)
    if fn is None:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {', '.join(SCENARIOS)})")
    t0 = time.perf_counter()
    try:
        detail = fn()
        ok, err = True, None
    except DrillFailure as e:
        detail, ok, err = None, False, str(e)
    return {"scenario": name, "ok": ok, "error": err, "detail": detail,
            "elapsed_s": round(time.perf_counter() - t0, 1)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    args = ap.parse_args(argv)
    if args.list:
        print("\n".join(SCENARIOS))
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name)
        print(json.dumps(verdict))
        if not verdict["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
