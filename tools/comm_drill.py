#!/usr/bin/env python
"""Quantized-collective (ZeRO++) drill CLI: prove on the 8-device mesh that

* the ``comm/<op>_bytes`` accounting matches the ANALYTIC wire payload for
  dense and quantized collectives (the acceptance instrument is itself
  pinned),
* a short fsdp training run with qwZ+hpZ+qgZ matches the bf16-collective
  baseline's final loss within tolerance, with the quantized ops' byte
  counters showing >= 3x volume reduction,
* the fp32 master path is bit-identical when quantization is off (the
  explicit-collective region with every feature disabled is
  deterministic),
* the two-hop qgZ split (intra-slice bf16, inter-slice quantized) holds
  loss parity and logs its hops under the documented op names, and hpZ
  falls back gracefully on a single-slice mesh.

* the MoE expert-dispatch all-to-alls (dense / quantized / hierarchical
  two-hop) log exactly the ``moe_a2a_wire_bytes`` analytic payload, and a
  full traced ``_grouped_moe_ep`` dispatch decomposes into those terms.

    python tools/comm_drill.py --list
    python tools/comm_drill.py --scenario bytes
    python tools/comm_drill.py --scenario parity
    python tools/comm_drill.py --scenario two-hop
    python tools/comm_drill.py --scenario moe-a2a
    python tools/comm_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
Slow pytest wrappers live in ``tests/unit/test_zeropp.py`` under the
``zpp`` + ``slow`` markers. ``bench.py --zero-pp`` reuses
:func:`measure_pair` to record comm-bytes and step-time into the bench
ledger.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TOL_LOSS = 0.05          # quantized-vs-baseline final-loss tolerance
MIN_REDUCTION = 3.0      # required comm-volume shrink on the quantized ops


class DrillFailure(AssertionError):
    pass


def check(ok, msg, details):
    if not ok:
        raise DrillFailure(f"{msg}: {json.dumps(details)}")


def _logger():
    from deepspeed_tpu.comm.logger import comms_logger

    comms_logger.enabled = True
    comms_logger.prof_all = True
    return comms_logger


def _delta(before, after):
    ops = set(before) | set(after)
    return {op: after.get(op, 0.0) - before.get(op, 0.0) for op in ops
            if after.get(op, 0.0) != before.get(op, 0.0)}


# ---------------------------------------------------------------------------
# scenario: bytes — the counters match the analytic wire payload
# ---------------------------------------------------------------------------

def scenario_bytes(workdir=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu import comm
    from deepspeed_tpu.comm import quantized as cq
    from deepspeed_tpu.parallel import build_mesh

    lg = _logger()
    topo = build_mesh(axis_sizes={"dp": 8})
    n = 4096                      # per-device elements
    bs = 512

    def traced_bytes(fn, x, in_spec, out_spec):
        """Trace (never execute) one shard_map'd collective and return the
        per-op byte deltas the trace logged."""
        before = dict(lg.bytes)
        jax.make_jaxpr(jax.shard_map(fn, mesh=topo.mesh, in_specs=in_spec,
                                     out_specs=out_spec,
                                     check_vma=False))(x)
        return _delta(before, dict(lg.bytes))

    xb = jnp.zeros((8 * n,), jnp.bfloat16)
    xf = jnp.zeros((8 * n,), jnp.float32)
    cases = []

    # dense ops: size * itemsize of the traced operand
    d = traced_bytes(lambda v: comm.all_gather(v, axis="dp"), xb,
                     P("dp"), P("dp"))
    cases.append(("all_gather bf16", d.get("all_gather"), n * 2))
    d = traced_bytes(lambda v: comm.reduce_scatter(v, axis="dp"), xf,
                     P(None), P("dp"))
    cases.append(("reduce_scatter fp32", d.get("reduce_scatter"), 8 * n * 4))
    d = traced_bytes(lambda v: comm.broadcast(v, src=0, axis="dp"), xb,
                     P("dp"), P("dp"))
    cases.append(("broadcast bf16", d.get("broadcast"), n * 2))

    # quantized ops: packed payload + fp32 block scales (wire_bytes)
    for bits in (8, 4):
        d = traced_bytes(
            lambda v, b=bits: cq.all_gather_q(v, "dp", bits=b, block_size=bs),
            xb, P("dp"), P("dp"))
        cases.append((f"all_gather int{bits}", d.get("all_gather"),
                      cq.wire_bytes(n, bits, bs)))
        d = traced_bytes(
            lambda v, b=bits: cq.reduce_scatter_q(v, "dp", bits=b,
                                                  block_size=bs),
            xf, P(None), P("dp"))
        # payload = 8 per-destination chunks of n elements each
        cases.append((f"reduce_scatter int{bits}", d.get("reduce_scatter"),
                      8 * cq.wire_bytes(n, bits, bs)))
        d = traced_bytes(
            lambda v, b=bits: cq.broadcast_q(v, 0, "dp", bits=b,
                                             block_size=bs),
            xb, P("dp"), P("dp"))
        cases.append((f"broadcast int{bits}", d.get("broadcast"),
                      cq.wire_bytes(n, bits, bs)))

    # two-hop reduce-scatter: full-payload bf16 intra hop + quantized
    # 1/slice piece on the cross hop, under the documented op names
    d = traced_bytes(
        lambda v: cq.two_hop_reduce_scatter(v, "dp", 2, bits=8,
                                            block_size=bs),
        xb, P(None), P("dp"))
    cases.append(("two-hop intra bf16", d.get("reduce_scatter_intra"),
                  8 * n * 2))
    # after the 2-wide intra hop each device holds 4n elements; the cross
    # a2a quantizes them as 4 per-destination chunks of n
    cases.append(("two-hop cross int8", d.get("reduce_scatter"),
                  4 * cq.wire_bytes(n, 8, bs)))
    for name, got, want in cases:
        check(got == want, f"byte accounting mismatch: {name}",
              {"got": got, "want": want})
    return {"cases": [{"op": c[0], "bytes": c[1]} for c in cases]}


# ---------------------------------------------------------------------------
# shared fsdp-training comparison (parity scenario + bench.py --zero-pp)
# ---------------------------------------------------------------------------

def _train(zero_pp, steps=5, seed=0, mesh=None, timing=False):
    """One short fsdp run under the given zero_pp block; returns losses,
    per-op comm byte deltas (trace-time = per-step payload), and
    step-time stats."""
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, get_preset

    lg = _logger()
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "param_persistence_threshold": 0,
                              "zero_pp": zero_pp},
        "mesh": mesh or {"fsdp": 4, "dp": 2},
        "steps_per_print": 10 ** 9,
    }
    before = dict(lg.bytes)
    eng = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=config)[0]
    rng = np.random.default_rng(seed)
    batch = {"input_ids": rng.integers(
        0, 256, (2 * eng.topology.dp_world_size, 32))}
    losses, times = [], []
    for _ in range(steps):
        t0 = time.perf_counter()
        loss = eng.forward(batch)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
        times.append(time.perf_counter() - t0)
    comm_bytes = _delta(before, dict(lg.bytes))
    tokens = batch["input_ids"].size
    out = {
        "losses": losses, "final_loss": losses[-1],
        "comm_bytes": {k: int(v) for k, v in sorted(comm_bytes.items())},
        "zpp": (dict(eng._zpp.features) if eng._zpp is not None else None),
    }
    if timing:
        med = sorted(times[1:])[len(times[1:]) // 2]  # skip the compile step
        out["step_ms"] = round(med * 1e3, 2)
        out["tokens_per_sec"] = round(tokens / med, 1)
    return out


def measure_pair(steps=5, quant=None, mesh=None, timing=True):
    """Baseline (explicit dense bf16 collectives) vs quantized run — the
    shared body of the parity drill and the ``bench.py`` zero_pp section."""
    quant = quant or {"enabled": True, "qwz": True, "qgz": True,
                      "hpz": True, "hpz_partition_size": 2,
                      "weight_bits": 4, "grad_bits": 8}
    base = _train({"enabled": True}, steps=steps, mesh=mesh, timing=timing)
    q = _train(quant, steps=steps, mesh=mesh, timing=timing)

    def _ratio(op):
        num = base["comm_bytes"].get(op, 0)
        den = q["comm_bytes"].get(op, 0)
        return round(num / den, 2) if den else None

    loss_delta = abs(q["final_loss"] - base["final_loss"]) \
        / max(abs(base["final_loss"]), 1e-9)
    return {
        "baseline": base, "quantized": q,
        "all_gather_reduction": _ratio("all_gather"),
        "reduce_scatter_reduction": _ratio("reduce_scatter"),
        "loss_delta_frac": round(loss_delta, 4),
        "loss_tolerance": TOL_LOSS,
    }


def scenario_parity(workdir=None):
    # determinism first: the dense explicit region (quantization OFF) must
    # be bit-identical run-to-run — the fp32 master path has no lossy op
    a = _train({"enabled": True}, steps=4)
    b = _train({"enabled": True}, steps=4)
    check(a["losses"] == b["losses"],
          "dense explicit-collective region is not bit-identical",
          {"a": a["losses"], "b": b["losses"]})
    check(a["zpp"] is not None and not any(
        a["zpp"][f] for f in ("qwz", "qgz", "hpz")),
        "dense baseline unexpectedly quantized", a["zpp"])

    res = measure_pair(steps=5, timing=False)
    check(res["loss_delta_frac"] <= TOL_LOSS,
          "quantized run lost loss parity with the bf16 baseline",
          {"delta": res["loss_delta_frac"], "tol": TOL_LOSS})
    for op in ("all_gather_reduction", "reduce_scatter_reduction"):
        check(res[op] is not None and res[op] >= MIN_REDUCTION,
              f"comm-volume reduction below {MIN_REDUCTION}x on {op}",
              {op: res[op],
               "baseline": res["baseline"]["comm_bytes"],
               "quantized": res["quantized"]["comm_bytes"]})
    return res


def scenario_two_hop(workdir=None):
    import deepspeed_tpu as ds  # noqa: F401 — ensure package import first
    from deepspeed_tpu.comm import quantized as cq

    mesh = {"fsdp": 8}
    base = _train({"enabled": True}, steps=4, mesh=mesh)
    two = _train({"enabled": True, "qgz": True, "slice_size": 2,
                  "cross_slice_only": True}, steps=4, mesh=mesh)
    check(two["zpp"]["two_hop"], "two-hop qgZ plan not built", two["zpp"])
    delta = abs(two["final_loss"] - base["final_loss"]) \
        / max(abs(base["final_loss"]), 1e-9)
    check(delta <= TOL_LOSS, "two-hop qgZ lost loss parity",
          {"delta": delta})
    cb = two["comm_bytes"]
    check(cb.get("reduce_scatter_intra", 0) > 0
          and cb.get("reduce_scatter", 0) > 0,
          "two-hop hops not logged under the documented op names", cb)
    # the cross (DCN) hop moves 1/slice_count of the intra payload,
    # quantized — it must be far smaller than the ICI hop
    check(cb["reduce_scatter"] < cb["reduce_scatter_intra"] / 2,
          "cross-slice hop not compressed vs the intra hop", cb)

    # hpZ single-slice fallback: slice-local partition would equal the
    # primary partition — the plan must disable the secondary, not crash
    hpz = _train({"enabled": True, "hpz": True}, steps=2, mesh=mesh)
    check(hpz["zpp"] is not None and not hpz["zpp"]["hpz"],
          "hpZ did not fall back gracefully on a single-slice mesh",
          hpz["zpp"])
    # int4 wire sanity rides along: packed payload is half of int8
    check(cq.wire_bytes(4096, 4, 512) < cq.wire_bytes(4096, 8, 512),
          "int4 wire payload not smaller than int8", {})
    return {"baseline_loss": base["final_loss"],
            "two_hop_loss": two["final_loss"],
            "comm_bytes": cb, "hpz_fallback": True}


def scenario_moe_a2a(workdir=None):
    """MoE expert-dispatch a2a wire accounting: every ``moe_all_to_all``
    form (dense / int8 / int4 / hierarchical two-hop) logs exactly the
    analytic payload of ``moe_a2a_wire_bytes``, and a full traced
    ``_grouped_moe_ep`` dispatch (x out, ids out, y back) decomposes into
    those same terms — the instrument the bench_moe ledger rides is
    itself pinned."""
    import types

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm import quantized as cq
    from deepspeed_tpu.moe import sharded_moe as sm
    from deepspeed_tpu.parallel import build_mesh

    lg = _logger()
    topo = build_mesh(axis_sizes={"ep": 8})
    cap, D, bs = 16, 32, 256

    def traced_bytes(fn, x, in_spec, out_spec):
        before = dict(lg.bytes)
        jax.make_jaxpr(jax.shard_map(fn, mesh=topo.mesh, in_specs=in_spec,
                                     out_specs=out_spec,
                                     check_vma=False))(x)
        return _delta(before, dict(lg.bytes))

    x = jnp.zeros((8, cap, D), jnp.bfloat16)
    cases = []
    for bits, sl in [(0, 0), (8, 0), (4, 0), (8, 2), (0, 2), (4, 4)]:
        d = traced_bytes(
            lambda v, b=bits, s=sl: cq.moe_all_to_all(
                v, "ep", bits=b, block_size=bs, slice_size=s),
            x, P(None, None, None), P(None, None, None))
        want = {k: v for k, v in cq.moe_a2a_wire_bytes(
            8, cap * D, bits=bits, block_size=bs, slice_size=sl,
            itemsize=2).items() if v}
        cases.append((f"moe a2a bits={bits} slice={sl}", d, want))
    for name, got, want in cases:
        check(got == want, f"moe a2a byte mismatch: {name}",
              {"got": got, "want": want})

    # full dispatch: 2 payload a2as (x out + y back) + 1 exact id a2a,
    # every term under the documented op keys
    E, Dm, top_k, B, T = 8, 16, 2, 4, 4
    cfg = types.SimpleNamespace(top_k=top_k, moe_ep_capacity_factor=0.0,
                                moe_kernel="ragged", moe_a2a_bits=8,
                                moe_a2a_slice=2, moe_a2a_block=bs)
    w = {"router": jnp.zeros((Dm, E), jnp.float32),
         "w_gate": jnp.zeros((E, Dm, 32), jnp.float32),
         "w_up": jnp.zeros((E, Dm, 32), jnp.float32),
         "w_down": jnp.zeros((E, 32, Dm), jnp.float32)}
    h = jnp.zeros((B, T, Dm), jnp.float32)
    before = dict(lg.bytes)
    with jax.sharding.set_mesh(topo.mesh):
        jax.make_jaxpr(lambda hh: sm.grouped_moe_mlp_block(hh, w, cfg))(h)
    got = _delta(before, dict(lg.bytes))
    ep_cap = -(-B * T // 8) * top_k         # s_local * top_k, dropless
    xw = cq.moe_a2a_wire_bytes(8, ep_cap * Dm, bits=8, block_size=bs,
                               slice_size=2, itemsize=4)
    iw = cq.moe_a2a_wire_bytes(8, ep_cap, bits=0, block_size=bs,
                               slice_size=2, itemsize=4)
    want = {k: v for k, v in
            {k: 2 * xw[k] + iw[k] for k in xw}.items() if v}
    check(got == want, "full _grouped_moe_ep dispatch bytes mismatch",
          {"got": got, "want": want})
    return {"cases": [{"op": c[0], "bytes": c[1]} for c in cases],
            "full_dispatch": {k: int(v) for k, v in got.items()}}


SCENARIOS = {
    "bytes": scenario_bytes,
    "parity": scenario_parity,
    "two-hop": scenario_two_hop,
    "moe-a2a": scenario_moe_a2a,
}


def run_scenario(name: str) -> dict:
    fn = SCENARIOS.get(name)
    if fn is None:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {', '.join(SCENARIOS)})")
    t0 = time.perf_counter()
    try:
        detail = fn()
        ok, err = True, None
    except DrillFailure as e:
        detail, ok, err = None, False, str(e)
    return {"scenario": name, "ok": ok, "error": err, "detail": detail,
            "elapsed_s": round(time.perf_counter() - t0, 1)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    args = ap.parse_args(argv)
    if args.list:
        print("\n".join(SCENARIOS))
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name)
        print(json.dumps(verdict))
        if not verdict["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
