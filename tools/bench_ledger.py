"""Perf-trend ledger: append-only JSONL of bench results.

Every run of ``bench.py`` / ``bench_infer.py`` / ``bench_capacity.py``
appends one schema-versioned, git-sha-stamped line to
``tools/bench_ledger.jsonl``, turning the round artifacts
(``BENCH_r0*.json`` snapshots) into a machine-readable trajectory.
``tools/bench_trend.py`` diffs the latest entry against the best prior
one and exits nonzero past a configurable regression threshold — the
missing half of the ROADMAP's scaling-artifact item: a *trend*, not a
point.

Ledger line shape (schema 1)::

    {"schema": 1, "bench": "bench", "git_sha": "abc123...",
     "time": 1722800000.0, "iso_time": "2026-08-04T17:00:00",
     "metric": "train_tokens_per_sec_per_chip", "value": 24100.0,
     "unit": "tokens/s", "result": {...the bench's full JSON...}}

``append_ledger`` is deliberately best-effort and silent on failure —
the ledger must never sink a benchmark run — and honours
``DSTPU_BENCH_LEDGER=0`` (skip) / ``DSTPU_BENCH_LEDGER_PATH`` (redirect,
e.g. for tests).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Optional

LEDGER_SCHEMA = 1
_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_LEDGER = os.path.join(_HERE, "bench_ledger.jsonl")


def git_sha(repo_dir: Optional[str] = None) -> str:
    """The current commit (short sha, '-dirty' suffixed when the tree has
    local modifications); 'unknown' outside a git checkout."""
    cwd = repo_dir or os.path.dirname(_HERE)
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10).stdout.strip()
        if not sha:
            return "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=cwd, capture_output=True, text=True, timeout=10
        ).stdout.strip()
        return sha + ("-dirty" if dirty else "")
    except Exception:
        return "unknown"


def ledger_path() -> str:
    return os.environ.get("DSTPU_BENCH_LEDGER_PATH", DEFAULT_LEDGER)


def append_ledger(result: dict, bench: str,
                  path: Optional[str] = None) -> Optional[str]:
    """Append one bench result to the ledger; returns the path written or
    None (disabled / failed — never raises)."""
    if os.environ.get("DSTPU_BENCH_LEDGER", "1") == "0":
        return None
    try:
        p = path or ledger_path()
        now = time.time()
        entry = {
            "schema": LEDGER_SCHEMA,
            "bench": bench,
            "git_sha": git_sha(),
            "time": now,
            "iso_time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                      time.localtime(now)),
            "metric": result.get("metric"),
            "value": result.get("value"),
            "unit": result.get("unit"),
            "result": result,
        }
        line = json.dumps(entry, default=str)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "a", encoding="utf-8") as f:
            f.write(line + "\n")
        return p
    except Exception:
        return None


def read_ledger(path: Optional[str] = None) -> list:
    """All parseable ledger entries, in file order (corrupt lines are
    skipped — an interrupted append must not poison the trend)."""
    p = path or ledger_path()
    out = []
    try:
        with open(p, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(entry, dict) and entry.get("schema") == \
                        LEDGER_SCHEMA:
                    out.append(entry)
    except OSError:
        pass
    return out
