#!/usr/bin/env python
"""Fused Pallas paged-decode kernel drill CLI: prove through the public
engine surface that

* greedy decode tokens are BIT-IDENTICAL between ``decode_kernel='pallas'``
  (interpret mode on CPU, native on TPU) and the XLA dense-gather twin in
  fp32 — across ragged sequence lengths, block-boundary prompts, an int8
  KV pool, and speculative verify rounds (the wide-decode shape),
* a demote→promote cycle through the FUSED promote-fence prologue (the
  promotions riding the decode dispatch instead of a standalone donated
  scatter) yields the same greedy tokens as the standalone-fence xla path,
  with ``tier_report()`` counting the saved dispatches,
* the kernel's throughput advantage holds: ``>= 2x`` decode tokens/s over
  the XLA path at occupancy 128–256 — asserted ONLY on real TPU hardware
  (interpret mode on the CPU harness is an emulation, not a perf figure;
  there the scenario just records both rates and the cross-run no-regress
  gate is ``tools/bench_trend.py`` over the ``bench_decode_kernel``
  ledger series this drill appends).

    python tools/decode_kernel_drill.py --list
    python tools/decode_kernel_drill.py --scenario parity
    python tools/decode_kernel_drill.py --scenario fused-fence
    python tools/decode_kernel_drill.py --scenario throughput
    python tools/decode_kernel_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
Slow pytest wrappers live in ``tests/unit/test_decode_kernel.py`` under
the ``pallas`` + ``slow`` markers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEEDUP_TARGET = 2.0     # pallas-over-xla tok/s floor at occ 128-256 (TPU)
TPU_OCCS = (128, 256)


class DrillFailure(AssertionError):
    pass


def check(ok, msg, details):
    if not ok:
        raise DrillFailure(f"{msg}: {json.dumps(details, default=str)}")


def _fp32_pair(block_size=8, max_sequences=8, max_seq_len=None, **kw):
    """Two engines over the SAME fp32 tiny model/params, one per kernel."""
    import jax

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.presets import get_preset
    from deepspeed_tpu.models.transformer import TransformerLM

    cfg = get_preset("tiny", dtype="float32",
                     max_seq_len=max_seq_len or 64)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    engines = {
        kern: InferenceEngineV2(model, params=params,
                                max_sequences=max_sequences,
                                block_size=block_size, decode_kernel=kern,
                                **kw)
        for kern in ("pallas", "xla")}
    return cfg, engines


def scenario_parity() -> dict:
    """fp32 greedy-token identity pallas vs xla: ragged lengths,
    block-boundary prompts, int8 KV, and spec-verify rounds."""
    import numpy as np

    detail = {}
    # ragged lengths incl. exact block-boundary prompts (block_size=8)
    cfg, engines = _fp32_pair(block_size=8)
    rng = np.random.default_rng(3)
    lens = [3, 8, 11, 16, 21]                 # 8 and 16 sit on boundaries
    prompts = [rng.integers(1, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    toks = {}
    for kern, eng in engines.items():
        uids = list(range(len(prompts)))
        first = eng.put(uids, prompts)
        starts = [int(np.argmax(first[u])) for u in uids]
        out = eng.decode_batch(uids, starts, steps=6)
        toks[kern] = np.stack([out[u] for u in uids])
        assert eng.decode_kernel == kern, eng.decode_kernel
    check(np.array_equal(toks["pallas"], toks["xla"]),
          "ragged greedy tokens diverged",
          {"pallas": toks["pallas"].tolist(), "xla": toks["xla"].tolist()})
    detail["ragged"] = {"lens": lens, "identical": True}

    # int8 KV pool
    cfg, engines = _fp32_pair(block_size=8, kv_dtype="int8")
    toks = {}
    for kern, eng in engines.items():
        first = eng.put([0, 1], [prompts[2], prompts[4]])
        starts = [int(np.argmax(first[0])), int(np.argmax(first[1]))]
        out = eng.decode_batch([0, 1], starts, steps=6)
        toks[kern] = np.stack([out[0], out[1]])
    check(np.array_equal(toks["pallas"], toks["xla"]),
          "int8-KV greedy tokens diverged",
          {"pallas": toks["pallas"].tolist(), "xla": toks["xla"].tolist()})
    detail["int8kv"] = {"identical": True}

    # spec-verify (the wide-decode shape) on repetitive text so drafts fire
    cfg, engines = _fp32_pair(
        block_size=8, speculative={"enabled": True, "ngram": 2,
                                   "max_draft": 3, "fallback_steps": 2})
    rep = np.tile(rng.integers(1, cfg.vocab_size, 3), 7).astype(np.int32)
    toks = {}
    for kern, eng in engines.items():
        first = eng.put([0], [rep])
        out = eng.decode_batch([0], [int(np.argmax(first[0]))], steps=8,
                               speculative=True)
        toks[kern] = out[0]
        check(eng.spec_stats["fused"] == (1 if kern == "pallas" else 0),
              "spec_stats fused flag wrong",
              {"kernel": kern, "stats": dict(eng.spec_stats)})
    check(np.array_equal(toks["pallas"], toks["xla"]),
          "spec-verify greedy tokens diverged",
          {"pallas": toks["pallas"].tolist(), "xla": toks["xla"].tolist()})
    detail["spec_verify"] = {"identical": True}
    return detail


def scenario_fused_fence() -> dict:
    """Demote→promote through the FUSED prologue: same greedy tokens as the
    standalone-fence xla path, saved dispatches counted."""
    import numpy as np

    cfg, engines = _fp32_pair(
        block_size=8, max_sequences=4, max_seq_len=96,
        prefix_cache={"enabled": True,
                      "tiers": {"enabled": True, "host_mb": 8.0}})
    rng = np.random.default_rng(5)
    shared = rng.integers(1, cfg.vocab_size, 24).astype(np.int32)  # 3 blocks
    sfx = rng.integers(1, cfg.vocab_size, 4).astype(np.int32)
    toks, reports = {}, {}
    for kern, eng in engines.items():
        # publish the shared prefix, flush, demote everything to host
        eng.put([0], [np.concatenate([shared, sfx])])
        eng.flush([0])
        pc = eng.prefix_cache
        pc.evict(pc.evictable_blocks())
        # a fresh request re-attaches the demoted prefix: the promotions
        # must fence through the (fused, for pallas) prologue of the next
        # dispatch before any attention read
        first = eng.put([1], [np.concatenate([shared, sfx])])
        out = eng.decode_batch([1], [int(np.argmax(first[1]))], steps=6)
        toks[kern] = out[1]
        reports[kern] = eng.tier_report()
        eng.close()
    check(np.array_equal(toks["pallas"], toks["xla"]),
          "fused-fence greedy tokens diverged",
          {"pallas": toks["pallas"].tolist(), "xla": toks["xla"].tolist()})
    check(reports["pallas"]["fused_prologue_dispatches_saved"] >= 1,
          "fused prologue saved no dispatches", reports["pallas"])
    check(reports["xla"]["fused_prologue_dispatches_saved"] == 0,
          "xla path claimed fused dispatches", reports["xla"])
    return {"identical": True,
            "saved_dispatches":
                reports["pallas"]["fused_prologue_dispatches_saved"]}


def scenario_throughput() -> dict:
    """A/B tokens/s pallas vs xla; >=2x asserted on real TPU at occ
    128-256, recorded (and trend-gated across runs) on the dev harness."""
    import jax

    from bench_infer import run_decode_kernel_bench

    on_tpu = jax.devices()[0].platform == "tpu"
    res = run_decode_kernel_bench(
        occupancies=TPU_OCCS if on_tpu else (2, 4))
    for occ, row in res["configs"].items():
        if res["dtype"] == "float32":
            # bit-identity is the fp32 contract; the TPU serving proxy is
            # bf16, where reduction order legitimately flips argmax ties
            check(row["greedy_identical"],
                  f"occ {occ}: greedy tokens diverged", row)
        if on_tpu and int(occ) in TPU_OCCS:
            check(row["speedup"] >= SPEEDUP_TARGET,
                  f"occ {occ}: pallas speedup below {SPEEDUP_TARGET}x", row)
    res["speedup_asserted"] = on_tpu
    return res


SCENARIOS = {
    "parity": scenario_parity,
    "fused-fence": scenario_fused_fence,
    "throughput": scenario_throughput,
}


def run_scenario(name: str) -> dict:
    fn = SCENARIOS.get(name)
    if fn is None:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {', '.join(SCENARIOS)})")
    t0 = time.perf_counter()
    try:
        detail = fn()
        ok, err = True, None
    except DrillFailure as e:
        detail, ok, err = None, False, str(e)
    return {"scenario": name, "ok": ok, "error": err, "detail": detail,
            "elapsed_s": round(time.perf_counter() - t0, 1)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--no-ledger", action="store_true",
                    help="skip the bench_decode_kernel ledger append")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    bench = None
    for name in names:
        verdict = run_scenario(name)
        print(json.dumps(verdict))
        if not verdict["ok"]:
            rc = 1
        elif name == "throughput":
            bench = verdict["detail"]
    if bench is not None and rc == 0 and not args.no_ledger:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench_ledger import append_ledger

        path = append_ledger(bench, "bench_decode_kernel")
        print(json.dumps({"ledger": path}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
