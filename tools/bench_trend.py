#!/usr/bin/env python
"""Perf-trend gate: diff the latest bench ledger entry against the best
prior one; exit nonzero past the regression threshold.

Reads ``tools/bench_ledger.jsonl`` (see ``tools/bench_ledger.py`` — each
``bench.py`` / ``bench_infer.py`` / ``bench_capacity.py`` run appends one
schema-versioned, git-sha-stamped line). For every tracked metric of every
bench with >= 2 entries, the LATEST value is compared against the BEST
prior value; a drop larger than ``--threshold`` (default 15 % — the same
inter-window spread ``bench.py`` itself tolerates) is a regression:

    python tools/bench_trend.py                     # all benches
    python tools/bench_trend.py --bench bench       # one bench
    python tools/bench_trend.py --threshold 0.10

Exit code 0 = no regression (including "not enough data yet"), 1 = at
least one tracked metric regressed, 2 = usage/ledger error. The JSON
verdict on stdout lists every comparison so CI logs carry the numbers,
not just the verdict.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

try:
    from bench_ledger import read_ledger
except ImportError:                      # invoked as tools/bench_trend.py
    import os

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench_ledger import read_ledger

#: tracked (dotted-path, direction) per bench; a ``*`` path segment fans
#: out over dict keys and the BEST match is taken (e.g. the fastest
#: decode occupancy) — all current metrics are higher-is-better
TRACKED = {
    "bench": [("value", "higher")],
    "bench_infer": [("prefill_tokens_per_sec", "higher"),
                    ("decode.*.tokens_per_sec", "higher"),
                    # achieved GB/s vs the measured stream roofline: a
                    # config that keeps its tok/s by shrinking its streamed
                    # bytes (e.g. a silently shorter context) still gates
                    ("decode.*.achieved_gbps", "higher")],
    # fused Pallas decode kernel vs its XLA dense-gather twin
    # (bench_infer.run_decode_kernel_bench / the decode-kernel drill):
    # per-occupancy series — the kernel's own throughput must not regress,
    # and neither may its advantage over the reference path
    "bench_decode_kernel": [("configs.*.pallas_tokens_per_sec", "higher"),
                            ("configs.*.speedup", "higher")],
    # capacity is a PER-(DEVICE, LADDER) series: the rung set runs on the
    # dev CPU harness and on real chips with different achievable maxima,
    # and a dev restatement must neither trip a phantom regression against
    # a TPU/full-ladder figure nor mask a real one (the old flat
    # best.params_b path was exactly that cross-series comparison)
    "bench_capacity": [("by_device.*.*.params_b", "higher")],
    # measured multi-chip scaling (bench.py --scaling): every
    # (device kind, mesh shape, world size) config is its own trend
    # series, like the decode.* occupancies — tokens/s/chip and parallel
    # efficiency both gate, so a shape that keeps its throughput by
    # silently losing efficiency (or vice versa) still trips the gate,
    # while a CPU-harness run never gates against a TPU entry
    "bench_scaling": [("curves.*.*.*.tokens_per_sec_per_chip", "higher"),
                      ("curves.*.*.*.parallel_efficiency", "higher")],
    # ZeRO++ quantized collectives (bench.py --zero-pp): comm-volume
    # reduction on the quantized ops and the quantized run's throughput
    "bench_zero_pp": [("all_gather_reduction", "higher"),
                      ("reduce_scatter_reduction", "higher"),
                      ("quantized.tokens_per_sec", "higher")],
    # elastic fleet (tools/elastic_drill.py): the raw figures are wall
    # times (lower-is-better), so the gate rides their higher-is-better
    # restatements — warm-over-cold start speedup and rejoins per second
    "bench_elastic": [("warm_speedup", "higher"),
                      ("rejoin_per_sec", "higher")],
    # SLO preemption (tools/serve_drill.py --scenario slo-storm): every
    # pause must come back (a resume failure sheds work the pause
    # promised to preserve) and preemption churn must not crater the
    # storm's aggregate decode throughput
    "bench_slo": [("resume_success_rate", "higher"),
                  ("storm_tokens_per_sec", "higher")],
    # cross-replica migration (tools/serve_drill.py --scenario
    # crash-migrate): every captured request must land on a sibling
    # (durable-manifest resume or re-prefill — a failed migration sheds
    # work the manifest promised to preserve), and the sibling's
    # post-crash decode throughput must not crater
    "bench_migration": [("migration_success_rate", "higher"),
                        ("resumed_tokens_per_sec", "higher")],
    # expert-parallel MoE serving sweep (bench.py --ep-sweep): decode
    # throughput per (experts, ep-width, kernel) cell, the dropless
    # ragged/padded speedup at equal config, and the per-expert load
    # balance (mean/max; 1.0 = even) the AutoEP planner optimises
    "bench_moe": [("moe.*.tokens_per_sec", "higher"),
                  ("moe.*.ragged_speedup", "higher"),
                  ("moe.*.balance", "higher")],
}


def extract(result: dict, path: str) -> Dict[str, float]:
    """Dotted-path lookup into a bench result, returned as
    ``{concrete_path: value}``. A ``*`` segment fans out over dict keys
    into SEPARATE concrete paths — each measured config is its own trend
    series, because two runs that measured different config sets (e.g.
    decode occupancies 8/32 vs 32/128+quant variants) are not comparable
    as a max: the gate would flag a phantom regression whenever the
    richer set goes unmeasured, and mask a real one behind any still-fast
    sibling config."""
    nodes = [("", result)]
    for part in path.split("."):
        nxt = []
        for prefix, node in nodes:
            if not isinstance(node, dict):
                continue
            if part == "*":
                nxt.extend((f"{prefix}.{k}" if prefix else str(k), v)
                           for k, v in node.items())
            elif part in node:
                nxt.append((f"{prefix}.{part}" if prefix else part,
                            node[part]))
        nodes = nxt
    return {p: float(v) for p, v in nodes
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


def compare(entries: List[dict], threshold: float,
            bench: Optional[str] = None) -> dict:
    """The trend verdict over parsed ledger entries (pure function — the
    tier-1 tests drive it with synthetic ledgers). A concrete metric is
    gated only when the bench's LATEST run measured it — a config the
    newest run skipped is "no data", not a regression."""
    comparisons, regressions = [], []
    benches = sorted({e["bench"] for e in entries
                      if bench is None or e["bench"] == bench})
    for b in benches:
        rows = [e for e in entries if e["bench"] == b]
        if len(rows) < 2:
            continue
        per_row = [(e, {}) for e in rows]
        for path, _direction in TRACKED.get(b, [("value", "higher")]):
            for e, vals in per_row:
                vals.update(extract(e.get("result") or {}, path))
        latest_e, latest_vals = per_row[-1]
        metrics = sorted(latest_vals)
        for metric in metrics:
            prior = [(e, vals[metric]) for e, vals in per_row[:-1]
                     if metric in vals]
            if not prior:
                continue
            latest = latest_vals[metric]
            best_e, best = max(prior, key=lambda ev: ev[1])
            drop = (best - latest) / best if best > 0 else 0.0
            rec = {
                "bench": b, "metric": metric,
                "latest": latest, "latest_sha": latest_e.get("git_sha"),
                "best_prior": best, "best_sha": best_e.get("git_sha"),
                "change_frac": round(-drop, 4),
                "regressed": drop > threshold,
            }
            comparisons.append(rec)
            if rec["regressed"]:
                regressions.append(rec)
    return {"threshold": threshold, "entries": len(entries),
            "comparisons": comparisons, "regressions": regressions,
            "ok": not regressions}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default tools/bench_ledger.jsonl)")
    ap.add_argument("--bench", default=None, help="restrict to one bench")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="max tolerated fractional drop vs best prior")
    args = ap.parse_args(argv)
    if not (0.0 <= args.threshold < 1.0):
        print("bench_trend: --threshold must be in [0, 1)", file=sys.stderr)
        return 2
    entries = read_ledger(args.ledger)
    verdict = compare(entries, args.threshold, bench=args.bench)
    print(json.dumps(verdict, indent=2))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
