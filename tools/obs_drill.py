#!/usr/bin/env python
"""Observability drill CLI: prove the metrics/tracing/profiling substrate
works against a real serving load, and that it is cheap enough to leave on
— exit nonzero if any invariant fails (the observability face of
``tools/chaos_drill.py`` / ``tools/serve_drill.py``).

Scenarios:

* **metrics-under-load** — synthetic continuous-batching load with tracing
  enabled; scrape ``/metrics`` over real HTTP and assert the exposition
  parses, the ``serving/ttft_ms`` / ``serving/tpot_ms`` /
  ``serving/queue_wait_ms`` histograms populate, and ``/healthz`` /
  ``/readyz`` flip with the batcher health states (DRAINING = live but
  not ready).
* **profile-capture** — arm the on-demand ``jax.profiler`` trigger via its
  trigger file mid-load; assert exactly one rate-limited capture fires and
  leaves trace artifacts on disk.
* **overhead-budget** — alternate measurement windows of the same workload
  with instrumentation enabled vs stubbed out; assert the median per-step
  overhead stays under 2 % (or under an absolute 50 µs floor — below
  timer noise there is nothing left to shave).

    python tools/obs_drill.py --list
    python tools/obs_drill.py --scenario metrics-under-load
    python tools/obs_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
Slow pytest wrappers live in ``tests/unit/test_observability.py`` under
the ``obs`` + ``slow`` markers.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_engine():
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset

    return InferenceEngineV2(TransformerLM(get_preset("tiny")),
                             max_sequences=8, max_seq_len=128, block_size=16)


def _make_batcher(engine, registry, **serving):
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.serving import ContinuousBatcher

    cfg = ServingConfig(**{"prefill_chunk": 32, "default_max_new_tokens": 8,
                           **serving})
    return ContinuousBatcher(engine, cfg, registry=registry)


def _load(batcher, n=6, prompt_len=24, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    uids = [batcher.submit(rng.integers(0, 250, prompt_len))
            for _ in range(n)]
    batcher.pump(max_steps=500)
    return uids


def _get(url):
    try:
        resp = urllib.request.urlopen(url, timeout=5)
        return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# scenarios: each returns (ok: bool, details: dict)
# ---------------------------------------------------------------------------

def scenario_metrics_under_load(workdir):
    """Tracing-enabled load; scrape /metrics over HTTP; assert the SLO
    histograms populate, the text format carries well-formed histogram
    series, and the probes follow READY -> DRAINING."""
    from deepspeed_tpu.observability import MetricsRegistry

    reg = MetricsRegistry()
    b = _make_batcher(_make_engine(), reg)
    uids = _load(b)
    resolved = {u: b.manager.resolve(u) for u in uids}
    srv = b.serve_metrics_http()
    try:
        ready0 = _get(srv.url + "/readyz")[0]
        live0 = _get(srv.url + "/healthz")[0]
        code, text = _get(srv.url + "/metrics")
        b.begin_drain("drill")
        ready_drain = _get(srv.url + "/readyz")[0]
        live_drain = _get(srv.url + "/healthz")[0]
    finally:
        srv.close()
    b.drain(timeout_s=30.0)

    ttft = reg.get("serving/ttft_ms").series[()]
    tpot = reg.get("serving/tpot_ms").series[()]
    qw = reg.get("serving/queue_wait_ms").series[()]

    def bucket_counts(name):
        vals = []
        for line in text.splitlines():
            if line.startswith(name + "_bucket"):
                vals.append(float(line.rsplit(" ", 1)[1]))
        return vals

    ttft_buckets = bucket_counts("serving_ttft_ms")
    details = {
        "resolved": resolved,
        "ttft_samples": ttft.count, "tpot_samples": tpot.count,
        "queue_wait_samples": qw.count,
        "ttft_p50_ms": round(ttft.percentile(50), 3),
        "ttft_p99_ms": round(ttft.percentile(99), 3),
        "scrape_code": code,
        "ttft_bucket_series": ttft_buckets,
        "probes": {"ready": ready0, "live": live0,
                   "ready_draining": ready_drain,
                   "live_draining": live_drain},
        "report_slo": b.serving_report()["slo_ms"],
    }
    ok = (all(s == "completed" for s in resolved.values())
          and code == 200
          and ttft.count == len(uids) and qw.count == len(uids)
          and tpot.count == len(uids) * 7          # 8 new tokens -> 7 gaps
          and ttft_buckets == sorted(ttft_buckets)  # monotone cumulative
          and ttft_buckets and ttft_buckets[-1] == float(ttft.count)
          and ready0 == 200 and live0 == 200
          and ready_drain == 503 and live_drain == 200)
    return ok, details


def scenario_profile_capture(workdir):
    """Touch the trigger file mid-load; assert exactly one capture fires
    (rate limit suppresses the second arm) and real jax.profiler artifacts
    land in the capture directory."""
    from deepspeed_tpu.observability import MetricsRegistry, ProfileTrigger

    prof_dir = os.path.join(workdir or ".", "obs_drill_profiles")
    b = _make_batcher(_make_engine(), MetricsRegistry(),
                      default_max_new_tokens=16)
    trig = ProfileTrigger(prof_dir, capture_steps=3, rate_limit_s=3600.0,
                          warmup_steps=2)
    b.profile_trigger = trig
    os.makedirs(prof_dir, exist_ok=True)
    open(trig.trigger_file, "w").close()       # arm from "outside"
    uids = _load(b, n=4)
    open(trig.trigger_file, "w").close()       # second arm: rate-limited
    _load(b, n=2, seed=1)
    if trig.capturing:                         # load ended mid-capture
        b.step()
    trig.close()
    artifacts = [os.path.join(r, f) for r, _d, fs in os.walk(prof_dir)
                 for f in fs]
    details = {"counters": trig.counters,
               "artifacts": artifacts[:8],
               "n_artifacts": len(artifacts),
               "resolved": {u: b.manager.resolve(u) for u in uids}}
    ok = (trig.counters["captures"] == 1
          and trig.counters["suppressed_rate_limit"] == 1
          and trig.counters["capture_errors"] == 0
          and len(artifacts) > 0
          and not os.path.exists(trig.trigger_file))
    return ok, details


class _NullMetrics:
    """API-compatible no-op ServingMetrics: the zero-instrumentation
    baseline the overhead budget is measured against."""

    class _Noop:
        def observe(self, v):
            pass

        def set(self, v):
            pass

        def inc(self, v=1.0):
            pass

        percentile = lambda self, q: 0.0  # noqa: E731
        count = 0

    def __init__(self):
        n = self._Noop()
        self.ttft_ms = self.tpot_ms = self.queue_wait_ms = n
        self.step_ms = self.e2e_ms = n
        self.health = self.queue_depth = n
        self.active_requests = self.kv_occupancy = n
        self.registry = None
        self.spans_enabled = False

    def terminal(self, s):
        return self._Noop()

    def shed(self, r):
        return self._Noop()

    def rejected(self, r):
        return self._Noop()

    def set_health(self, h):
        pass


def scenario_overhead_budget(workdir):
    """Two-part budget proof that the registry + span tracing cost < 2% of
    a serving step (or < 50 us — below host-timer resolution):

    1. **direct op cost** — microbenchmark EXACTLY the instrument
       operations one traced serving step performs (step-latency observe,
       four gauge updates, per-request clock reads + TTFT/TPOT observes,
       the profile-trigger nil check) and divide by the measured median
       step time. This is deterministic: the ops are pure host float work,
       so the number reproduces to the microsecond.
    2. **end-to-end A/B with an A/A noise floor** — steady-state decode
       steps in alternating 8-step blocks on the SAME in-flight batch,
       flipping between full instrumentation and no-op stubs. Decode
       steps get monotonically slower as KV grows, so the estimator is
       the symmetric ABA triplet median (``t_mid - (t_prev+t_next)/2``:
       linear drift cancels; block minima reject one-sided scheduler
       spikes). An identically-shaped A/A run (stubs in BOTH arms)
       calibrates the sandbox's noise floor; the A/B overhead must stay
       under max(budget + floor, 0.5 ms) — the absolute allowance keeps a
       loaded CI worker green while a real regression (an accidental
       device sync is >= 1 ms/step) still trips it.
    """
    import numpy as np

    from deepspeed_tpu.observability import MetricsRegistry, ServingMetrics

    engine = _make_engine()
    real_null = _NullMetrics()
    BLOCK = 8

    def loaded_batcher(seed):
        # 4 requests in steady decode: 24-token prompt + up to 100 new
        # tokens each → ~96 pure decode steps before any completes
        b = _make_batcher(engine, MetricsRegistry(),
                          default_max_new_tokens=100)
        rng = np.random.default_rng(seed)
        [b.submit(rng.integers(0, 250, 24)) for _ in range(4)]
        while b.manager.prefilling():
            b.step()
        return b

    def set_mode(b, instrumented, real_metrics):
        b._trace = instrumented
        b.metrics = real_metrics if instrumented else real_null
        b.manager.metrics = b.metrics if instrumented else None

    def run_rounds(ab: bool):
        """3 rounds of 10 alternating blocks; returns (rounds, step_ms).
        ``ab=False`` stubs BOTH arms (the A/A noise calibration)."""
        rounds, samples = [], []
        for round_ in range(3):
            b = loaded_batcher(round_)
            real_metrics = b.metrics if ab else real_null
            for _ in range(3):                 # warm the decode path
                b.step()
            mode = bool(round_ % 2)            # alternate starting mode too
            sequence = []
            for _block in range(10):
                set_mode(b, mode, real_metrics)
                best = float("inf")
                for _ in range(BLOCK):
                    t0 = time.perf_counter()
                    b.step()
                    best = min(best, time.perf_counter() - t0)
                sequence.append((mode, best * 1e3))
                samples.append(best * 1e3)
                mode = not mode
            rounds.append(sequence)
            set_mode(b, True, real_metrics)
            b.begin_drain("overhead drill")    # reclaim the pool
            b.drain(timeout_s=30.0)
            if engine.state.sequences:         # invariant: no leak
                raise AssertionError(
                    f"leaked sequences {list(engine.state.sequences)}")
        return rounds, statistics.median(samples)

    def triplet_median(rounds):
        diffs = []
        for seq in rounds:
            for (m0, t0), (m1, t1), (m2, t2) in zip(seq, seq[1:], seq[2:]):
                if m0 == m2 != m1:
                    d = t1 - (t0 + t2) / 2.0
                    diffs.append(d if m1 else -d)
        return statistics.median(diffs), diffs

    # -- part 2: end-to-end A/B + A/A floor ----------------------------
    aa_rounds, base_step_ms = run_rounds(ab=False)
    noise_floor_ms, aa_diffs = triplet_median(aa_rounds)
    noise_floor_ms = abs(noise_floor_ms)
    ab_rounds, _ = run_rounds(ab=True)
    overhead_ms, ab_diffs = triplet_median(ab_rounds)

    # -- part 1: direct cost of one traced step's instrument ops -------
    m = ServingMetrics(MetricsRegistry())
    clock = time.monotonic
    N = 20000
    t0 = time.perf_counter()
    for i in range(N):
        t_step = clock()                       # the step() t0 read
        m.step_ms.observe(4.0)
        m.set_health("ready")
        m.queue_depth.set(0.0)
        m.active_requests.set(4.0)
        m.kv_occupancy.set(0.5)
        for _ in range(4):                     # 4 decoding requests
            now = clock()
            m.tpot_ms.observe(now - t_step + 4.0)
        _ = None is not None                   # profile-trigger nil check
    ops_ms = (time.perf_counter() - t0) / N * 1e3

    budget_ms = 0.02 * base_step_ms
    ok_ops = ops_ms <= max(budget_ms, 0.05)
    # the op microbench enforces the 2% budget deterministically; the e2e
    # bound is the tripwire for a BIG hidden regression (an accidental
    # device sync costs >= 1 ms/step here), so it gets an absolute 0.5 ms
    # allowance on top of the calibrated floor — a loaded CI worker's
    # residual noise (~0.1-0.4 ms observed) stays under it, a real sync
    # regression cannot
    ok_e2e = overhead_ms <= max(budget_ms + noise_floor_ms, 0.5)
    details = {"ms_per_step": round(base_step_ms, 4),
               "budget_ms": round(budget_ms, 4),
               "op_cost_ms_per_step": round(ops_ms, 5),
               "op_cost_pct": round(ops_ms / base_step_ms * 100, 3),
               "e2e_overhead_ms": round(overhead_ms, 4),
               "e2e_noise_floor_ms": round(noise_floor_ms, 4),
               "ok_ops": ok_ops, "ok_e2e": ok_e2e,
               "aa_triplet_diffs_ms": [round(d, 4) for d in aa_diffs],
               "ab_triplet_diffs_ms": [round(d, 4) for d in ab_diffs]}
    return ok_ops and ok_e2e, details


def scenario_tracing_overhead(workdir):
    """The event-bus budget proof, same shape as part 1 of
    overhead-budget: microbenchmark EXACTLY the bus operations one traced
    serving step performs (a step span B/E pair, the engine put span, two
    async request stamps, four call-site enabled-guards) against a
    measured median step time — must stay under 2 % (or the 50 µs
    timer-noise floor). Disabled cost is measured separately and must be
    ~0 (an attribute check + branch: < 1 µs for ALL of a step's guards),
    and the ring must hold its bound under a 10k-event storm."""
    from deepspeed_tpu.observability import (MetricsRegistry,
                                             configure_tracing, get_bus)

    # measured median step on this box (same loaded-batcher shape as the
    # overhead-budget scenario) — the denominator of the 2% budget
    b = _make_batcher(_make_engine(), MetricsRegistry(),
                      default_max_new_tokens=100)
    import numpy as np

    rng = np.random.default_rng(0)
    [b.submit(rng.integers(0, 250, 24)) for _ in range(4)]
    while b.manager.prefilling():
        b.step()
    samples = []
    for _ in range(24):
        t0 = time.perf_counter()
        b.step()
        samples.append((time.perf_counter() - t0) * 1e3)
    step_ms = statistics.median(samples)
    b.begin_drain("tracing overhead drill")
    b.drain(timeout_s=30.0)

    bus = get_bus()
    configure_tracing(enabled=True, ring_size=4096, sample=1,
                      dump_dir=workdir)
    N = 20000
    t0 = time.perf_counter()
    for i in range(N):
        # one traced serving step's bus work: the step span, the engine
        # put span nested inside it, one admit + one first-token stamp
        with bus.span("batcher", "step", args={"step": i}):
            with bus.span("engine", "put", args={"uids": [0, 1, 2, 3]}):
                pass
            bus.async_instant("request", "request", i,
                              args={"subsys": "serving", "what": "admit"})
            bus.async_instant("request", "request", i,
                              args={"subsys": "batcher",
                                    "what": "first_token"})
    enabled_ms = (time.perf_counter() - t0) / N * 1e3
    # ring boundedness under a 10k-event storm (satellite invariant)
    bus.clear()
    for i in range(10000):
        bus.instant("storm", "evt", args={"i": i})
    storm_len = len(bus._rings["storm"])
    configure_tracing(enabled=False)
    bus.clear()
    t0 = time.perf_counter()
    for i in range(N):
        if bus.enabled:            # the per-site guard, 4x per step
            raise AssertionError
        if bus.enabled:
            raise AssertionError
        if bus.enabled:
            raise AssertionError
        if bus.enabled:
            raise AssertionError
    disabled_ms = (time.perf_counter() - t0) / N * 1e3
    disabled_events = bus.total_events()

    budget_ms = 0.02 * step_ms
    ok_enabled = enabled_ms <= max(budget_ms, 0.05)
    ok_disabled = disabled_ms <= 0.001 and disabled_events == 0
    details = {"ms_per_step": round(step_ms, 4),
               "budget_ms": round(budget_ms, 4),
               "enabled_cost_ms_per_step": round(enabled_ms, 5),
               "enabled_cost_pct": round(enabled_ms / step_ms * 100, 3),
               "disabled_cost_ms_per_step": round(disabled_ms, 6),
               "disabled_events": disabled_events,
               "storm_ring_len": storm_len, "ring_size": 4096,
               "ok_enabled": ok_enabled, "ok_disabled": ok_disabled}
    return ok_enabled and ok_disabled and storm_len == 4096, details


SCENARIOS = {
    "metrics-under-load": scenario_metrics_under_load,
    "profile-capture": scenario_profile_capture,
    "overhead-budget": scenario_overhead_budget,
    "tracing-overhead": scenario_tracing_overhead,
}


def run_scenario(name: str, workdir=None) -> dict:
    """Run one drill; returns the verdict record (also usable from tests)."""
    if name not in SCENARIOS:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {sorted(SCENARIOS)})")
    t0 = time.time()
    ok, details = SCENARIOS[name](workdir or ".")
    return {"scenario": name, "ok": ok,
            "seconds": round(time.time() - t0, 2), "details": details}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--workdir", default=".", help="scratch directory")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name, workdir=args.workdir)
        print(json.dumps(verdict, indent=2, default=str))
        if not verdict["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
