#!/usr/bin/env python
"""dslint CLI entry point.

Usage:
    python tools/dslint.py deepspeed_tpu/              # full run vs baseline
    python tools/dslint.py --changed                   # pre-commit mode
    python tools/dslint.py --json --no-baseline ...    # everything, for triage

The analyzer lives in the ``tools/dslint/`` package; this wrapper only
makes ``python tools/dslint.py`` work from anywhere by putting its own
directory on sys.path first.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from dslint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
