#!/usr/bin/env python
"""Elastic-fleet chaos drill CLI: drive the replica-lifecycle layer
(``deepspeed_tpu/serving/fleet.py`` + ``coldstart.py``) through crash,
burst, and weight-swap scenarios and exit nonzero if the elasticity
invariants fail — the fleet face of ``tools/serve_drill.py``.

Invariants asserted after EVERY drill:

* **no request silently lost** — every uid the ROUTER admitted resolves
  terminal (``completed | shed | expired``) at the pool level, across
  replica crashes, scale-downs, and rolling swaps (crash-severed in-flight
  requests resolve as loud ``replica_crash`` sheds, never vanish);
* **no KV-block leak** — every replica left in the pool returns its block
  pool to the fully-free state once the storm quiesces;
* **no shared-tier leak** — the fleet's replicas share one durable NVMe
  namespace (cross-replica migration on); at drill exit it must be EMPTY
  (every resume manifest and durable KV file reclaimed with its
  request), and the namespace is removed exception-safely even when an
  assertion fails mid-drill;
* scenario-specific checks (the crash actually produced a flight-recorder
  dump, the autoscaler actually grew and shrank the pool, the rolling
  swap actually bumped every incarnation while honoring the READY floor,
  the warm start actually beat the cold start by the required margin).

    python tools/elastic_drill.py --list
    python tools/elastic_drill.py --scenario replica-crash-mid-storm
    python tools/elastic_drill.py --scenario burst-autoscale
    python tools/elastic_drill.py --scenario rolling-swap
    python tools/elastic_drill.py --scenario cold-start-bench
    python tools/elastic_drill.py --all

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
Scenarios that measure (cold/warm start, drain->rejoin) append a
``bench_elastic`` entry to the perf ledger (``tools/bench_ledger.py``),
gated by ``tools/bench_trend.py`` on the higher-is-better restatements
(``warm_speedup``, ``rejoin_per_sec``). Slow pytest wrappers live in
``tests/unit/test_fleet.py`` under the ``elastic`` + ``slow`` markers.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TERMINAL = ("completed", "shed", "expired")


def _fresh_injector():
    from deepspeed_tpu.resilience import set_injector

    set_injector(None)


def _reset_tracing():
    from deepspeed_tpu.observability import configure_tracing, get_bus

    configure_tracing(enabled=False)
    get_bus().clear()


def _make_fleet(n, workdir, fleet_kw=None, serving_kw=None, cache=None):
    """A WarmStartCache-backed pool of ``n`` replicas + its controller.

    Every replica (initial, respawn, scale-up, swap) is built through the
    SAME cache/factory the controller uses, so the first build is the only
    cold one and the drill exercises the real respawn path end to end.
    """
    from deepspeed_tpu.config.config import FleetConfig, ServingConfig
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.serving import (ContinuousBatcher, FleetController,
                                       Replica, ReplicaRouter, WarmStartCache,
                                       warm_key)

    cache = cache or WarmStartCache(os.path.join(workdir, "warm"))
    key = warm_key(TransformerLM(get_preset("tiny")))
    engine_kw = dict(max_sequences=8, max_seq_len=128, block_size=16)
    # every replica (initial, respawn, scale-up, swap) shares ONE durable
    # NVMe namespace: crash-severed in-flight requests re-home onto
    # siblings through it, and the drill asserts it is empty at exit
    shared = os.path.join(workdir, "shared-nvme")
    os.makedirs(shared, exist_ok=True)
    scfg = ServingConfig(**{"prefill_chunk": 32, "default_max_new_tokens": 8,
                            "migration": {"enabled": True,
                                          "shared_nvme_path": shared,
                                          "manifest_ttl_s": 300.0},
                            **(serving_kw or {})})

    def make_replica(name):
        eng, info = cache.build_engine(
            key, lambda: TransformerLM(get_preset("tiny")),
            engine_kw=engine_kw)
        rep = Replica(name, ContinuousBatcher(eng, scfg))
        rep.start_info = info
        return rep

    router = ReplicaRouter([make_replica(f"r{i}") for i in range(n)]).start()
    fc = FleetController(router, make_replica,
                         FleetConfig(**{"respawn_backoff_s": 0.0,
                                        **(fleet_kw or {})}))
    return router, fc, cache, make_replica


def _await_terminal(router, uids, timeout_s=90.0):
    """Pool-level 'no request silently lost': wait for every admitted uid
    to reach a terminal state; returns {uid: state} for stragglers."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        states = {u: router.resolve(u) for u in uids}
        if all(s in TERMINAL for s in states.values()):
            return {}
        time.sleep(0.05)
    return {u: s for u, s in states.items() if s not in TERMINAL}


def _pool_invariants(router, uids, timeout_s=90.0) -> dict:
    """The cross-scenario elasticity invariants (see module doc)."""
    unresolved = _await_terminal(router, uids, timeout_s)
    # quiesce, then every live replica's KV pool must be fully free
    pools = {}
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        pools = {}
        for rep in router._snapshot():
            alloc = rep.batcher.engine.state.allocator
            pools[rep.name] = {"free": alloc.free_blocks,
                               "total": alloc.num_blocks,
                               "restored": (alloc.free_blocks
                                            == alloc.num_blocks)}
        if all(p["restored"] for p in pools.values()):
            break
        time.sleep(0.05)
    counts = {}
    for u in uids:
        s = router.resolve(u)
        counts[s] = counts.get(s, 0) + 1
    return {
        "admitted": len(uids), "terminal_counts": counts,
        "unresolved_uids": unresolved, "kv_pools": pools,
        "ok": (not unresolved
               and all(p["restored"] for p in pools.values())),
    }


def _storm(router, count, max_new_tokens=8, deadline_s=None):
    """Submit ``count`` requests; ShedError rejections are LOUD
    backpressure, not lost requests — returned separately."""
    from deepspeed_tpu.serving import ShedError

    uids, rejected = [], 0
    for i in range(count):
        try:
            uids.append(router.submit([1 + i % 7, 2, 3],
                                      max_new_tokens=max_new_tokens,
                                      deadline_s=deadline_s))
        except ShedError:
            rejected += 1
    return uids, rejected


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------
def scenario_replica_crash_mid_storm(workdir):
    """Kill one replica's worker mid-storm: queued requests fail over to
    the sibling, in-flight ones shed LOUDLY, the flight recorder dumps,
    the controller respawns under the same name (warm start) and the
    respawned replica serves again — zero admitted uids lost."""
    from deepspeed_tpu.observability import configure_tracing
    from deepspeed_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                                 set_injector)

    dump_dir = os.path.join(workdir, "flight")
    configure_tracing(enabled=True, ring_size=4096, sample=1,
                      dump_dir=dump_dir)
    router, fc, cache, _ = _make_fleet(
        2, workdir, fleet_kw={"heartbeat_timeout_s": 30.0})
    try:
        uids, rejected = _storm(router, 32)
        set_injector(FaultInjector(
            [FaultSpec(kind="replica_crash", site="r0")]))
        t0 = time.monotonic()
        while router.replicas["r0"].alive and time.monotonic() - t0 < 15:
            time.sleep(0.01)
        crashed = not router.replicas["r0"].alive
        set_injector(None)
        actions = fc.poll()
        respawned = bool(actions["recovered"]
                         and actions["recovered"][0]["respawned"])
        inv = _pool_invariants(router, uids)
        # the respawned incarnation must take NEW traffic
        post_uid = router.submit([9, 8, 7], max_new_tokens=4)
        post_state = _await_terminal(router, [post_uid], 30.0)
        dumps = glob.glob(os.path.join(dump_dir, "flight_replica_crash_*"))
        details = {
            "crashed": crashed, "respawned": respawned,
            "recovered": actions["recovered"], "rejected": rejected,
            "incarnation": router.replicas["r0"].incarnation,
            "respawn_source": getattr(router.replicas["r0"], "start_info",
                                      None),
            "crash_failovers": router.counters["crash_failovers"],
            "readmits": router.counters["readmits"],
            "flight_dumps": [os.path.basename(p) for p in dumps],
            "post_respawn_completed": not post_state,
            "invariants": inv,
        }
        ok = (crashed and respawned and inv["ok"] and len(dumps) == 1
              and router.counters["crash_failovers"] == 1
              and router.counters["readmits"] == 1
              and not post_state)
        return ok, details
    finally:
        router.close()
        fc.close()
        _reset_tracing()


def scenario_burst_autoscale(workdir):
    """A queue burst grows the pool (hysteresis: two pressured polls),
    the post-burst idle shrinks it back to ``min_replicas`` — every
    admitted uid terminal through both transitions."""
    router, fc, cache, _ = _make_fleet(
        1, workdir,
        fleet_kw={"min_replicas": 1, "max_replicas": 3,
                  "scale_up_queue_per_replica": 2.0, "scale_up_polls": 2,
                  "scale_down_idle_polls": 3},
        serving_kw={"max_queue_depth": 128, "default_max_new_tokens": 16})
    try:
        uids, rejected = _storm(router, 48, max_new_tokens=16)
        polls = 0
        while fc.counters["scale_ups"] == 0 and polls < 20:
            fc.poll()
            polls += 1
            time.sleep(0.02)
        grew_to = len(router.replicas)
        inv = _pool_invariants(router, uids)
        # pool idle now: keep polling until the autoscaler shrinks back
        polls = 0
        while len(router.replicas) > 1 and polls < 30:
            fc.poll()
            polls += 1
            time.sleep(0.02)
        details = {
            "rejected": rejected, "grew_to": grew_to,
            "shrunk_to": len(router.replicas),
            "scale_ups": fc.counters["scale_ups"],
            "scale_downs": fc.counters["scale_downs"],
            "invariants": inv,
        }
        ok = (grew_to >= 2 and len(router.replicas) == 1
              and fc.counters["scale_ups"] >= 1
              and fc.counters["scale_downs"] >= 1 and inv["ok"])
        return ok, details
    finally:
        router.close()
        fc.close()


def scenario_rolling_swap(workdir):
    """Rolling weight swap under live traffic: every replica drained,
    rebuilt, READY-probed, and readmitted one at a time — incarnations
    all bump, the pool never drops below the READY floor, and no admitted
    uid (including ones submitted DURING the swap) is lost."""
    router, fc, cache, _ = _make_fleet(
        2, workdir, fleet_kw={"min_ready_floor": 1})
    try:
        before = {r.name: r.incarnation for r in router._snapshot()}
        uids, rejected = _storm(router, 16)
        live_uids, stop = [], threading.Event()

        def trickle():
            from deepspeed_tpu.serving import ShedError

            while not stop.is_set():
                try:
                    live_uids.append(router.submit([4, 5, 6],
                                                   max_new_tokens=4))
                except ShedError:
                    pass
                time.sleep(0.02)

        t = threading.Thread(target=trickle, daemon=True)
        t.start()
        try:
            res = fc.rolling_swap()
        finally:
            stop.set()
            t.join(timeout=5)
        after = {r.name: r.incarnation for r in router._snapshot()}
        inv = _pool_invariants(router, uids + live_uids)
        rejoin_ms = [r["drain_rejoin_ms"] for r in res["replicas"]
                     if r.get("swapped")]
        details = {
            "swap": res, "incarnations_before": before,
            "incarnations_after": after, "rejected": rejected,
            "during_swap_submitted": len(live_uids),
            "readmits": router.counters["readmits"],
            "invariants": inv,
            "bench": {"drain_rejoin_ms": (max(rejoin_ms)
                                          if rejoin_ms else None),
                      "rejoin_per_sec": (1000.0 / max(rejoin_ms)
                                         if rejoin_ms else None)},
        }
        ok = (res["ok"] and inv["ok"]
              and all(after[n] > before[n] for n in before)
              and router.counters["readmits"] == len(before)
              and len(live_uids) > 0)
        return ok, details
    finally:
        router.close()
        fc.close()


def scenario_cold_start_bench(workdir):
    """Fast cold start measured: the first engine build (compile + init)
    is cold; a respawn through the WarmStartCache (AIO-streamed weights +
    reused executables) must be >= 3x faster and produce a replica that
    serves. An injected ``weight_load_io_error`` mid-path falls back to a
    cold build instead of failing the respawn."""
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                                 set_injector)
    from deepspeed_tpu.serving import warm_key
    from deepspeed_tpu.serving.coldstart import evict_module

    # measure a GENUINE cold build even when an earlier scenario in this
    # process already compiled the tiny model (the module table is
    # process-global by design)
    evict_module(warm_key(TransformerLM(get_preset("tiny"))))
    router, fc, cache, make_replica = _make_fleet(1, workdir)
    try:
        cold_ms = router.replicas["r0"].start_info["ms"]
        cold_src = router.replicas["r0"].start_info["source"]
        # warm respawn through the full controller path
        rep = fc._spawn("warm0")
        warm_ms = rep.start_info["ms"]
        warm_src = rep.start_info["source"]
        uid = rep.submit([1, 2, 3], max_new_tokens=4)
        t0 = time.monotonic()
        while (rep.resolve(uid) not in TERMINAL
               and time.monotonic() - t0 < 30):
            time.sleep(0.02)
        warm_served = rep.resolve(uid) == "completed"
        rep.close()
        # injected IO failure in the warm weight path -> cold fallback
        set_injector(FaultInjector(
            [FaultSpec(kind="weight_load_io_error", site="warm")]))
        rep2 = fc._spawn("fb0")
        fb_src = rep2.start_info["source"]
        rep2.close()
        set_injector(None)
        speedup = cold_ms / max(warm_ms, 1e-6)
        details = {
            "cold_start_ms": cold_ms, "cold_source": cold_src,
            "warm_start_ms": warm_ms, "warm_source": warm_src,
            "warm_speedup": round(speedup, 1),
            "warm_served": warm_served,
            "io_error_fallback_source": fb_src,
            "cache": cache.report(),
            "bench": {"cold_start_ms": cold_ms, "warm_start_ms": warm_ms,
                      "warm_speedup": round(speedup, 2)},
        }
        ok = (cold_src == "cold" and warm_src == "warm" and warm_served
              and speedup >= 3.0 and fb_src == "cold"
              and cache.counters["warm_load_failures"] >= 1)
        return ok, details
    finally:
        router.close()
        fc.close()


SCENARIOS = {
    "replica-crash-mid-storm": scenario_replica_crash_mid_storm,
    "burst-autoscale": scenario_burst_autoscale,
    "rolling-swap": scenario_rolling_swap,
    "cold-start-bench": scenario_cold_start_bench,
}


def _shared_tier_leftovers(workdir) -> list:
    """Files still alive under the fleet's shared NVMe namespace — the
    drill-exit invariant is an EMPTY shared tier (every resume manifest
    and durable KV file reclaimed with its request)."""
    base = os.path.join(workdir, "shared-nvme")
    out = []
    for root, _dirs, files in os.walk(base):
        out.extend(os.path.join(os.path.relpath(root, base), f)
                   for f in files)
    return sorted(out)


def run_scenario(name: str, workdir=None) -> dict:
    """Run one drill; returns the verdict record (also usable from
    tests). Each scenario gets a throwaway workdir unless given one."""
    if name not in SCENARIOS:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {sorted(SCENARIOS)})")
    _fresh_injector()
    t0 = time.time()
    owned = workdir is None
    if owned:
        workdir = tempfile.mkdtemp(prefix=f"elastic_{name}_")
    try:
        ok, details = SCENARIOS[name](workdir)
        leftovers = _shared_tier_leftovers(workdir)
        details["shared_tier_leftovers"] = leftovers
        ok = ok and not leftovers
    finally:
        _fresh_injector()
        # exception-safe teardown (mirrors the kv-tier drill's rmtree
        # fix): an assertion failure mid-drill must not leave the
        # spawned replicas' shared NVMe namespace behind
        shutil.rmtree(os.path.join(workdir, "shared-nvme"),
                      ignore_errors=True)
        if owned:
            shutil.rmtree(workdir, ignore_errors=True)
    return {"scenario": name, "ok": ok,
            "seconds": round(time.time() - t0, 2), "details": details}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--no-ledger", action="store_true",
                    help="skip the bench_elastic perf-ledger append")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    bench = {}
    for name in names:
        verdict = run_scenario(name)
        print(json.dumps(verdict, indent=2, default=str))
        if not verdict["ok"]:
            rc = 1
        for k, v in (verdict["details"].get("bench") or {}).items():
            if v is not None:
                bench[k] = v
    if bench and rc == 0 and not args.no_ledger:
        from bench_ledger import append_ledger

        result = {"metric": "warm_speedup",
                  "value": bench.get("warm_speedup"), "unit": "x", **bench}
        path = append_ledger(result, "bench_elastic")
        print(json.dumps({"ledger": path, "bench_elastic": bench}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
