"""dslint core: source loading, AST plumbing, and the Finding model.

The suite is stdlib-only (``ast`` + ``tokenize``) on purpose: it runs as a
tier-1-collected test on every CI pass, so it must import nothing the
container may lack and finish in seconds over the whole package.

Checkers are small classes with two hooks:

* ``check_file(sf)``  — per-file findings (most rules);
* ``finish()``        — cross-file findings after every file was visited
  (the lock-order graph is the only current user).

Findings are keyed for baseline matching by a *line-number-free*
fingerprint — ``path::rule::qualname::normalized-snippet`` — so an edit
elsewhere in a file does not invalidate the checked-in baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

#: structured-comment annotations the lock checker understands
GUARDED_BY_RE = re.compile(r"#:\s*guarded_by:\s*(\w+)")
HOLDS_RE = re.compile(r"#:\s*holds:\s*(\w+)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str           # repo-relative, posix separators
    line: int
    col: int
    func: str           # enclosing qualname, or "<module>"
    message: str
    snippet: str        # stripped source line

    @property
    def fingerprint(self) -> str:
        return "::".join((self.path, self.rule, self.func,
                          normalize_snippet(self.snippet)))

    def to_json(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "func": self.func, "message": self.message,
                "snippet": self.snippet, "fingerprint": self.fingerprint}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}\n    in {self.func}: {self.snippet}")


def normalize_snippet(snippet: str) -> str:
    return " ".join(snippet.split())


class SourceFile:
    """One parsed module: AST with parent links, raw lines, per-line
    comments (via ``tokenize`` so ``#`` inside strings never confuses the
    annotation scan)."""

    def __init__(self, path: str, display_path: str, text: str):
        self.path = path
        self.display_path = display_path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:   # unterminated multiline at EOF etc.
            pass

    # ------------------------------------------------------------------
    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def comment(self, lineno: int) -> str:
        return self.comments.get(lineno, "")

    def iter_parents(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def qualname(self, node: ast.AST) -> str:
        """Qualified name of the innermost function enclosing ``node``
        (``Class.method`` / ``outer.<locals>.inner``), or ``<module>``."""
        names: List[str] = []
        chain = [node] + list(self.iter_parents(node))
        for anc in chain:
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(anc.name)
        if not names:
            return "<module>"
        return ".".join(reversed(names))

    def enclosing_function(self, node: ast.AST
                           ) -> Optional[ast.FunctionDef]:
        for anc in self.iter_parents(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for anc in self.iter_parents(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.display_path, line=lineno,
                       col=col, func=self.qualname(node), message=message,
                       snippet=self.line(lineno))


# ----------------------------------------------------------------------
# shared AST helpers
# ----------------------------------------------------------------------
def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted source text of a Name/Attribute chain
    (``jax.numpy.asarray`` → that string; anything else → "")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def is_jit_callable(node: ast.AST) -> bool:
    """True for expressions that name ``jax.jit`` (or a bare ``jit`` /
    ``api.jit`` import alias)."""
    name = dotted_name(node)
    return name == "jit" or name.endswith(".jit")


def is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` — including ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    if is_jit_callable(node.func):
        return True
    fname = dotted_name(node.func)
    if fname in ("partial", "functools.partial") and node.args:
        return is_jit_callable(node.args[0])
    return False


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------
def collect_py_files(paths: Iterable[str], root: str) -> List[Tuple[str, str]]:
    """Expand files/directories into (abs_path, display_path) pairs.
    display paths are relative to ``root`` when possible (stable baseline
    keys regardless of invocation cwd)."""
    out: List[Tuple[str, str]] = []
    seen = set()
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif ap.endswith(".py") and os.path.exists(ap):
            out.append(ap)
    pairs = []
    for ap in out:
        if ap in seen:
            continue
        seen.add(ap)
        try:
            rel = os.path.relpath(ap, root)
        except ValueError:
            rel = ap
        disp = rel if not rel.startswith("..") else ap
        pairs.append((ap, disp.replace(os.sep, "/")))
    return pairs


def run_checkers(pairs: List[Tuple[str, str]], checkers) -> List[Finding]:
    findings: List[Finding] = []
    for ap, disp in pairs:
        try:
            with open(ap, "r", encoding="utf-8") as f:
                text = f.read()
            sf = SourceFile(ap, disp, text)
        except SyntaxError as e:
            findings.append(Finding(
                rule="parse-error", path=disp, line=e.lineno or 1,
                col=e.offset or 0, func="<module>",
                message=f"file does not parse: {e.msg}", snippet=""))
            continue
        except OSError as e:
            findings.append(Finding(
                rule="parse-error", path=disp, line=1, col=0,
                func="<module>", message=f"cannot read file: {e}",
                snippet=""))
            continue
        for checker in checkers:
            findings.extend(checker.check_file(sf))
    for checker in checkers:
        findings.extend(checker.finish())
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
