"""resource-lifecycle checker.

The PR 9/10 leak class: a pooled/refcounted resource is acquired, some
fallible work happens, and only then is ownership handed off or the
resource released — so any exception in between leaks it (pool buffer
never returned, refcount never decremented, lock never released).

A call is *acquire-like* when the method name is ``allocate``, ``acquire``
or ``incref``, or the method is ``get`` on a receiver whose spelling
contains ``pool`` (``self.pool.get(n)`` — but not ``dict.get`` /
``queue.get``). The site is clean when any of these hold:

* it is the context expression of a ``with`` (contextmanager owns release);
* it is lexically inside a ``try`` whose ``finally`` or ``except`` bodies
  call a release-like method (``free``/``release``/``decref``/``put``/
  ``close``/``abort``) — the exception path restores the resource;
* the acquire's statement is a ``return``/immediately returned — ownership
  transfers before anything can raise;
* nothing that can raise follows it in the function (no later calls,
  subscripts or attribute loads before every release/handoff — approximated
  as: no further statements containing a Call in the same function body).

Everything else is a finding. Legitimate hand-off patterns (builder
functions where the very construction of the owner can't raise) belong in
the baseline with a justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, SourceFile, dotted_name

RULE = "resource-lifecycle"

ACQUIRE_METHODS = {"allocate", "acquire", "incref"}
POOL_GET_RECV_HINT = "pool"
RELEASE_METHODS = {"free", "release", "decref", "put", "close", "abort",
                   "put_nowait"}


def _is_acquire(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    meth = call.func.attr
    recv = dotted_name(call.func.value).lower()
    if meth in ACQUIRE_METHODS:
        return True
    if meth == "get" and POOL_GET_RECV_HINT in recv:
        return True
    return False


_RELEASE_HINTS = ("release", "abort", "reclaim", "cleanup", "decref",
                  "free")


def _contains_release(nodes: Iterable[ast.stmt]) -> bool:
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in RELEASE_METHODS \
                        or any(h in attr.lower() for h in _RELEASE_HINTS):
                    return True
    return False


class ResourceLifecycleChecker:
    rule = RULE

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_acquire(node)):
                continue
            meth = node.func.attr  # type: ignore[union-attr]
            # (a) `with recv.acquire(...) as x:` — manager owns release
            parent = sf.parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            # direct `return recv.acquire(...)` — ownership transfers
            if isinstance(parent, ast.Return):
                continue
            # (b) protected by a try whose finally/except releases
            protected = False
            for anc in sf.iter_parents(node):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break
                if isinstance(anc, ast.Try):
                    cleanup: List[ast.stmt] = list(anc.finalbody)
                    for h in anc.handlers:
                        cleanup.extend(h.body)
                    if _contains_release(cleanup):
                        protected = True
                        break
            if protected:
                continue
            # (c)/(d): walk the remaining top-level statements of the
            # function in order. Call-free statements (index math, guards
            # with early returns) cannot raise and are skipped; the first
            # statement that CAN raise decides: a Try whose except/finally
            # releases is the protected-handoff idiom (clean), a release
            # call itself is clean, anything else means an exception there
            # leaks the resource.
            fn = sf.enclosing_function(node)
            body = (fn.body if fn is not None
                    else getattr(sf.tree, "body", []))
            later = [s for s in body
                     if getattr(s, "lineno", 0) > node.lineno]
            risky = False
            for s in later:
                if isinstance(s, ast.Return):
                    break            # ownership transfers to the caller
                if isinstance(s, ast.Try):
                    cleanup = list(s.finalbody)
                    for h in s.handlers:
                        cleanup.extend(h.body)
                    if _contains_release(cleanup):
                        break        # acquire; try: handoff except: release
                    risky = True
                    break
                if _contains_release([s]):
                    break            # released before anything fallible
                if any(isinstance(sub, ast.Call) for sub in ast.walk(s)):
                    risky = True
                    break
            if not risky:
                continue
            out.append(sf.finding(
                self.rule, node,
                f"'{meth}' result can leak: fallible work follows before "
                f"release/handoff and no try/finally (or with-statement) "
                f"releases it on the exception path"))
        return out

    def finish(self) -> Iterable[Finding]:
        return []
