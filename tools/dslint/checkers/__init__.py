"""Checker registry: rule name -> checker factory."""

from .control_flow import ControlFlowChecker
from .event_span import EventSpanChecker
from .host_sync import HostSyncChecker
from .lifecycle import ResourceLifecycleChecker
from .locks import LockDisciplineChecker
from .recompile import RecompileHazardChecker

ALL_CHECKERS = {
    "host-sync": HostSyncChecker,
    "lock-discipline": LockDisciplineChecker,
    "resource-lifecycle": ResourceLifecycleChecker,
    "recompile-hazard": RecompileHazardChecker,
    "control-flow": ControlFlowChecker,
    "event-span": EventSpanChecker,
}

RULE_HELP = {
    "host-sync": ("device→host syncs (.item(), np.asarray, device_get, "
                  "block_until_ready, float/int on traced values) inside "
                  "@jax.jit functions and configured hot step paths"),
    "lock-discipline": ("'#: guarded_by: <lock>' attribute accesses "
                        "outside 'with self.<lock>:', plus a cross-file "
                        "lock acquisition-order graph"),
    "resource-lifecycle": ("allocate/acquire/incref/pool-get call sites "
                           "that leak on exception paths (no try/finally, "
                           "with, or immediate handoff)"),
    "recompile-hazard": ("jax.jit created per call / in loops, and "
                         "unhashable literals in static arg positions"),
    "control-flow": ("unconditional self-recursion with identical "
                     "arguments; bare/BaseException handlers swallowing "
                     "interrupts inside worker loops"),
    "event-span": ("bus begin()/async_begin()/emit('B'|'b') sites where "
                   "fallible work follows with no try/finally, "
                   "bus.span(...) with-block, or pre-risk end — an "
                   "exception exports an unclosed span"),
}

__all__ = ["ALL_CHECKERS", "RULE_HELP"]
