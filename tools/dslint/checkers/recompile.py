"""recompile-hazard checker.

``jax.jit`` caches compiled executables on the *identity* of the wrapped
callable plus hashes of static arguments. Three patterns silently defeat
the cache or blow up at call time:

* **jit-and-call** — ``jax.jit(f)(x)``: the wrapper is created, used once
  and dropped; every execution re-traces and re-compiles.
* **jit-in-loop** — ``for ...: g = jax.jit(f)``: a fresh wrapper (fresh
  cache) per iteration, even when ``f`` is loop-invariant.
* **unhashable static** — a callable jitted with ``static_argnums``/
  ``static_argnames`` that is later called (same scope) with a ``list``/
  ``dict``/``set`` display in a static position: ``TypeError: unhashable``
  at best, a per-call recompile via a workaround wrapper at worst.

A jit whose result is bound to ``self.<attr>`` inside ``__init__`` (or any
method — memoized on the instance) is the idiomatic fix and is never
flagged by the loop rule unless the binding really is per-iteration.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..core import Finding, SourceFile, dotted_name, is_jit_call

RULE = "recompile-hazard"

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


def _static_spec(call: ast.Call) -> Optional[Tuple[Tuple[int, ...],
                                                   Tuple[str, ...]]]:
    """(static positions, static names) from a jax.jit(...) call, or None
    when not statically resolvable."""
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = (v.elts if isinstance(v, (ast.Tuple, ast.List))
                    else [v])
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.append(e.value)
                else:
                    return None
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = (v.elts if isinstance(v, (ast.Tuple, ast.List))
                    else [v])
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.append(e.value)
                else:
                    return None
    if not nums and not names:
        return None
    return tuple(nums), tuple(names)


class RecompileHazardChecker:
    rule = RULE

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        # name -> static spec, for jitted callables bound in this file
        jitted_static: Dict[str, Tuple[Tuple[int, ...],
                                       Tuple[str, ...]]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and is_jit_call(node):
                # jit-and-call: jax.jit(f)(x)
                parent = sf.parents.get(node)
                if isinstance(parent, ast.Call) and parent.func is node:
                    out.append(sf.finding(
                        self.rule, node,
                        "jax.jit(...) created and invoked in one "
                        "expression: the compiled wrapper is dropped "
                        "after the call, so EVERY call re-traces and "
                        "re-compiles — bind the jitted callable once and "
                        "reuse it"))
                # jit-in-loop
                for anc in sf.iter_parents(node):
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                        break
                    if isinstance(anc, (ast.For, ast.While, ast.comprehension)):
                        out.append(sf.finding(
                            self.rule, node,
                            "jax.jit(...) inside a loop builds a fresh "
                            "wrapper (fresh compile cache) per iteration "
                            "— hoist it out of the loop"))
                        break
                # comprehension bodies: ListComp/GeneratorExp ancestors
                for anc in sf.iter_parents(node):
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        break
                    if isinstance(anc, (ast.ListComp, ast.SetComp,
                                        ast.DictComp, ast.GeneratorExp)):
                        out.append(sf.finding(
                            self.rule, node,
                            "jax.jit(...) inside a comprehension builds a "
                            "fresh wrapper per element — hoist it out"))
                        break
                # record static specs for call-site hashability checks
                spec = _static_spec(node)
                if spec is not None:
                    parent = sf.parents.get(node)
                    if isinstance(parent, ast.Assign):
                        for tgt in parent.targets:
                            if isinstance(tgt, ast.Name):
                                jitted_static[tgt.id] = spec
                            elif isinstance(tgt, ast.Attribute):
                                jitted_static[dotted_name(tgt)] = spec
        if jitted_static:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                spec = jitted_static.get(fname)
                if spec is None:
                    continue
                nums, names = spec
                for i in nums:
                    if i < len(node.args) and isinstance(
                            node.args[i], _UNHASHABLE):
                        out.append(sf.finding(
                            self.rule, node.args[i],
                            f"unhashable literal passed in static arg "
                            f"position {i} of jitted '{fname}' "
                            f"(static args are hashed for the compile "
                            f"cache — pass a tuple or hashable config)"))
                for kw in node.keywords:
                    if kw.arg in names and isinstance(kw.value,
                                                      _UNHASHABLE):
                        out.append(sf.finding(
                            self.rule, kw.value,
                            f"unhashable literal passed for static arg "
                            f"'{kw.arg}' of jitted '{fname}'"))
        return out

    def finish(self) -> Iterable[Finding]:
        return []
