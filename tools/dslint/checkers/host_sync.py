"""host-sync / trace-purity checker.

Two kinds of context get scanned:

* **jit contexts** — function defs decorated with ``@jax.jit`` (directly or
  via ``partial(jax.jit, ...)``), defs/methods referenced by a
  ``jax.jit(<name>)`` call anywhere in the same file, and lambdas passed
  straight into ``jax.jit``. Host-materialization there either breaks the
  trace or silently constant-folds a tracer.
* **hot paths** — a configurable list of (path-suffix, qualname) step-loop
  functions where a host sync is *legal* but each one stalls the dispatch
  queue; every sync must be deliberate (baseline it with a justification).

Flagged inside both: ``.item()``, ``.tolist()``, ``.block_until_ready()``,
``jax.block_until_ready``, ``jax.device_get``, ``np.asarray``/``np.array``.
Inside jit contexts additionally ``float()/int()/bool()`` on non-constant
arguments (host round-trip on a traced value).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..core import (Finding, SourceFile, call_name, dotted_name,
                    is_jit_call, is_jit_callable)

RULE = "host-sync"

#: (path suffix, qualname) pairs whose bodies are step-loop hot paths.
DEFAULT_HOT_PATHS: Tuple[Tuple[str, str], ...] = (
    ("runtime/engine.py", "DeepSpeedTpuEngine.step"),
    ("inference/engine_v2.py", "InferenceEngineV2.decode_batch"),
    ("serving/batcher.py", "ContinuousBatcher.step"),
    ("offload/optimizer.py", "HostOffloadOptimizer._run_adam"),
    ("offload/optimizer.py", "HostOffloadOptimizer._run_adam_pipelined"),
)

#: attribute calls that force a device→host sync wherever they appear
SYNC_ATTRS = {"item", "tolist", "block_until_ready"}

#: dotted callables that force a sync
SYNC_CALLS = {
    "jax.device_get", "jax.block_until_ready",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "np.frombuffer", "numpy.frombuffer",
}

CASTS = {"float", "int", "bool"}


def _is_static_expr(node: ast.expr) -> bool:
    """Expressions whose ``float()/int()`` is trace-safe: literals, len(),
    ``.shape`` / ``.ndim`` / ``.size`` reads, time.* reads."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name == "len" or name.startswith("time."):
            return True
        return False
    if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim",
                                                         "size"):
        return True
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


class HostSyncChecker:
    rule = RULE

    def __init__(self, hot_paths: Tuple[Tuple[str, str], ...] = None):
        self.hot_paths = (DEFAULT_HOT_PATHS if hot_paths is None
                          else tuple(hot_paths))

    # ------------------------------------------------------------------
    def _jit_contexts(self, sf: SourceFile) -> Set[ast.AST]:
        """Function defs / lambdas whose bodies run under a jax trace."""
        jitted: Set[ast.AST] = set()
        jit_target_names: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if is_jit_callable(dec) or is_jit_call(dec):
                        jitted.add(node)
            if isinstance(node, ast.Call) and is_jit_call(node):
                # jax.jit(target, ...) / partial(jax.jit, target, ...)
                args = node.args
                if dotted_name(node.func) in ("partial", "functools.partial"):
                    args = args[1:]
                for a in args[:1]:
                    if isinstance(a, ast.Lambda):
                        jitted.add(a)
                    elif isinstance(a, ast.Name):
                        jit_target_names.add(a.id)
                    elif isinstance(a, ast.Attribute):
                        jit_target_names.add(a.attr)
                    elif isinstance(a, ast.Call):
                        # jax.jit(vmap(f)) / jit(partial(f, ...)):
                        # the innermost named callable is what traces
                        for inner in a.args[:1]:
                            if isinstance(inner, ast.Name):
                                jit_target_names.add(inner.id)
                            elif isinstance(inner, ast.Lambda):
                                jitted.add(inner)
        if jit_target_names:
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node.name in jit_target_names:
                    jitted.add(node)
        return jitted

    def _hot_functions(self, sf: SourceFile) -> Set[ast.AST]:
        """Configured hot roots plus their same-file callee closure: the
        step loop's helpers (``self._x(...)`` / bare-name calls resolved in
        this file) are just as hot as the root that calls them."""
        hot: Set[ast.AST] = set()
        wanted = {q for suffix, q in self.hot_paths
                  if sf.display_path.endswith(suffix)}
        if not wanted:
            return hot
        defs: List[ast.AST] = [
            n for n in ast.walk(sf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        by_name: Dict[str, List[ast.AST]] = {}
        for node in defs:
            by_name.setdefault(node.name, []).append(node)
            cls = sf.enclosing_class(node)
            qual = f"{cls.name}.{node.name}" if cls else node.name
            if qual in wanted:
                hot.add(node)
        frontier = list(hot)
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                callee = None
                if isinstance(func, ast.Name):
                    callee = func.id
                elif isinstance(func, ast.Attribute) \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id in ("self", "cls"):
                    callee = func.attr
                if callee is None:
                    continue
                for target in by_name.get(callee, ()):
                    if target not in hot:
                        hot.add(target)
                        frontier.append(target)
        return hot

    # ------------------------------------------------------------------
    def _context_of(self, sf: SourceFile, node: ast.AST, contexts) -> bool:
        chain = [node] + list(sf.iter_parents(node))
        return any(anc in contexts for anc in chain)

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        jit_ctx = self._jit_contexts(sf)
        hot_ctx = self._hot_functions(sf)
        if not jit_ctx and not hot_ctx:
            return []
        out: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            in_jit = self._context_of(sf, node, jit_ctx)
            in_hot = (not in_jit
                      and self._context_of(sf, node, hot_ctx))
            if not in_jit and not in_hot:
                continue
            where = "jit-traced function" if in_jit else "hot step path"
            name = call_name(node)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SYNC_ATTRS \
                    and not name.startswith(("time.", "queue.")):
                out.append(sf.finding(
                    self.rule, node,
                    f".{node.func.attr}() forces a device→host sync "
                    f"inside a {where}"))
                continue
            if name in SYNC_CALLS:
                out.append(sf.finding(
                    self.rule, node,
                    f"{name}() materializes on host inside a {where}"))
                continue
            if in_jit and name in CASTS and node.args \
                    and not _is_static_expr(node.args[0]):
                out.append(sf.finding(
                    self.rule, node,
                    f"{name}() on a possibly-traced value inside a "
                    f"jit-traced function (concretization / host sync)"))
        return out

    def finish(self) -> Iterable[Finding]:
        return []
