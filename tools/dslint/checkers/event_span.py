"""event-span lifecycle checker.

The tracing-era sibling of the resource-lifecycle rule (PR 11): a
``begin()``-style event emit opens a duration (``B``) or async (``b``)
track on the bus, and the matching ``end()`` must land on EVERY exit
path, or the exported trace carries an unclosed span that the exporter
has to close synthetically — the timeline then shows a phantom
operation running until the export horizon, which is exactly the
misleading artifact an operator debugging a hang cannot afford. The
fix is ``bus.span(...)`` (a context manager whose ``with`` block IS the
``finally``) or an explicit ``try``/``finally`` around the fallible work.

A call is *begin-like* when it is:

* ``<recv>.begin(...)`` or ``<recv>.async_begin(...)`` where the receiver
  spelling names a bus (contains ``bus``, e.g. ``self._ebus``, ``bus``,
  ``get_bus()``); or
* ``<recv>.emit("B" | "b", ...)`` on such a receiver (the raw phase API).

The site is clean when any of these hold (the resource-lifecycle shapes):

* it is the context expression of a ``with`` (a span-like manager);
* it is lexically inside a ``try`` whose ``finally``/``except`` bodies
  contain an *end-like* call (``end``/``async_end``/``emit("E"|"e")``);
* the begin's function emits the end (or returns / hands off) before any
  statement that can raise — trailing emits (open-at-exit lifecycle
  handoffs, e.g. a ticket constructor opening the track its ``release``
  closes) are clean by construction.

Cross-function begin/end pairs (submit opens, terminal closes) are the
*intended* async idiom and are not flagged — the rule fires only when
fallible work follows the begin in the SAME function unprotected.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, SourceFile, dotted_name

RULE = "event-span"

BEGIN_METHODS = {"begin", "async_begin"}
END_METHODS = {"end", "async_end"}
BUS_HINT = "bus"
BEGIN_PHASES = {"B", "b"}
END_PHASES = {"E", "e"}


def _recv_is_bus(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = call.func.value
    name = dotted_name(recv).lower()
    if BUS_HINT in name:
        return True
    # get_bus().begin(...) — the receiver is a call, not a name chain
    if isinstance(recv, ast.Call):
        return BUS_HINT in dotted_name(recv.func).lower()
    return False


def _emit_phase(call: ast.Call) -> str:
    """The literal phase of an ``emit("X", ...)`` call, or ""."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return ""


def _is_begin(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute) or not _recv_is_bus(call):
        return False
    meth = call.func.attr
    if meth in BEGIN_METHODS:
        return True
    return meth == "emit" and _emit_phase(call) in BEGIN_PHASES


def _is_end_call(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    meth = node.func.attr
    if meth in END_METHODS:
        return True
    return meth == "emit" and _emit_phase(node) in END_PHASES


def _contains_end(nodes: Iterable[ast.stmt]) -> bool:
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and _is_end_call(node):
                return True
    return False


class EventSpanChecker:
    rule = RULE

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_begin(node)):
                continue
            meth = node.func.attr  # type: ignore[union-attr]
            parent = sf.parents.get(node)
            if isinstance(parent, (ast.withitem, ast.Return)):
                continue
            # protected by an enclosing try whose finally/except ends it
            protected = False
            for anc in sf.iter_parents(node):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break
                if isinstance(anc, ast.Try):
                    cleanup: List[ast.stmt] = list(anc.finalbody)
                    for h in anc.handlers:
                        cleanup.extend(h.body)
                    if _contains_end(cleanup):
                        protected = True
                        break
            if protected:
                continue
            # walk the statements that EXECUTE after the begin: the rest
            # of its enclosing block, then — when that block exhausts
            # undecided — the statements after the enclosing compound
            # statement, out to the function boundary (a begin nested in
            # `if self.tracing:` leaks just the same when fallible work
            # follows the guard). The first decisive statement wins:
            # Return = open-at-exit handoff (the async submit→terminal
            # idiom), a Try decides by whether its finally/except ends
            # the span, an end-call is clean, any other call is the leak.
            stmt = node
            while stmt is not None and not isinstance(stmt, ast.stmt):
                stmt = sf.parents.get(stmt)
            risky = False
            decided = False
            while stmt is not None and not decided:
                owner = sf.parents.get(stmt)
                if owner is None:
                    break
                block = None
                for _field, val in ast.iter_fields(owner):
                    if isinstance(val, list) and stmt in val:
                        block = val
                        break
                if block is not None:
                    for s in block[block.index(stmt) + 1:]:
                        if isinstance(s, ast.Return):
                            decided = True
                            break
                        if isinstance(s, ast.Try):
                            cleanup = list(s.finalbody)
                            for h in s.handlers:
                                cleanup.extend(h.body)
                            decided = True
                            risky = not _contains_end(cleanup)
                            break
                        if _contains_end([s]):
                            decided = True
                            break
                        if any(isinstance(sub, ast.Call)
                               for sub in ast.walk(s)):
                            decided = True
                            risky = True
                            break
                if decided:
                    break
                nxt = owner
                while nxt is not None and not isinstance(nxt, ast.stmt):
                    nxt = sf.parents.get(nxt)
                if nxt is None or isinstance(nxt, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef)):
                    break              # function end: open-at-exit handoff
                stmt = nxt
            if not risky:
                continue
            out.append(sf.finding(
                self.rule, node,
                f"'{meth}' opens an event span but fallible work follows "
                f"with no try/finally (or with bus.span(...)) closing it "
                f"on the exception path — an exception here exports an "
                f"unclosed span"))
        return out

    def finish(self) -> Iterable[Finding]:
        return []
