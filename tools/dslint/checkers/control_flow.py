"""control-flow trap checker.

**self-recursion** — a function that calls *itself* with exactly its own
parameter list, in order, with none of those parameters reassigned
anywhere in the body, on an unconditional path (nothing but plain
statements / ``try`` bodies / ``with`` bodies between the ``def`` and the
call). That is ``RecursionError`` by construction — the shape of the PR 7
``_cancel_quiet`` bug, where a delegation typo'd into the method itself.
Recursion guarded by an ``if``, inside a loop, in an ``except`` handler
(retry-on-error), or with any argument changed is NOT flagged.

**swallowed BaseException in worker loops** — a bare ``except:`` or
``except BaseException:`` handler without a ``raise``, lexically inside a
``while``/``for`` loop. A worker thread's run loop that swallows
``SystemExit``/``KeyboardInterrupt`` can never be shut down and hides
real faults as silent retries. ``except Exception:`` is fine (that is the
correct spelling); re-raising handlers are fine.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ..core import Finding, SourceFile

RULE = "control-flow"


def _param_names(fn: ast.FunctionDef) -> Optional[List[str]]:
    a = fn.args
    if a.vararg or a.kwarg or a.kwonlyargs or a.posonlyargs:
        return None  # exotic signatures: skip rather than guess
    names = [p.arg for p in a.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _reassigned(fn: ast.FunctionDef, names: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in names \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        if isinstance(node, (ast.AugAssign,)) \
                and isinstance(node.target, ast.Name) \
                and node.target.id in names:
            return True
    return False


class ControlFlowChecker:
    rule = RULE

    # ------------------------------------------------------------------
    def _self_recursion(self, sf: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in [n for n in ast.walk(sf.tree)
                   if isinstance(n, ast.FunctionDef)]:
            params = _param_names(fn)
            if params is None:
                continue
            pset = set(params)
            reassigned_checked = None
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                is_self_call = (
                    (isinstance(func, ast.Name) and func.id == fn.name)
                    or (isinstance(func, ast.Attribute)
                        and func.attr == fn.name
                        and isinstance(func.value, ast.Name)
                        and func.value.id in ("self", "cls")))
                if not is_self_call:
                    continue
                args = [a.id if isinstance(a, ast.Name) else None
                        for a in node.args]
                kwargs = {kw.arg: (kw.value.id
                                   if isinstance(kw.value, ast.Name)
                                   else None)
                          for kw in node.keywords}
                passed = args + [kwargs.get(p) for p in
                                 params[len(args):]]
                if len(passed) != len(params) \
                        or any(p != q for p, q in zip(passed, params)):
                    continue
                # identical arguments — is any of them ever reassigned?
                if reassigned_checked is None:
                    reassigned_checked = _reassigned(fn, pset)
                if pset and reassigned_checked:
                    continue
                # unconditional path check: every ancestor between the
                # call and the def must be pass-through control flow
                conditional = False
                for anc in sf.iter_parents(node):
                    if anc is fn:
                        break
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                        conditional = True   # nested def: different story
                        break
                    if isinstance(anc, (ast.If, ast.IfExp, ast.While,
                                        ast.For, ast.ExceptHandler,
                                        ast.Match, ast.BoolOp)):
                        conditional = True
                        break
                if conditional:
                    continue
                out.append(sf.finding(
                    self.rule, node,
                    f"'{fn.name}' unconditionally calls itself with its "
                    f"own unchanged arguments — infinite recursion "
                    f"(delegation typo?)"))
        return out

    # ------------------------------------------------------------------
    def _swallowed_base_exception(self, sf: SourceFile
                                  ) -> Iterable[Finding]:
        out: List[Finding] = []
        for handler in [n for n in ast.walk(sf.tree)
                        if isinstance(n, ast.ExceptHandler)]:
            t = handler.type
            catches_base = (
                t is None
                or (isinstance(t, ast.Name) and t.id == "BaseException")
                or (isinstance(t, ast.Attribute)
                    and t.attr == "BaseException"))
            if not catches_base:
                continue
            if any(isinstance(n, ast.Raise) and n.exc is None
                   for n in ast.walk(handler)):
                continue  # re-raises: correct interrupt hygiene
            in_loop = any(isinstance(anc, (ast.While, ast.For))
                          for anc in sf.iter_parents(handler))
            if not in_loop:
                continue
            spelled = "bare 'except:'" if t is None \
                else "'except BaseException:'"
            out.append(sf.finding(
                self.rule, handler,
                f"{spelled} inside a loop without re-raise swallows "
                f"SystemExit/KeyboardInterrupt — the worker loop becomes "
                f"unkillable and real faults turn into silent retries "
                f"(catch Exception, or re-raise)"))
        return out

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        return list(self._self_recursion(sf)) \
            + list(self._swallowed_base_exception(sf))

    def finish(self) -> Iterable[Finding]:
        return []
