"""lock-discipline checker.

Two rules:

**guarded_by** — an attribute assignment annotated

    self._routes = {}   #: guarded_by: _lock

declares that every *other* read/write of ``self._routes`` inside the class
must happen lexically under ``with self._lock:`` (the annotation may also
sit on the line directly above the assignment). Exemptions:

* ``__init__`` / ``__del__`` — construction and teardown precede/outlive
  sharing;
* methods whose ``def`` line carries ``#: holds: _lock`` — helpers
  documented as called-with-the-lock-held (the checker trusts, the
  annotation documents);
* the annotated assignment itself.

**lock-order** — every lexically nested acquisition ``with self.A: ...
with self.B:`` contributes an edge A→B to a cross-file graph keyed by
``ClassName.attr``. If both A→B and B→A exist anywhere in the analyzed
set, every contributing site is reported: inconsistent acquisition order
is a deadlock waiting for the right interleaving. Lock attributes are
recognized by a ``threading.Lock/RLock/Condition/Semaphore`` assignment or
a ``lock``/``_cv`` name suffix.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import (GUARDED_BY_RE, HOLDS_RE, Finding, SourceFile,
                    dotted_name)

RULE = "lock-discipline"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _is_lock_ctor(node: ast.expr) -> bool:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        return name.split(".")[-1] in _LOCK_CTORS
    return False


def _lockish(attr: str) -> bool:
    return attr.endswith("lock") or attr.endswith("_cv") \
        or attr.endswith("_mutex") or attr.endswith("_sem")


class LockDisciplineChecker:
    rule = RULE

    def __init__(self):
        # (Class.attr_a, Class.attr_b) -> list of (Finding-ready site info)
        self._edges: Dict[Tuple[str, str], List[Finding]] = {}

    # ------------------------------------------------------------------
    # guarded_by
    # ------------------------------------------------------------------
    def _annotations(self, sf: SourceFile, cls: ast.ClassDef
                     ) -> Dict[str, str]:
        """attr -> lock attr, from ``#: guarded_by:`` comments on (or one
        line above) ``self.X = ...`` assignments anywhere in the class."""
        guarded: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    m = GUARDED_BY_RE.search(sf.comment(node.lineno))
                    if m is None \
                            and sf.line(node.lineno - 1).startswith("#"):
                        # a comment-ONLY line directly above also binds
                        # (trailing comments of the previous statement
                        # must not leak onto this one)
                        m = GUARDED_BY_RE.search(
                            sf.comment(node.lineno - 1))
                    if m:
                        guarded[tgt.attr] = m.group(1)
        return guarded

    def _method_holds(self, sf: SourceFile,
                      fn: ast.FunctionDef) -> Set[str]:
        holds: Set[str] = set()
        for lineno in range(fn.lineno,
                            (fn.body[0].lineno if fn.body else fn.lineno)):
            m = HOLDS_RE.search(sf.comment(lineno))
            if m:
                holds.add(m.group(1))
        return holds

    def _under_with_lock(self, sf: SourceFile, node: ast.AST,
                         lock: str, stop: ast.AST) -> bool:
        for anc in sf.iter_parents(node):
            if anc is stop:
                break
            if isinstance(anc, ast.With):
                for item in anc.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Attribute) \
                            and expr.attr == lock:
                        return True
        return False

    def _check_guarded(self, sf: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls in [n for n in ast.walk(sf.tree)
                    if isinstance(n, ast.ClassDef)]:
            guarded = self._annotations(sf, cls)
            if not guarded:
                continue
            for fn in [n for n in ast.walk(cls)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and sf.enclosing_class(n) is cls]:
                if fn.name in ("__init__", "__del__"):
                    continue
                holds = self._method_holds(sf, fn)
                for node in ast.walk(fn):
                    if not (isinstance(node, ast.Attribute)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"
                            and node.attr in guarded):
                        continue
                    if sf.enclosing_function(node) is not fn:
                        continue   # nested defs judged once, as themselves
                        # (a closure can outlive the outer with-block)
                    lock = guarded[node.attr]
                    if lock in holds:
                        continue
                    if self._under_with_lock(sf, node, lock, stop=fn):
                        continue
                    kind = ("write" if isinstance(node.ctx,
                                                  (ast.Store, ast.Del))
                            else "read")
                    out.append(sf.finding(
                        self.rule, node,
                        f"self.{node.attr} is '#: guarded_by: {lock}' but "
                        f"this {kind} is outside 'with self.{lock}:' "
                        f"(annotate the method '#: holds: {lock}' if the "
                        f"caller owns the lock)"))
        return out

    # ------------------------------------------------------------------
    # lock-order graph
    # ------------------------------------------------------------------
    def _lock_attrs(self, sf: SourceFile, cls: ast.ClassDef) -> Set[str]:
        attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" \
                            and _is_lock_ctor(node.value):
                        attrs.add(tgt.attr)
        return attrs

    def _collect_order_edges(self, sf: SourceFile) -> None:
        for cls in [n for n in ast.walk(sf.tree)
                    if isinstance(n, ast.ClassDef)]:
            known = self._lock_attrs(sf, cls)

            def lock_of(withnode: ast.With) -> Optional[str]:
                for item in withnode.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Attribute) and \
                            (expr.attr in known or _lockish(expr.attr)):
                        return expr.attr
                return None

            for outer in [n for n in ast.walk(cls)
                          if isinstance(n, ast.With)]:
                a = lock_of(outer)
                if a is None:
                    continue
                for inner in [n for n in ast.walk(outer)
                              if isinstance(n, ast.With) and n is not outer]:
                    b = lock_of(inner)
                    if b is None or b == a:
                        continue
                    key = (f"{cls.name}.{a}", f"{cls.name}.{b}")
                    self._edges.setdefault(key, []).append(sf.finding(
                        self.rule, inner,
                        f"acquires {key[1]} while holding {key[0]}"))

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        out = list(self._check_guarded(sf))
        self._collect_order_edges(sf)
        return out

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for (a, b), sites in sorted(self._edges.items()):
            if a < b and (b, a) in self._edges:
                rev = self._edges[(b, a)]
                for f in sites + rev:
                    out.append(Finding(
                        rule=self.rule, path=f.path, line=f.line,
                        col=f.col, func=f.func,
                        message=(f"inconsistent lock order: both {a}→{b} "
                                 f"and {b}→{a} acquisitions exist "
                                 f"(potential deadlock); {f.message}"),
                        snippet=f.snippet))
        self._edges.clear()
        return out
