"""dslint command line.

Exit codes: 0 clean (after baseline), 1 findings, 2 usage/internal error.

``--changed`` analyzes only files touched vs a git revision (default
``HEAD``) plus staged and untracked .py files — the pre-commit mode, a few
milliseconds instead of the whole package.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from .baseline import Baseline, BaselineError, write_baseline
from .checkers import ALL_CHECKERS, RULE_HELP
from .core import collect_py_files, run_checkers

DEFAULT_BASELINE = "tools/dslint_baseline.txt"


def repo_root(start: str = ".") -> str:
    """Nearest ancestor containing .git (falls back to cwd)."""
    cur = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(cur, ".git")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def changed_files(root: str, base: str) -> List[str]:
    """Changed-vs-``base`` + staged + untracked python files."""
    out: List[str] = []
    for cmd in (["git", "diff", "--name-only", base],
                ["git", "diff", "--name-only", "--cached"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, check=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            print(f"dslint: --changed needs git: {e}", file=sys.stderr)
            raise SystemExit(2)
        out.extend(line.strip() for line in res.stdout.splitlines()
                   if line.strip().endswith(".py"))
    seen, uniq = set(), []
    for p in out:
        ap = os.path.join(root, p)
        if p not in seen and os.path.exists(ap):
            seen.add(p)
            uniq.append(ap)
    return uniq


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dslint",
        description="JAX- and threading-aware static analysis for this "
                    "codebase's recurring failure modes.")
    p.add_argument("paths", nargs="*", default=["deepspeed_tpu"],
                   help="files or directories (default: deepspeed_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"suppression file (default: {DEFAULT_BASELINE} "
                        f"at the repo root, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report everything")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write current findings as a TODO-justified "
                        "baseline and exit")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REV",
                   help="only analyze files changed vs REV (default HEAD) "
                        "plus staged/untracked — pre-commit mode")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rules to run "
                        f"(of: {','.join(sorted(ALL_CHECKERS))})")
    p.add_argument("--ignore", default=None, metavar="RULES",
                   help="comma-separated rules to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the checker catalogue and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in sorted(ALL_CHECKERS):
            print(f"{rule}\n    {RULE_HELP[rule]}")
        return 0

    rules = set(ALL_CHECKERS)
    if args.select:
        rules = {r.strip() for r in args.select.split(",") if r.strip()}
    if args.ignore:
        rules -= {r.strip() for r in args.ignore.split(",") if r.strip()}
    unknown = rules - set(ALL_CHECKERS)
    if unknown:
        print(f"dslint: unknown rule(s): {', '.join(sorted(unknown))}",
              file=sys.stderr)
        return 2
    checkers = [ALL_CHECKERS[r]() for r in sorted(rules)]

    # anchor the repo root on the first analyzed path, not the cwd: display
    # paths (= baseline keys) must be repo-relative no matter where the
    # tool is invoked from
    anchor = next((p for p in args.paths if os.path.exists(p)), ".")
    root = repo_root(anchor if os.path.isdir(anchor)
                     else os.path.dirname(os.path.abspath(anchor)) or ".")
    if args.changed is not None:
        files = changed_files(root, args.changed)
        # scope the changed set to the requested paths — resolving relative
        # entries against the detected repo ROOT, not the cwd (running from
        # a subdirectory must not silently filter everything out)
        prefixes = [p if os.path.isabs(p) else os.path.join(root, p)
                    for p in args.paths]
        prefixes = [os.path.abspath(p) for p in prefixes]
        files = [f for f in files
                 if any(os.path.abspath(f).startswith(pre + os.sep)
                        or os.path.abspath(f) == pre for pre in prefixes)]
        pairs = collect_py_files(files, root)
    else:
        pairs = collect_py_files(args.paths, root)

    findings = run_checkers(pairs, checkers)

    if args.write_baseline:
        n = write_baseline(args.write_baseline, findings)
        print(f"dslint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {args.write_baseline} "
              f"(replace each TODO with a real justification)")
        return 0

    baseline = None
    if not args.no_baseline:
        bp = args.baseline or os.path.join(root, DEFAULT_BASELINE)
        if os.path.exists(bp):
            try:
                baseline = Baseline.load(bp)
            except BaselineError as e:
                print(f"dslint: {e}", file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"dslint: baseline not found: {bp}", file=sys.stderr)
            return 2

    suppressed = []
    stale: List[str] = []
    if baseline is not None:
        findings, suppressed = baseline.split(findings)
        # an entry is only provably stale when its file WAS analyzed this
        # run (--changed / partial-path runs must not cry wolf)
        analyzed = {disp for _, disp in pairs}
        stale = [k for k in baseline.stale_entries()
                 if k.split("::", 1)[0] in analyzed]

    if args.as_json:
        print(json.dumps({
            "files_analyzed": len(pairs),
            "rules": sorted(rules),
            "findings": [f.to_json() for f in findings],
            "suppressed": len(suppressed),
            "stale_baseline_entries": stale,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        tail = (f"dslint: {len(findings)} finding"
                f"{'' if len(findings) == 1 else 's'} "
                f"({len(suppressed)} baselined) across {len(pairs)} files")
        if stale:
            tail += (f"; {len(stale)} STALE baseline entr"
                     f"{'y' if len(stale) == 1 else 'ies'} "
                     f"(fixed or drifted — prune them):")
            print(tail)
            for k in stale:
                print(f"    stale: {k}")
        else:
            print(tail)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
