"""dslint — JAX- and threading-aware static analysis for deepspeed_tpu.

Five checkers purpose-built for this codebase's recurring failure modes
(see tools/dslint/checkers/ and the README "Static analysis" section):

* ``host-sync``          — hidden device→host syncs in jit/hot paths
* ``lock-discipline``    — ``#: guarded_by:`` violations + lock-order graph
* ``resource-lifecycle`` — pool/refcount leaks on exception paths
* ``recompile-hazard``   — per-call jax.jit wrappers, unhashable statics
* ``control-flow``       — identical-arg self-recursion, swallowed
                           BaseException in worker loops

Programmatic use::

    from dslint import run
    findings = run(["deepspeed_tpu"])          # list[Finding]

CLI: ``python tools/dslint.py [paths] [--json] [--baseline F] [--changed]``.
"""

from typing import Iterable, List, Optional

from .baseline import Baseline, BaselineError, write_baseline
from .checkers import ALL_CHECKERS, RULE_HELP
from .cli import main
from .core import Finding, collect_py_files, run_checkers

__version__ = "0.1.0"


def run(paths: Iterable[str], rules: Optional[Iterable[str]] = None,
        root: str = ".") -> List[Finding]:
    """Analyze ``paths`` with the selected ``rules`` (default: all)."""
    selected = sorted(rules) if rules is not None else sorted(ALL_CHECKERS)
    checkers = [ALL_CHECKERS[r]() for r in selected]
    return run_checkers(collect_py_files(paths, root), checkers)


__all__ = ["run", "main", "Finding", "Baseline", "BaselineError",
           "write_baseline", "ALL_CHECKERS", "RULE_HELP",
           "collect_py_files", "run_checkers", "__version__"]
