"""Baseline (suppression) file support.

One entry per line::

    <path>::<rule>::<qualname>::<normalized snippet> -- <justification>

The key is the finding fingerprint — deliberately line-number-free so an
edit elsewhere in the file does not invalidate the baseline. The
`` -- justification`` is MANDATORY: a suppression without a written reason
is a parse error (exit 2), which is what keeps the baseline honest — every
entry answers "why is this not a bug?" in the file itself.

An entry suppresses every finding with the same fingerprint (two identical
snippets in one function are one decision). Entries that no longer match
anything are reported as stale so the baseline shrinks as code heals;
stale entries are a warning, not a failure (a fix should not force a
lockstep baseline edit to land).
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Tuple

from .core import Finding


class BaselineError(Exception):
    pass


class Baseline:
    def __init__(self, entries: Dict[str, str], path: str = ""):
        self.entries = entries          # fingerprint -> justification
        self.path = path
        self.matched: Dict[str, int] = {}

    # ------------------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "Baseline":
        entries: Dict[str, str] = {}
        with open(path, "r", encoding="utf-8") as f:
            for lineno, raw in enumerate(f, 1):
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if " -- " not in line:
                    raise BaselineError(
                        f"{path}:{lineno}: baseline entry has no "
                        f"' -- <justification>' (every suppression must "
                        f"say why): {line!r}")
                key, just = line.split(" -- ", 1)
                key = key.strip()
                just = just.strip()
                if not just:
                    raise BaselineError(
                        f"{path}:{lineno}: empty justification")
                if key.count("::") < 3:
                    raise BaselineError(
                        f"{path}:{lineno}: malformed key (want "
                        f"path::rule::qualname::snippet): {key!r}")
                entries[key] = just
        return cls(entries, path)

    # ------------------------------------------------------------------
    def split(self, findings: Iterable[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """(new, suppressed) — also records per-entry match counts."""
        new: List[Finding] = []
        suppressed: List[Finding] = []
        self.matched = {k: 0 for k in self.entries}
        for f in findings:
            fp = f.fingerprint
            if fp in self.entries:
                self.matched[fp] += 1
                suppressed.append(f)
            else:
                new.append(f)
        return new, suppressed

    def stale_entries(self) -> List[str]:
        return [k for k, n in self.matched.items() if n == 0]


def write_baseline(path: str, findings: Iterable[Finding],
                   justification: str = "TODO: justify or fix") -> int:
    """Emit a baseline seeding every current finding (deduplicated by
    fingerprint). Written entries carry a TODO justification on purpose:
    the file will not load until a human replaces each with a reason."""
    seen: Dict[str, Finding] = {}
    for f in findings:
        seen.setdefault(f.fingerprint, f)
    lines = ["# dslint baseline — format:",
             "#   path::rule::qualname::snippet -- justification",
             "# A suppression without a real justification does not load.",
             ""]
    for fp in sorted(seen):
        lines.append(f"{fp} -- {justification}")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return len(seen)
