#!/usr/bin/env python
"""Serving chaos drill CLI: drive the request-lifecycle layer
(``deepspeed_tpu/serving``) through a named overload/failure scenario and
exit nonzero if the serving invariants fail — the serving face of
``tools/chaos_drill.py``.

Invariants asserted after EVERY drill:

* **no KV-block leak** — the engine's block pool accounting returns to its
  initial state (every allocated block freed, no live sequences);
* **no request silently lost** — every admitted uid resolves to
  ``completed | shed | expired`` in the terminal ledger;
* scenario-specific checks (deadlines actually expired, sheds actually
  typed/retryable, drain actually closed admission and finished in-flight).

    python tools/serve_drill.py --list
    python tools/serve_drill.py --scenario deadline-storm
    python tools/serve_drill.py --scenario shed-under-kv-pressure
    python tools/serve_drill.py --scenario sigterm-drain
    python tools/serve_drill.py --scenario frontend-storm
    python tools/serve_drill.py --scenario prefix-storm
    python tools/serve_drill.py --scenario slo-storm
    python tools/serve_drill.py --scenario crash-migrate
    python tools/serve_drill.py --scenario moe-storm

Exit code 0 = invariants held; 1 = violated (details on stdout as JSON).
A passing ``slo-storm`` run appends a ``bench_slo`` entry (preemption
counters, resume success rate) to the perf ledger (``tools/
bench_ledger.py``) unless ``--no-ledger``; a passing ``crash-migrate``
run appends a ``bench_migration`` entry (migration success rate, resumed
tokens/s); ``tools/bench_trend.py`` gates on both. Slow pytest wrappers
live in ``tests/unit/test_serving.py`` under the ``serving`` + ``slow``
markers (``slo`` for the SLO drill, ``migrate`` for the migration
drill in ``tests/unit/test_migration.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_batcher(num_blocks=None, monitor=None, clock=time.monotonic,
                  engine_kw=None, **serving):
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerLM, get_preset
    from deepspeed_tpu.serving import ContinuousBatcher

    ekw = {"max_sequences": 8, "max_seq_len": 128, "block_size": 16,
           "num_blocks": num_blocks, **(engine_kw or {})}
    preset = ekw.pop("preset_kw", {})
    eng = InferenceEngineV2(TransformerLM(get_preset("tiny", **preset)),
                            **ekw)
    cfg = ServingConfig(**{"prefill_chunk": 32, "default_max_new_tokens": 8,
                           **serving})
    return ContinuousBatcher(eng, cfg, monitor=monitor, clock=clock)


def _fresh_injector():
    from deepspeed_tpu.resilience import set_injector

    set_injector(None)


def _invariants(b, uids) -> dict:
    """The cross-scenario serving invariants (see module doc)."""
    alloc = b.engine.state.allocator
    unresolved = {u: b.manager.resolve(u) for u in uids
                  if b.manager.resolve(u)
                  not in ("completed", "shed", "expired")}
    return {
        "kv_pool_restored": alloc.free_blocks == alloc.num_blocks,
        "free_blocks": alloc.free_blocks, "num_blocks": alloc.num_blocks,
        "live_sequences": len(b.engine.state.sequences),
        "unresolved_uids": unresolved,
        "ok": (alloc.free_blocks == alloc.num_blocks
               and not b.engine.state.sequences and not unresolved),
    }


# ---------------------------------------------------------------------------
# scenarios: each returns (ok: bool, details: dict)
# ---------------------------------------------------------------------------

def scenario_deadline_storm(workdir):
    """A burst of requests with deadlines too tight for the queue they join,
    plus one injected cache_io_error step. Invariant: every expired request
    — including ones caught mid-chunked-prefill — releases all KV blocks;
    the IO-failed step loses no request; survivors with generous deadlines
    still complete."""
    import numpy as np

    from deepspeed_tpu.resilience import FaultInjector, set_injector

    now = [0.0]
    b = _make_batcher(clock=lambda: now[0], default_max_new_tokens=4,
                      max_queue_depth=32)
    # one engine step fails on KV-cache IO; the batcher must retry, not drop
    set_injector(FaultInjector([{"kind": "cache_io_error", "times": 1}]))
    real_step = b.step

    def step():
        ran = real_step()
        if ran:
            now[0] += 1.0
        return ran
    b.step = step
    rng = np.random.default_rng(0)
    tight = [b.submit(rng.integers(0, 250, 96), deadline_s=2.5)
             for _ in range(6)]            # 96-token prompts need 3 chunks
    loose = [b.submit(rng.integers(0, 250, 40), deadline_s=60.0)
             for _ in range(4)]
    b.pump(max_steps=200)
    rep = b.serving_report()
    inv = _invariants(b, tight + loose)
    details = {"report": rep, "invariants": inv,
               "tight": {u: b.manager.resolve(u) for u in tight},
               "loose": {u: b.manager.resolve(u) for u in loose}}
    ok = (inv["ok"] and rep["counters"]["expired"] >= 1
          and all(b.manager.resolve(u) == "completed" for u in loose)
          and rep["counters"]["completed"] >= len(loose)
          and rep["counters"]["step_failures"] == 1)
    return ok, details


def scenario_shed_under_kv_pressure(workdir):
    """More aggregate KV demand than the pool holds, then a shed_storm
    fault on top. Invariant: the batcher sheds lowest-priority/newest with
    typed retryable ShedErrors instead of CapacityError escaping put();
    the high-priority request completes; the pool drains back to empty."""
    import numpy as np

    from deepspeed_tpu.resilience import FaultInjector, set_injector
    from deepspeed_tpu.serving import ShedError

    b = _make_batcher(num_blocks=12, default_max_new_tokens=16,
                      kv_high_watermark=0.8, kv_low_watermark=0.5,
                      max_queue_depth=8)
    rng = np.random.default_rng(1)
    vip = b.submit(rng.integers(0, 250, 60), priority=10)
    crowd = [b.submit(rng.integers(0, 250, 60)) for _ in range(6)]
    rejected = 0
    try:
        for _ in range(4):           # overflow the bounded queue
            b.submit(rng.integers(0, 250, 60))
    except ShedError as e:
        rejected += 1
        retryable = e.retryable and e.reason == "queue_full"
    else:
        retryable = False
    b.pump(max_steps=30)
    set_injector(FaultInjector([{"kind": "shed_storm", "times": 2}]))
    b.pump(max_steps=300)
    _fresh_injector()
    b.pump(max_steps=300)
    rep = b.serving_report()
    inv = _invariants(b, [vip] + crowd)
    shed_reqs = [b.manager.done[u] for u in crowd
                 if b.manager.resolve(u) == "shed"]
    details = {"report": rep, "invariants": inv,
               "vip": b.manager.resolve(vip),
               "crowd": {u: b.manager.resolve(u) for u in crowd},
               "queue_full_rejected": rejected,
               "queue_full_retryable": retryable}
    ok = (inv["ok"] and b.manager.resolve(vip) == "completed"
          and rep["counters"]["shed"] >= 1 and rejected >= 1 and retryable
          and all(r.error is not None and r.error.retryable
                  for r in shed_reqs))
    return ok, details


def scenario_sigterm_drain(workdir):
    """SIGTERM mid-flight. Invariant: admission closes with a retryable
    'draining' ShedError, queued requests are shed, every in-flight
    sequence resolves (completed within the drain budget), and the batcher
    exits drained with the pool back to its initial state."""
    import numpy as np

    from deepspeed_tpu.serving import ShedError

    b = _make_batcher(default_max_new_tokens=8, max_queue_depth=32,
                      max_active_requests=4)
    b.install_signal_handlers()
    try:
        rng = np.random.default_rng(2)
        uids = [b.submit(rng.integers(0, 250, 40)) for _ in range(6)]
        b.step()
        b.step()                       # some in flight, some still queued
        os.kill(os.getpid(), signal.SIGTERM)
        b.pump(max_steps=100)
        if not b.drained:
            b.drain(timeout_s=60.0)
        try:
            b.submit(rng.integers(0, 250, 8))
            admission_closed = False
        except ShedError as e:
            admission_closed = e.reason == "draining" and e.retryable
    finally:
        b.restore_signal_handlers()
    rep = b.serving_report()
    inv = _invariants(b, uids)
    details = {"report": rep, "invariants": inv,
               "states": {u: b.manager.resolve(u) for u in uids},
               "admission_closed": admission_closed}
    ok = (inv["ok"] and b.drained and admission_closed
          and rep["counters"]["completed"] >= 1
          and rep["health"] == "draining")
    return ok, details


def scenario_frontend_storm(workdir):
    """Real HTTP load (stdlib client, real sockets) against a 2-replica
    router behind the network front-end: a storm of concurrent
    mixed-priority requests with a shed_storm fault on top, then a SIGTERM
    drain of one replica mid-storm. Invariants: ≥1 429 with Retry-After;
    the drained replica's queued requests migrate to the sibling; every
    admitted router uid resolves terminal (none lost); both KV pools
    restored; front-end/router close idempotently."""
    import threading

    from deepspeed_tpu.config.config import FrontendConfig, RouterConfig
    from deepspeed_tpu.resilience import FaultInjector, set_injector
    from deepspeed_tpu.serving import (FrontendError, GenerateClient,
                                       Replica, ReplicaRouter,
                                       ServingFrontend)

    b0 = _make_batcher(max_queue_depth=8, default_max_new_tokens=3)
    b1 = _make_batcher(max_queue_depth=8, default_max_new_tokens=3)
    r0, r1 = Replica("r0", b0), Replica("r1", b1)
    router = ReplicaRouter([r0, r1], RouterConfig()).start()
    fe = ServingFrontend(router, FrontendConfig(
        api_keys={"gold": 5}, max_header_priority=4)).start()
    results, lock = [], threading.Lock()

    def wait_for(cond, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.05)
        return False

    def unary(i, key):
        cli = GenerateClient(fe.url, api_key=key, timeout_s=180)
        try:
            out = cli.generate(list(range(1, 10 + i % 4)),
                               max_new_tokens=3,
                               priority=None if key else (i % 3))
            with lock:
                results.append(("ok", out))
        except FrontendError as e:
            with lock:
                results.append(("err", e))

    def streamer(i):
        try:
            evs = list(GenerateClient(fe.url, timeout_s=180).stream(
                list(range(1, 12)), max_new_tokens=3))
            with lock:
                results.append(("stream", evs))
        except FrontendError as e:
            with lock:
                results.append(("err", e))

    timings = {}
    try:
        # phase 1 — storm: queues fill while the workers hold, the shed
        # storm lands on full queues, the overflow 429s at submit time
        r0.paused = r1.paused = True
        threads = [threading.Thread(
            target=unary, args=(i, "gold" if i % 5 == 0 else None))
            for i in range(20)]
        for t in threads:
            t.start()
        wait_for(lambda: r0.stats["queue_depth"] + r1.stats["queue_depth"]
                 + sum(1 for r in results if r[0] == "err") >= 20)
        set_injector(FaultInjector([{"kind": "shed_storm", "times": 2}]))
        r0.paused = r1.paused = False
        for t in threads:
            t.join(timeout=180)
        _fresh_injector()
        errs_p1 = [r[1] for r in results if r[0] == "err"]

        # phase 2 — SIGTERM drain of r0 mid-flight, queued work migrates
        results.clear()
        r0.paused = r1.paused = True
        threads = ([threading.Thread(target=unary, args=(i, "gold"))
                    for i in range(6)]
                   + [threading.Thread(target=streamer, args=(i,))
                      for i in range(6)])
        for t in threads:
            t.start()
        wait_for(lambda: r0.stats["queue_depth"]
                 + r1.stats["queue_depth"] >= 12)
        queued_r0 = r0.stats["queue_depth"]
        router.install_signal_handlers(drain="r0")
        t_drain = time.monotonic()
        os.kill(os.getpid(), signal.SIGTERM)
        migrated_done = wait_for(
            lambda: router.counters["migrated"]
            + router.counters["migration_failed"] >= queued_r0)
        timings["drain_to_migrated_s"] = round(
            time.monotonic() - t_drain, 3)
        r0.paused = r1.paused = False
        for t in threads:
            t.join(timeout=180)
        quiesced = wait_for(
            lambda: all(r.stats["active"] == 0
                        and r.stats["queue_depth"] == 0
                        for r in (r0, r1)))
    finally:
        _fresh_injector()
        router.restore_signal_handlers()
        fe.close()
        fe.close()                    # idempotent-shutdown satellite
        router.close()
        router.close()

    oks = [r[1] for r in results if r[0] == "ok"]
    streams = [r[1] for r in results if r[0] == "stream"]
    errs_p2 = [r[1] for r in results if r[0] == "err"]
    pool0 = _invariants(b0, [])
    pool1 = _invariants(b1, [])
    # no admitted uid lost: every router uid either terminal in a ledger
    # (ok/stream/end-record 429) — router.resolve follows migrations
    admitted_ids = ([o["id"] for o in oks]
                    + [evs[-1]["data"].get("id") for evs in streams
                       if evs and evs[-1]["event"] == "end"]
                    + [e.body["id"] for e in errs_p1 + errs_p2
                       if "id" in (e.body or {})])
    resolved = {i: router.resolve(i) for i in admitted_ids}
    unresolved = {i: st for i, st in resolved.items()
                  if st not in ("completed", "shed", "expired", "cancelled")}
    got_429 = [e for e in errs_p1 if e.status == 429
               and e.retry_after_s is not None]
    # a phase-2 request may legitimately end shed-retryable (the sibling's
    # queue can genuinely fill during migration — that's backpressure, not
    # loss); what may NOT happen is a stream without a terminal end record
    # or a uid that resolves to nothing
    done_streams = [evs for evs in streams
                    if evs and evs[-1]["event"] == "end"]
    completed_streams = [evs for evs in done_streams
                         if evs[-1]["data"]["state"] == "completed"]
    rep = router.report()
    details = {
        "phase1_429": len(got_429), "phase1_errs": len(errs_p1),
        "phase2_ok": len(oks), "phase2_streams": len(streams),
        "phase2_streams_completed": len(completed_streams),
        "phase2_errs": len(errs_p2),
        "queued_r0_at_drain": queued_r0,
        "migrated_done": migrated_done, "quiesced": quiesced,
        "router_counters": rep["counters"], "timings": timings,
        "unresolved_ids": unresolved,
        "pool_r0": pool0, "pool_r1": pool1,
    }
    ok = (len(got_429) >= 1
          and rep["counters"]["migrated"] >= 1
          and migrated_done and quiesced
          and not unresolved
          and all(o["state"] == "completed" and len(o["tokens"]) == 3
                  for o in oks)
          and len(done_streams) == len(streams)
          and all(evs[-1]["data"]["state"] in ("completed", "shed")
                  for evs in done_streams)
          and all(len(evs[-1]["data"]["tokens"]) == 3
                  for evs in completed_streams)
          and len(oks) + len(completed_streams) >= 1
          and pool0["kv_pool_restored"] and pool1["kv_pool_restored"])
    return ok, details


def scenario_prefix_storm(workdir):
    """N clients share one system prompt (prefix cache + n-gram speculation
    on, fp32 so exactness is argmax-stable). Invariants: cache hit-rate > 0
    with every warm request attaching the shared blocks; token streams
    IDENTICAL to a cache-less baseline; distinct-prefix churn forces LRU
    eviction without evicting any block a live sequence shares; after
    flush + cache clear the pool is fully restored with zero refcounts
    leaked."""
    import numpy as np

    spec = {"enabled": True, "ngram": 2, "max_draft": 4, "fallback_steps": 4}
    pkw = {"preset_kw": {"dtype": "float32"}}
    rng = np.random.default_rng(0)
    system = rng.integers(0, 250, 48)          # 3 shared full blocks
    prompts = [np.concatenate([system, rng.integers(0, 250, 6)])
               for _ in range(8)]

    def serve(b):
        outs = []
        for p in prompts:      # sequential: request 1 publishes, 2..N hit
            uid = b.submit(p)
            b.pump(max_steps=200)
            outs.append([int(t) for t in b.manager.done[uid].generated])
        return outs

    base = serve(_make_batcher(engine_kw=pkw, default_max_new_tokens=10))
    # pool sized so the distinct-prefix churn below overflows it: eviction
    # must fire while the shared system blocks stay resident (hot LRU)
    b = _make_batcher(num_blocks=40,
                      engine_kw={**pkw, "prefix_cache": True,
                                 "speculative": spec},
                      default_max_new_tokens=10)
    got = serve(b)
    rep = b.serving_report()
    pc = b.engine.prefix_cache

    # churn distinct prefixes through the small pool to force LRU eviction
    for i in range(12):
        uid = b.submit(rng.integers(0, 250, 56))
        b.pump(max_steps=200)
    churn_rep = b.serving_report()

    alloc = b.engine.state.allocator
    live_after = len(b.engine.state.sequences)
    cleared = pc.clear()
    restored = alloc.free_blocks == alloc.num_blocks
    leaked = alloc.leaked_blocks()
    hit_rate = rep["counters"]["prefix_hit_requests"] / (len(prompts) - 1)
    details = {
        "tokens_identical": got == base,
        "hit_requests": rep["counters"]["prefix_hit_requests"],
        "hit_tokens": rep["counters"]["prefix_hit_tokens"],
        "hit_rate": round(hit_rate, 3),
        "speculative": rep["speculative"],
        "evicted_blocks": churn_rep["prefix_cache"]["evicted_blocks"],
        "cleared_blocks": cleared, "live_sequences": live_after,
        "pool_restored": restored, "leaked_blocks": leaked,
        "kv": churn_rep["kv"],
    }
    ok = (got == base
          and hit_rate > 0
          and rep["counters"]["prefix_hit_tokens"]
          >= 48 * (len(prompts) - 1)
          and rep["speculative"]["rounds"] > 0
          and churn_rep["prefix_cache"]["evicted_blocks"] > 0
          and live_after == 0 and restored and not leaked)
    return ok, details


def scenario_kv_tier(workdir):
    """Distinct-prefix churn through a small HBM block pool with the host +
    NVMe KV tiers on (fp32 so exactness is argmax-stable). Invariants:
    demote→promote cycles happen in BOTH tiers (host hits and NVMe hits,
    after demotions into each); every token stream is IDENTICAL to a
    cache-less baseline — including the second round, where prompts are
    served off promoted blocks; effective cache capacity (resident +
    demoted nodes) reaches ≥ 5× the HBM pool; after flush + clear the
    pool, the pinned-buffer pool, and the tier store are fully restored
    with zero loans or refcounts leaked."""
    import shutil
    import tempfile

    nvme_dir = tempfile.mkdtemp(dir=workdir) if workdir \
        else tempfile.mkdtemp()
    try:
        return _kv_tier_body(nvme_dir)
    finally:
        # the swapper only best-effort-removes files for discarded
        # entries; without this, every run leaks a /tmp dir of KV files
        shutil.rmtree(nvme_dir, ignore_errors=True)


def _kv_tier_body(nvme_dir):
    import numpy as np

    num_blocks, bs = 16, 16
    pkw = {"preset_kw": {"dtype": "float32"}}
    rng = np.random.default_rng(7)
    # 30 prompts x 3 full blocks each: far more cached state than 16 HBM
    # blocks can hold — round 1 churns the tree through demotion, round 2
    # serves the same prompts off promoted blocks
    prompts = [np.concatenate([rng.integers(0, 250, 48),
                               rng.integers(0, 250, 4)])
               for _ in range(30)]

    def serve(b, ps):
        outs = []
        for p in ps:
            uid = b.submit(p)
            b.pump(max_steps=200)
            outs.append([int(t) for t in b.manager.done[uid].generated])
        return outs

    cold = _make_batcher(num_blocks=num_blocks, engine_kw=pkw,
                         default_max_new_tokens=6)
    base = serve(cold, prompts)
    base_recent = serve(cold, prompts[-8:])
    # host budget ~10 blocks (a tiny-model block is L*bs*lanes*4B*2); the
    # other ~70 demoted blocks must ride the NVMe tier
    tiers = {"enabled": True, "host_mb": 10 * (2 * bs * 64 * 4 * 2) / 2**20,
             "nvme_path": nvme_dir, "promote_depth": 4}
    b = _make_batcher(num_blocks=num_blocks,
                      engine_kw={**pkw,
                                 "prefix_cache": {"enabled": True,
                                                  "tiers": tiers}},
                      default_max_new_tokens=6)
    round1 = serve(b, prompts)
    pc = b.engine.prefix_cache
    capacity_r1 = pc.report()["blocks"] + pc.report()["demoted_nodes"]
    # the freshest demotions are still in the host tier: replaying the
    # most recent prompts exercises the host demote→promote cycle before
    # their blocks age out to NVMe
    round_recent = serve(b, prompts[-8:])
    round2 = serve(b, prompts)
    rep = b.serving_report()
    pcr = pc.report()
    tiers_rep = pcr["tiers"]
    capacity = max(capacity_r1, pcr["blocks"] + pcr["demoted_nodes"])

    alloc = b.engine.state.allocator
    live_after = len(b.engine.state.sequences)
    cleared = pc.clear()
    pool_restored = alloc.free_blocks == alloc.num_blocks
    leaked = alloc.leaked_blocks()
    store = b.engine._tier_store
    store_entries = store.entries()
    pinned = store.pool.report()
    swapper_rep = store.swapper.report() if store.swapper else {}
    b.engine.close()
    details = {
        "round1_identical": round1 == base,
        "recent_identical": round_recent == base_recent,
        "round2_identical": round2 == base,
        "effective_capacity_blocks": capacity,
        "hbm_pool_blocks": num_blocks,
        "capacity_ratio": round(capacity / num_blocks, 2),
        "prefix_cache": pcr,
        "tier_counters": {k: tiers_rep[k] for k in
                          ("host_demotions", "nvme_demotions", "host_hits",
                           "nvme_hits", "host_misses", "nvme_misses",
                           "dropped")},
        "batcher_tier_counters": {
            "tier_hit_requests": rep["counters"]["tier_hit_requests"],
            "tier_promoted_blocks":
                rep["counters"]["tier_promoted_blocks"]},
        "cleared_nodes": cleared, "live_sequences": live_after,
        "pool_restored": pool_restored, "leaked_blocks": leaked,
        "store_entries_after_clear": store_entries,
        "pinned_pool_after_clear": pinned,
        "swapper_after_clear": {k: swapper_rep.get(k) for k in
                                ("inflight_tickets",
                                 "loaned_read_buffers")},
    }
    ok = (round1 == base and round2 == base
          and round_recent == base_recent
          and tiers_rep["host_demotions"] >= 1
          and tiers_rep["nvme_demotions"] >= 1
          and tiers_rep["host_hits"] >= 1
          and tiers_rep["nvme_hits"] >= 1
          and pcr["promoted_blocks"] >= 1
          and rep["counters"]["tier_promoted_blocks"] >= 1
          and capacity >= 5 * num_blocks
          and live_after == 0 and pool_restored and not leaked
          and store_entries == 0
          and pinned["outstanding"] == 0
          and swapper_rep.get("inflight_tickets", 0) == 0
          and swapper_rep.get("loaned_read_buffers", 0) == 0)
    return ok, details


def scenario_slo_storm(workdir):
    """A latency-tier burst lands on a pool already decoding batch-tier
    work while a preempt_storm fault forces the preemption path (fp32 so
    exactness is argmax-stable). Invariants: ZERO latency-tier sheds —
    the storm pauses batch victims through the KV tier store instead of
    dropping anyone; >= 1 pause→resume round-trip actually happens (by
    counters); every request of every tier still completes and the
    preempted streams are BIT-IDENTICAL to an injection-free replay of
    the same workload; pool, pause store and loans fully restored."""
    import numpy as np

    from deepspeed_tpu.resilience import FaultInjector, set_injector

    pkw = {"preset_kw": {"dtype": "float32"}}
    rng = np.random.default_rng(11)
    batch_prompts = [rng.integers(0, 250, 48) for _ in range(4)]
    lat_prompts = [rng.integers(0, 250, 24) for _ in range(3)]

    def run(inject):
        b = _make_batcher(engine_kw=pkw, default_max_new_tokens=8,
                          max_queue_depth=32,
                          slo={"enabled": True, "preempt": True})
        uids_b = [b.submit(p, tier="batch") for p in batch_prompts]
        b.pump(max_steps=4)            # batch work prefills / starts decode
        if inject:
            set_injector(FaultInjector(
                [{"kind": "preempt_storm", "times": 2}]))
        uids_l = [b.submit(p, tier="latency", deadline_s=120.0)
                  for p in lat_prompts]
        b.pump(max_steps=400)
        _fresh_injector()
        b.pump(max_steps=400)
        toks = {u: [int(t) for t in b.manager.done[u].generated]
                for u in uids_b + uids_l if u in b.manager.done}
        return b, uids_b, uids_l, toks

    t0 = time.time()
    b, uids_b, uids_l, toks = run(inject=True)
    storm_s = time.time() - t0
    _, base_b, base_l, base_toks = run(inject=False)

    rep = b.serving_report()
    inv = _invariants(b, uids_b + uids_l)
    mc = b.manager.counters
    shed_tiers = [r.tier for r in b.manager.done.values()
                  if r.finish_reason == "shed"]
    store = b.engine._tier_store
    tier_rep = b.engine.tier_report() or {}
    gen_tokens = sum(len(v) for v in toks.values())
    bench = {
        "metric": "resume_success_rate", "unit": "ratio",
        "value": (mc["resumed"] / mc["paused"] if mc["paused"] else 0.0),
        "paused": mc["paused"], "resumed": mc["resumed"],
        "resume_success_rate": (mc["resumed"] / mc["paused"]
                                if mc["paused"] else 0.0),
        "storm_tokens_per_sec": round(gen_tokens / max(storm_s, 1e-9), 2),
        "latency_sheds": sum(1 for t in shed_tiers if t == "latency"),
    }
    # identical uid sequence across the two runs → positional comparison
    identical = (len(uids_b + uids_l) == len(base_b + base_l)
                 and all(toks.get(u) == base_toks.get(v)
                         for u, v in zip(uids_b + uids_l, base_b + base_l)))
    details = {"report": rep, "invariants": inv, "bench": bench,
               "states": {u: b.manager.resolve(u) for u in uids_b + uids_l},
               "shed_tiers": shed_tiers,
               "bit_identical_vs_unpreempted": identical,
               "paused_requests_after": tier_rep.get("paused_requests"),
               "store_entries_after": store.entries() if store else 0}
    ok = (inv["ok"]
          and mc["paused"] >= 1 and mc["resumed"] >= 1
          and mc["resumed"] == mc["paused"]
          and rep["counters"]["resume_failures"] == 0
          and not any(t == "latency" for t in shed_tiers)
          and all(b.manager.resolve(u) == "completed"
                  for u in uids_b + uids_l)
          and identical
          and tier_rep.get("paused_requests", 0) == 0
          and (store.entries() if store else 0) == 0)
    return ok, details


def scenario_crash_migrate(workdir):
    """Two replicas share a durable NVMe namespace; one is killed
    mid-decode with batch-tier victims paused (durable manifests on the
    shared tier) and latency-tier work still decoding. Invariants: the
    sibling ADOPTS >= 1 paused request through its manifest and resumes
    it fp32-BIT-IDENTICAL to an uncrashed replay; >= 1 manifest-less
    in-flight request recovers by re-prefill from token history
    (recompute, never zero-fill); zero admitted uids unresolved — every
    stream carries a ``migrated`` event and exactly one terminal record;
    the surviving pool, its tier store, and the shared namespace
    (manifests + KV files) are fully reclaimed."""
    import shutil
    import tempfile

    shared = tempfile.mkdtemp(dir=workdir) if workdir \
        else tempfile.mkdtemp()
    try:
        return _crash_migrate_body(shared)
    finally:
        # exception-safe: a failed assertion must not leak the shared
        # namespace (same fix as the kv-tier drill's rmtree)
        shutil.rmtree(shared, ignore_errors=True)


def _crash_migrate_body(shared):
    import queue as queue_mod

    import numpy as np

    from deepspeed_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                                 set_injector)
    from deepspeed_tpu.serving import Replica, ReplicaRouter

    pkw = {"preset_kw": {"dtype": "float32"}}
    mig = {"enabled": True, "shared_nvme_path": shared,
           "manifest_ttl_s": 300.0}
    rng = np.random.default_rng(23)
    batch_prompts = [rng.integers(0, 250, 48) for _ in range(4)]
    lat_prompts = [rng.integers(0, 250, 24) for _ in range(3)]
    plan = ([(p, "batch", 12) for p in batch_prompts]
            + [(p, "latency", 8) for p in lat_prompts])

    # uncrashed replay: greedy fp32 per-prompt baselines
    solo = _make_batcher(engine_kw=pkw, default_max_new_tokens=8)
    base = []
    for p, _tier, n in plan:
        uid = solo.submit(p, max_new_tokens=n)
        solo.pump(max_steps=400)
        base.append([int(t) for t in solo.manager.done[uid].generated])

    # 17 HBM blocks is the deterministic sweet spot: four decoding batch
    # requests hold 4 blocks each (16/17 stays under the raised
    # watermark), and once the storm pauses two of them the three live
    # latency requests (2 blocks each) leave only 3 free — a paused
    # victim needs 4 to resume, so the pauses STAY paused until the
    # crash lands
    def mk():
        return _make_batcher(num_blocks=17, engine_kw=pkw,
                             default_max_new_tokens=8, max_queue_depth=32,
                             kv_high_watermark=0.95, kv_low_watermark=0.5,
                             slo={"enabled": True, "preempt": True},
                             migration=mig)

    r0, r1 = Replica("r0", mk()), Replica("r1", mk())
    router = ReplicaRouter([r0, r1]).start()
    streams, collected = [], {}

    def drain_events():
        for uid, q in streams:
            buf = collected.setdefault(uid, [])
            while True:
                try:
                    buf.append(q.get_nowait())
                except queue_mod.Empty:
                    break

    def evs(uid, kind):
        return [e for e in collected.get(uid, ())
                if e.get("event") == kind]

    def wait_for(cond, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            drain_events()
            if cond():
                return True
            time.sleep(0.005)
        return False

    timings = {}
    try:
        # phase 1 — batch-tier work lands on r0 and reaches mid-decode
        uids = []
        for p, tier, n in plan[:len(batch_prompts)]:
            q = queue_mod.Queue()
            uids.append(r0.submit(p, max_new_tokens=n, tier=tier,
                                  events=q))
            streams.append((uids[-1], q))
        mid_decode = wait_for(
            lambda: all(evs(u, "token") and not evs(u, "end")
                        for u in uids))

        # phase 2 — latency storm + forced preemption: two batch victims
        # pause, auto-exporting durable manifests onto the shared tier.
        # The worker is FROZEN while the storm is armed — a free-running
        # replica would burn the preempt fault on steps where the latency
        # work is not yet admitted, pausing nobody
        r0.paused = True
        for p, tier, n in plan[len(batch_prompts):]:
            q = queue_mod.Queue()
            uids.append(r0.submit(p, max_new_tokens=n, tier=tier,
                                  events=q))
            streams.append((uids[-1], q))
        set_injector(FaultInjector([{"kind": "preempt_storm", "times": 2}]))
        r0.paused = False
        got_paused = wait_for(lambda: r0.stats["paused_batch"] >= 1,
                              timeout=60.0)
        paused_at_crash = r0.stats["paused_batch"]

        # phase 3 — kill r0's worker mid-decode, then fail over: PAUSED
        # requests adopt through their manifests, severed DECODING ones
        # re-prefill from token history on the sibling
        set_injector(FaultInjector(
            [FaultSpec(kind="replica_crash", site="r0")]))
        crashed = wait_for(lambda: not r0.alive, timeout=30.0)
        set_injector(None)
        drain_events()
        sent_before_crash = {u: len(evs(u, "token")) for u in uids}
        t_crash = time.monotonic()
        fo = router.fail_over("r0")
        done = wait_for(lambda: all(evs(u, "end") for u in uids))
        t_done = time.monotonic()
        timings["crash_to_all_terminal_s"] = round(t_done - t_crash, 3)
        quiesced = wait_for(
            lambda: (r1.stats["active"] == 0
                     and r1.stats["queue_depth"] == 0), timeout=30.0)
        # shared namespace reclaimed: every manifest and durable KV file
        # dies with its request (sibling-side discard removes files the
        # donor produced)
        reclaimed = wait_for(lambda: not _shared_tier_files(shared),
                             timeout=30.0)
        leftovers = [] if reclaimed else _shared_tier_files(shared)
    finally:
        _fresh_injector()
        router.close()

    drain_events()
    ends = {u: evs(u, "end") for u in uids}
    tokens = {u: (ends[u][0]["tokens"] if ends[u] else None)
              for u in uids}
    migrated_uids = [u for u in uids if evs(u, "migrated")]
    resumed_from = {u: ends[u][0].get("migrated_from")
                    for u in uids if ends[u]}
    identical = all(tokens[u] == base[i] for i, u in enumerate(uids))
    resumed_tokens = sum(len(evs(u, "token")) - sent_before_crash[u]
                         for u in uids)
    rc = router.counters
    inv1 = _invariants(r1.batcher, [])
    store = r1.batcher.engine._tier_store
    mig_total = rc["adopts"] + rc["reprefill_failovers"]
    rate = (mig_total / (mig_total + rc["migration_failed"])
            if mig_total + rc["migration_failed"] else 0.0)
    bench = {
        "metric": "migration_success_rate", "unit": "ratio",
        "value": rate, "migration_success_rate": rate,
        "resumed_tokens_per_sec": round(
            resumed_tokens / max(t_done - t_crash, 1e-9), 2),
        "durable_adopts": rc["adopts"],
        "reprefill_failovers": rc["reprefill_failovers"],
    }
    details = {
        "mid_decode": mid_decode, "got_paused": got_paused,
        "paused_at_crash": paused_at_crash, "crashed": crashed,
        "failover": fo, "all_terminal": done, "quiesced": quiesced,
        "router_counters": rc, "bench": bench, "timings": timings,
        "migrated_uids": migrated_uids, "resumed_from": resumed_from,
        "bit_identical_vs_uncrashed": identical,
        "states": {u: (ends[u][0]["state"] if ends[u] else None)
                   for u in uids},
        "shared_tier_leftovers": leftovers,
        "pool_r1": inv1,
        "store_entries_r1": store.entries() if store else 0,
    }
    ok = (mid_decode and got_paused and paused_at_crash >= 1 and crashed
          and done and quiesced and identical
          and fo["failed"] == 0
          and rc["adopts"] >= 1                 # >= 1 durable resume
          and rc["reprefill_failovers"] >= 1    # >= 1 manifest-less
          and all(len(ends[u]) == 1 for u in uids)
          and all(ends[u][0]["state"] == "completed" for u in uids)
          # every IN-FLIGHT capture resumed as an adoption from r0; a
          # queued-at-crash capture is re-submitted fresh (no donor tag)
          and all(f in (None, "r0") for f in resumed_from.values())
          and sum(1 for f in resumed_from.values() if f == "r0")
          == mig_total
          and len(migrated_uids) == fo["migrated"]
          and not leftovers
          and inv1["kv_pool_restored"]
          and (store.entries() if store else 0) == 0)
    return ok, details


def _shared_tier_files(shared):
    """Every regular file still alive under the shared namespace."""
    out = []
    for root, _dirs, files in os.walk(shared):
        out.extend(os.path.join(os.path.relpath(root, shared), f)
                   for f in files)
    return sorted(out)


def scenario_moe_storm(workdir):
    """Expert-parallel MoE serving under a router skewed to two hot
    experts, with ``moe_a2a_error`` faults injected mid-dispatch at both
    the prefill and decode sites. Invariants: the dropless grouped path
    loses ZERO tokens (every admitted request completes its full
    max_new_tokens — no capacity drops, no fault-shed work); the injected
    a2a failures surface as retried step failures, never lost requests;
    an AutoEP rebalance from the observed (skewed) load replicates the
    hot experts and holds the shard max/mean load under the documented
    LPT bound while greedy outputs stay IDENTICAL across the swap; the
    KV pool is fully restored at exit. Runs in a re-exec'd 8-device child
    (the parent process may hold a 1-device jax)."""
    if not os.environ.get("DSTPU_MOE_STORM_CHILD"):
        import subprocess
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            out = os.path.join(td, "verdict.json")
            env = dict(os.environ, DSTPU_MOE_STORM_CHILD="1",
                       DSTPU_MOE_STORM_OUT=out, JAX_PLATFORMS="cpu",
                       XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                                  + " --xla_force_host_platform_"
                                    "device_count=8"))
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--scenario", "moe-storm", "--no-ledger"],
                env=env, capture_output=True, text=True, timeout=1800)
            if not os.path.exists(out):
                return False, {"error": "moe-storm child produced no "
                                        "verdict", "rc": p.returncode,
                               "stdout": p.stdout[-2000:],
                               "stderr": p.stderr[-2000:]}
            with open(out) as f:
                rec = json.load(f)
            return rec["ok"], rec["details"]

    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.observability.registry import MetricsRegistry
    from deepspeed_tpu.resilience import FaultInjector, set_injector

    n_new = 6
    b = _make_batcher(
        engine_kw={"preset_kw": {"num_experts": 8, "top_k": 2,
                                 "moe_dispatch": "grouped",
                                 "dtype": "float32",
                                 "param_dtype": "float32"},
                   "mesh": {"ep": 4, "dp": 2},
                   "moe_replica_slots": 1},
        default_max_new_tokens=n_new, max_queue_depth=64)
    eng = b.engine
    reg = MetricsRegistry()
    eng.enable_metrics(registry=reg)
    # skew the router hard toward experts 0/1 — the hot-expert storm the
    # balancer exists for (both live on ep shard 0 under natural layout)
    mlp = eng.params["layers"]["mlp"]
    mlp["router"] = mlp["router"] * 0.0 + jnp.asarray(
        [5.0, 4.0] + [-5.0] * 6, mlp["router"].dtype)

    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 250, int(n)) for n in
               rng.integers(8, 40, 10)]
    ref_prompt = rng.integers(0, 250, 20)

    # phase 1 — storm with mid-dispatch a2a faults at both hook sites
    set_injector(FaultInjector([
        {"kind": "moe_a2a_error", "times": 1, "site": "prefill"},
        {"kind": "moe_a2a_error", "times": 1, "site": "decode"},
    ]))
    uids = [b.submit(p) for p in prompts]
    b.pump(max_steps=600)
    _fresh_injector()
    b.pump(max_steps=200)
    # reference prompt decoded ALONE (same batch shape as its post-
    # rebalance replay, so greedy identity is a pure weight-swap check)
    ref_a = b.submit(ref_prompt)
    b.pump(max_steps=200)
    rep = b.serving_report()
    toks = {u: [int(t) for t in b.manager.done[u].generated]
            for u in uids + [ref_a] if u in b.manager.done}

    # phase 2 — AutoEP rebalance from the observed skewed load
    counts = eng._moe_tracker.snapshot()
    imb_obs = eng._moe_tracker.imbalance()
    plan = eng.rebalance_moe()
    reb_counter = reg.counter("moe/rebalances").value

    # phase 3 — identical prompt replayed across the swap
    ref_b = b.submit(ref_prompt)
    b.pump(max_steps=200)
    toks[ref_b] = [int(t) for t in b.manager.done[ref_b].generated] \
        if ref_b in b.manager.done else None
    inv = _invariants(b, uids + [ref_a, ref_b])

    hot_frac = float(counts[0] + counts[1]) / max(float(counts.sum()), 1.0)
    details = {
        "report": {"counters": rep["counters"]},
        "invariants": inv,
        "expert_counts": [int(c) for c in counts],
        "observed_imbalance": round(imb_obs, 3),
        "hot_expert_frac": round(hot_frac, 3),
        "plan": None if plan is None else {
            "nrep": plan.nrep, "moved_slots": plan.moved_slots,
            "imbalance_before": round(plan.imbalance_before, 3),
            "imbalance_after": round(plan.imbalance_after, 3),
            "bound": round(plan.bound, 3)},
        "token_counts": {u: len(v) if v else 0 for u, v in toks.items()},
        "greedy_identical_across_rebalance":
            toks.get(ref_a) == toks.get(ref_b) and toks.get(ref_a),
        "rebalances_counter": reb_counter,
    }
    ok = (inv["ok"]
          # zero token loss: every request completed its FULL budget
          and all(b.manager.resolve(u) == "completed"
                  for u in uids + [ref_a, ref_b])
          and all(len(toks.get(u) or []) == n_new
                  for u in uids + [ref_a, ref_b])
          # both injected a2a faults were absorbed as failed steps
          and rep["counters"]["step_failures"] >= 2
          # the skew was real, the plan replicated the hottest expert and
          # holds the documented bound with strict improvement
          and imb_obs > 1.3
          and plan is not None
          and plan.nrep[int(np.argmax(counts))] > 1
          and plan.imbalance_after <= plan.bound + 1e-9
          and plan.imbalance_after < plan.imbalance_before
          and reb_counter == 1.0
          # the rebalance changed nothing observable
          and bool(details["greedy_identical_across_rebalance"]))
    if os.environ.get("DSTPU_MOE_STORM_OUT"):
        with open(os.environ["DSTPU_MOE_STORM_OUT"], "w") as f:
            json.dump({"ok": ok, "details": details}, f, default=str)
    return ok, details


SCENARIOS = {
    "deadline-storm": scenario_deadline_storm,
    "shed-under-kv-pressure": scenario_shed_under_kv_pressure,
    "sigterm-drain": scenario_sigterm_drain,
    "frontend-storm": scenario_frontend_storm,
    "prefix-storm": scenario_prefix_storm,
    "kv-tier": scenario_kv_tier,
    "slo-storm": scenario_slo_storm,
    "crash-migrate": scenario_crash_migrate,
    "moe-storm": scenario_moe_storm,
}


def run_scenario(name: str, workdir=None) -> dict:
    """Run one drill; returns the verdict record (also usable from tests)."""
    if name not in SCENARIOS:
        raise SystemExit(f"unknown scenario {name!r} "
                         f"(have: {sorted(SCENARIOS)})")
    _fresh_injector()
    t0 = time.time()
    try:
        ok, details = SCENARIOS[name](workdir)
    finally:
        _fresh_injector()
    return {"scenario": name, "ok": ok,
            "seconds": round(time.time() - t0, 2), "details": details}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", help="which drill to run")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--no-ledger", action="store_true",
                    help="skip the bench_slo perf-ledger append")
    args = ap.parse_args(argv)
    if args.list:
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0]}")
        return 0
    names = list(SCENARIOS) if args.all else (
        [args.scenario] if args.scenario else None)
    if not names:
        ap.error("pass --scenario NAME, --all, or --list")
    rc = 0
    for name in names:
        verdict = run_scenario(name)
        print(json.dumps(verdict, indent=2, default=str))
        if not verdict["ok"]:
            rc = 1
        elif name == "slo-storm" and not args.no_ledger:
            from bench_ledger import append_ledger

            path = append_ledger(verdict["details"]["bench"], "bench_slo")
            print(json.dumps({"ledger": path,
                              "bench_slo": verdict["details"]["bench"]}))
        elif name == "crash-migrate" and not args.no_ledger:
            from bench_ledger import append_ledger

            path = append_ledger(verdict["details"]["bench"],
                                 "bench_migration")
            print(json.dumps({"ledger": path,
                              "bench_migration":
                                  verdict["details"]["bench"]}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
