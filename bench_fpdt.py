"""FPDT fused-projection capacity probe (VERDICT r3 missing #6).

Compares the compiled peak device memory of a 1-layer training step at
growing context lengths under (a) the pre-r4 seam path — full-T q/k/v
materialized at the projection boundary, then chunked ``fpdt_attention`` —
and (b) the fused per-chunk-projection path (``fpdt_block_attention``),
then RUNS a real forward+backward at a context where the seam path's
compiled peak exceeds the chip's HBM.

Run on the real chip: ``python bench_fpdt.py``. Prints one JSON line.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

import dataclasses

from deepspeed_tpu.models.transformer import TransformerConfig, TransformerLM

HBM_BYTES = 15.0e9  # v5e usable HBM (16 GB nominal)
CHUNK = 4096


def make(T, impl):
    # MHA (K == H): the seam path's full-T k/v + their cotangents cost
    # ~12 KB/token extra at this width, so its OOM point sits well below
    # the fused path's — the capacity gap this probe demonstrates
    cfg = dataclasses.replace(
        TransformerConfig(vocab_size=8192, hidden_size=2048, num_layers=1,
                          num_heads=16, num_kv_heads=16, max_seq_len=T,
                          dtype="bfloat16", param_dtype="float32",
                          remat_policy="full", loss_tiling=32),
        attention_impl=impl, fpdt_chunk=CHUNK)
    return cfg, TransformerLM(cfg)


def step_fn(model, cfg):
    def loss_fn(params, ids):
        return model.loss_fn(params, {"input_ids": ids})

    def step(params, ids):
        loss, g = jax.value_and_grad(loss_fn)(params, ids)
        # SGD keeps the probe about activations, not optimizer tiers
        params = jax.tree_util.tree_map(lambda p, gg: p - 1e-4 * gg.astype(
            p.dtype), params, g)
        return loss, params

    return jax.jit(step, donate_argnums=(0,))


def compiled_peak(T, impl):
    cfg, model = make(T, impl)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    ids = jax.ShapeDtypeStruct((1, T), jnp.int32)
    c = step_fn(model, cfg).lower(params, ids).compile()
    ma = c.memory_analysis()
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None or peak == 0:
        peak = ma.temp_size_in_bytes + ma.argument_size_in_bytes
    return float(peak)


def _try_peak(fn, *a):
    """Compiled peak bytes, or the HBM overrun the compiler reports when the
    program cannot be placed at all (this backend hard-fails such compiles)."""
    import re
    import sys

    try:
        return fn(*a), False
    except Exception as e:  # noqa: BLE001 — compile OOM is a datapoint
        m = re.search(r"Used ([0-9.]+)G of", str(e))
        print(f"compile failed: {str(e)[:200]}", file=sys.stderr)
        return (float(m.group(1)) * 1e9 if m else float("inf")), True


def run_step(run_T: int) -> dict:
    """Compile + run two fused-path training steps at ``run_T`` (fresh
    process: a failed oversized compile can poison this backend's device
    state, so the run must not share a process with the OOM probes)."""
    cfg, model = make(run_T, "fpdt")
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, 8192, (1, run_T), dtype=np.int32))
    step = step_fn(model, cfg)
    loss, params = step(params, ids)          # compile + step 1
    float(loss)          # forced fetch — only a host fetch synchronizes
    t0 = time.perf_counter()                  # through the tunnel
    loss, params = step(params, ids)
    float(loss)
    dt = time.perf_counter() - t0
    # throughput + MFU at the max-T point (r4 verdict missing #6 asked for
    # tokens/s, not just a capacity number). FLOPs: dense 2NT + causal
    # attention 2T^2*H*hd forward; remat_policy=full re-runs the forward in
    # backward -> total ~ 4x forward
    n_params = cfg.num_params_estimate()
    fwd = 2.0 * n_params * run_T + 2.0 * run_T * run_T \
        * cfg.num_heads * cfg.head_dim
    flops = 4.0 * fwd
    return {"T": run_T, "loss": float(loss), "step_s": dt,
            "tokens_per_sec": round(run_T / dt, 1),
            "mfu": round(flops / dt / 197e12, 4)}


def main():
    import subprocess
    import sys

    if len(sys.argv) > 2 and sys.argv[1] == "--run":
        print(json.dumps(run_step(int(sys.argv[2]))))
        return
    out = {"chunk": CHUNK, "hbm_bytes": HBM_BYTES, "points": []}
    run_T = None
    for T in (131072, 176128, 217088, 258048, 290816):
        row = {"T": T}
        row["fused_peak"], row["fused_oom"] = _try_peak(compiled_peak, T, "fpdt")
        row["seam_peak"], row["seam_oom"] = _try_peak(compiled_peak_seam, T)
        print(f"T={T}: {row}", file=sys.stderr)
        out["points"].append(row)
        # run at the LARGEST fused-feasible T (r4 mistakenly ran at the
        # first seam-OOM demo point instead of the fused path's own max)
        if not row["fused_oom"] and row["fused_peak"] < HBM_BYTES:
            run_T = T
        if row["fused_peak"] > HBM_BYTES:
            break
    if run_T is not None:
        r = subprocess.run([sys.executable, __file__, "--run", str(run_T)],
                           capture_output=True, text=True, timeout=3600)
        if r.returncode == 0 and r.stdout.strip():
            out["ran"] = json.loads(r.stdout.strip().splitlines()[-1])
        else:
            out["ran"] = {"T": run_T, "error": r.stderr[-400:]}
    print(json.dumps(out))


def compiled_peak_seam(T):
    """Pre-r4 behavior: full-T projections + chunked seam attention."""
    import deepspeed_tpu.models.transformer as tfm
    from deepspeed_tpu.sequence.fpdt import fpdt_attention

    def seam_attn(q, k, v, causal=True, **kw):
        return fpdt_attention(q, k, v, causal=causal, chunk=CHUNK,
                              offload=False)

    tfm.register_attention_impl("fpdt_seam", seam_attn)
    return compiled_peak(T, "fpdt_seam")


if __name__ == "__main__":
    main()
