"""User-style drive for PR 7: network serving front-end + replica router.

Drives the PUBLIC surface: JSON config via ``deepspeed_tpu.from_config``
(serving.frontend / serving.router blocks), ``ServingFrontend.
from_deepspeed_config`` over a 2-replica ``ReplicaRouter``, real HTTP via
``GenerateClient`` — then the failure probes (typo'd config keys, string
prompt, oversize prompt, queue-full 429, disabled-block refusal) and the
drain/migration path. CPU-only container (no /root/.axon_site): runs on
the default single CPU device, which is what serving uses anyway.
"""

import json
import os
import signal
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.serving import (ContinuousBatcher, FrontendError,  # noqa: E402
                                   GenerateClient, Replica, ReplicaRouter,
                                   ServingFrontend)

CHECKS = []


def check(name, ok, detail=""):
    CHECKS.append((name, bool(ok), detail))
    print(f"[{'ok' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail
                                                  else ""))


cfg_json = {
    "train_batch_size": 8,
    "serving": {
        "enabled": True,
        "prefill_chunk": 32,
        "default_max_new_tokens": 4,
        "max_queue_depth": 4,
        "retry_after_s": 0.5,
        "frontend": {
            "enabled": True,
            "api_keys": {"gold-tenant": 7},
            "max_prompt_tokens": 64,
        },
        "router": {"enabled": True, "failover_attempts": 0},
    },
}
path = os.path.join(tempfile.mkdtemp(), "ds.json")
with open(path, "w") as f:
    json.dump(cfg_json, f)
cfg = deepspeed_tpu.from_config(path)
check("from_config consumes serving.frontend/router blocks",
      cfg.serving.frontend.api_keys == {"gold-tenant": 7}
      and cfg.serving.router.enabled)

# config probes: pydantic must name the bad field
try:
    deepspeed_tpu.DeepSpeedTpuConfig(train_batch_size=8, serving={
        "enabled": True, "frontend": {"api_kyes": {"a": 1}}})
    check("typo'd frontend key rejected", False)
except Exception as e:
    check("typo'd frontend key rejected", "api_kyes" in str(e), str(e)[:80])
try:
    deepspeed_tpu.DeepSpeedTpuConfig(train_batch_size=8, serving={
        "enabled": True, "router": {"failover_attempts": -1}})
    check("negative failover_attempts rejected", False)
except Exception as e:
    check("negative failover_attempts rejected",
          "failover_attempts" in str(e))

from deepspeed_tpu.models import TransformerLM, get_preset  # noqa: E402
from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2  # noqa: E402


def engine():
    return InferenceEngineV2(TransformerLM(get_preset("tiny")),
                             max_sequences=8, max_seq_len=128,
                             block_size=16)


e0, e1 = engine(), engine()
b0 = ContinuousBatcher.from_deepspeed_config(e0, cfg)
b1 = ContinuousBatcher.from_deepspeed_config(e1, cfg)
r0, r1 = Replica("r0", b0), Replica("r1", b1)
router = ReplicaRouter([r0, r1], cfg.serving.router).start()

# the disabled-block refusal
try:
    ServingFrontend.from_deepspeed_config(
        router, deepspeed_tpu.DeepSpeedTpuConfig(train_batch_size=8))
    check("frontend without serving.frontend.enabled refused", False)
except ValueError as e:
    check("frontend without serving.frontend.enabled refused",
          "serving.frontend.enabled" in str(e))

fe = ServingFrontend.from_deepspeed_config(router, cfg).start()
cli = GenerateClient(fe.url, timeout_s=120)

out = cli.generate(list(range(1, 17)), max_new_tokens=3)
check("unary generate over HTTP", out["state"] == "completed"
      and len(out["tokens"]) == 3 and out["span"]["ttft_ms"] is not None)

evs = list(GenerateClient(fe.url, api_key="gold-tenant").stream(
    list(range(1, 13)), max_new_tokens=3))
check("SSE stream: tokens then end",
      [e["event"] for e in evs] == ["token", "token", "token", "end"]
      and evs[-1]["data"]["state"] == "completed")

import http.client  # noqa: E402

conn = http.client.HTTPConnection(fe.server.host, fe.server.port, timeout=10)
conn.request("GET", "/metrics")
resp = conn.getresponse()
scrape = resp.read().decode()
check("one port: /metrics next to the API", resp.status == 200
      and "serving_queue_depth" in scrape
      and "frontend_http_requests_total" in scrape)
conn.request("GET", "/readyz")
rz = conn.getresponse()
rz.read()
check("one port: /readyz ready", rz.status == 200)
conn.close()

# wire-protocol probes (raw POST — the client would refuse client-side)
conn = http.client.HTTPConnection(fe.server.host, fe.server.port, timeout=10)
conn.request("POST", "/v1/generate", body=json.dumps({"prompt": "a string"}),
             headers={"Content-Type": "application/json",
                      "Connection": "close"})
raw = conn.getresponse()
body = json.loads(raw.read().decode())
check("string prompt -> 400", raw.status == 400
      and body["error"]["type"] == "prompt_not_tokenized")
conn.close()
try:
    cli.generate(list(range(100)))       # > max_prompt_tokens=64
    check("oversize prompt -> 413", False)
except FrontendError as e:
    check("oversize prompt -> 413", e.status == 413)

# queue-full 429 with the load-aware Retry-After
r0.paused = r1.paused = True
for _ in range(8):                       # fill both 4-deep queues
    router.submit(list(range(8)), max_new_tokens=2)
try:
    cli.generate(list(range(8)), max_new_tokens=2)
    check("queue-full -> 429 + Retry-After", False)
except FrontendError as e:
    check("queue-full -> 429 + Retry-After", e.status == 429
          and e.retry_after_s is not None
          and e.body["error"]["retry_after_s"] > 0.5,
          f"retry_after={e.body['error']['retry_after_s']}")

# SIGTERM drain of r0 -> queued requests migrate to r1 and complete.
# Let r1 work off its queue first so the siblings have room to take them.
r1.paused = False
deadline = time.monotonic() + 120
while time.monotonic() < deadline and (
        r1.stats["active"] or r1.stats["queue_depth"]):
    time.sleep(0.05)
r1.paused = True
router.install_signal_handlers(drain="r0")
queued_r0 = r0.stats["queue_depth"]
os.kill(os.getpid(), signal.SIGTERM)
deadline = time.monotonic() + 60
while time.monotonic() < deadline and (
        router.counters["migrated"] + router.counters["migration_failed"]
        < queued_r0):
    time.sleep(0.05)
r0.paused = r1.paused = False
deadline = time.monotonic() + 120
while time.monotonic() < deadline and any(
        r.stats["active"] or r.stats["queue_depth"] for r in (r0, r1)):
    time.sleep(0.1)
states = [router.resolve(u) for u in range(router._next_ruid)]
check("SIGTERM drain migrated the queue",
      router.counters["migrated"] >= 1 and queued_r0 >= 1,
      f"queued_r0={queued_r0} counters={router.counters}")
check("every routed uid resolves terminal",
      all(s in ("completed", "shed", "expired", "cancelled")
          for s in states), f"{states}")
check("draining replica not routable, pool still ready",
      not r0.routable and router.health == "ready")

fe.close()
fe.close()
router.close()
router.close()
for name, eng in (("r0", e0), ("r1", e1)):
    alloc = eng.state.allocator
    check(f"KV pool restored on {name}",
          alloc.free_blocks == alloc.num_blocks
          and not eng.state.sequences)

failed = [c for c in CHECKS if not c[1]]
print(f"\n{len(CHECKS) - len(failed)}/{len(CHECKS)} checks passed")
sys.exit(1 if failed else 0)
