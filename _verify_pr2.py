"""PR 2 verify drive: multi-host resilience coordination through the public API.

Run on the CPU mesh:  DSTPU_VERIFY_CPU=1 python _verify_pr2.py
Run on the TPU chip:  python _verify_pr2.py
"""
import json
import os
import signal
import sys
import tempfile
import time

CPU = os.environ.get("DSTPU_VERIFY_CPU") == "1"
if CPU:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

if CPU:
    jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402

import deepspeed_tpu as ds  # noqa: E402
from deepspeed_tpu.models import TransformerLM, get_preset  # noqa: E402

print(f"devices: {jax.devices()}")
MESH = {"fsdp": 8} if CPU else {"fsdp": 1}
work = tempfile.mkdtemp(prefix="verify_pr2_")
ckpt = os.path.join(work, "ckpt")


def config(path, **resilience):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2}, "mesh": MESH,
           "steps_per_print": 100,
           "resilience": {"enabled": True, **resilience}}
    p = os.path.join(work, path)
    json.dump(cfg, open(p, "w"))
    return p


def train(eng, n, seed=0):
    rng = np.random.default_rng(seed)
    B = eng.train_micro_batch_size_per_gpu() * eng.topology.dp_world_size
    it = iter(lambda: {"input_ids": rng.integers(0, 256, (B, 16))}, None)
    out = [eng.train_batch(it) for _ in range(n)]
    return out


def check(name, cond, detail=""):
    print(f"  [{'OK' if cond else 'FAIL'}] {name} {detail}")
    if not cond:
        sys.exit(f"VERIFY FAILED: {name} {detail}")


# --- 1. config probes: pydantic must name bad fields; dead policies rejected
print("1) config probes")
from deepspeed_tpu.config import from_config  # noqa: E402

try:
    from_config({"resilience": {"heartbeat": {"deadlines_s": 9}}})
    check("typo'd heartbeat key rejected", False)
except Exception as e:
    check("typo'd heartbeat key rejected", "deadlines_s" in str(e), str(e)[:80])
try:
    ds.initialize(model=TransformerLM(get_preset("tiny")),
                  config=json.load(open(config(
                      "bad.json",
                      coordination={"enabled": False},
                      heartbeat={"enabled": True, "dir": os.path.join(
                          work, "hb0")}))))
    check("on_hang=abort without coordination rejected", False)
except ValueError as e:
    check("on_hang=abort without coordination rejected",
          "coordination" in str(e))

# --- 2. coordinated SIGTERM emergency save, decision stamped in the manifest
print("2) coordinated emergency save (SIGTERM -> fleet SAVE at boundary)")
eng, *_ = ds.initialize(model=TransformerLM(get_preset("tiny")),
                        config=config("a.json"))
train(eng, 1)
eng.save_checkpoint(ckpt)   # creates the manager + SIGTERM handler
os.kill(os.getpid(), signal.SIGTERM)
train(eng, 1)               # boundary: agreed SAVE
rep = eng.resilience_report()
check("emergency save committed", rep["checkpoint"]["emergency_saves"] == 1)
man = json.load(open(os.path.join(ckpt, "preempt_step2", "manifest.json")))
check("decision recorded in manifest",
      man["coordination"]["decision"] == "SAVE"
      and man["coordination"]["step"] == 2, str(man["coordination"]))
check("report has coordination section",
      rep["coordination"]["counters"]["collectives"] >= 1)
eng.shutdown()

# --- 3. async save: background commit; crash between stage and commit
print("3) async manifest-committed save + stage-crash fallback")
from deepspeed_tpu.resilience import FaultInjector, set_injector  # noqa: E402

eng, *_ = ds.initialize(
    model=TransformerLM(get_preset("tiny")),
    config=config("b.json", checkpoint={"async_save": True}))
train(eng, 2)
eng.save_checkpoint(ckpt + "2")
eng._primary_mgr.drain()
from deepspeed_tpu.resilience.manager import verify_tag_dir  # noqa: E402

ok, why = verify_tag_dir(os.path.join(ckpt + "2", "global_step2"))
check("async save committed + verified", ok, why)
man2 = json.load(open(os.path.join(ckpt + "2", "global_step2",
                                   "manifest.json")))
check("async manifest records the STAGED step", man2["global_steps"] == 2)
train(eng, 1)
set_injector(FaultInjector([{"kind": "io_error", "site": "async_commit"}]))
eng.save_checkpoint(ckpt + "2")
eng._primary_mgr.drain(raise_on_error=False)
set_injector(None)
eng.shutdown()
eng2, *_ = ds.initialize(
    model=TransformerLM(get_preset("tiny")),
    config=config("c.json", checkpoint={"async_save": True}))
path, _ = eng2.load_checkpoint(ckpt + "2")
check("restart-and-load fell back to the previous verified tag",
      path is not None and path.endswith("global_step2")
      and eng2.global_steps == 2, f"loaded {path}")
losses = train(eng2, 1, seed=3)
check("training resumes finite after fallback", np.isfinite(losses[0]))
eng2.shutdown()

# --- 4. heartbeat + hang watchdog: stuck collective -> coordinated ABORT
print("4) hung collective -> watchdog -> coordinated abort")
from deepspeed_tpu.resilience import CoordinatedAbort  # noqa: E402

eng3, *_ = ds.initialize(
    model=TransformerLM(get_preset("tiny")),
    config=config("d.json",
                  heartbeat={"enabled": True,
                             "dir": os.path.join(work, "hb"),
                             "interval_s": 0.05, "poll_s": 0.05,
                             "deadline_s": 60.0,
                             "collective_deadline_s": 0.15},
                  faults=[{"kind": "slow_collective", "delay_s": 0.7}]))
t0 = time.time()
try:
    train(eng3, 3)
    check("hung collective aborted", False)
except CoordinatedAbort as e:
    check("hung collective -> CoordinatedAbort", "hang" in str(e), str(e)[:90])
rep3 = eng3.resilience_report()
check("watchdog classified the collective",
      "all_reduce_host" in rep3["heartbeat"]["last_cause"],
      rep3["heartbeat"]["last_cause"][:90])
hb = json.load(open(os.path.join(work, "hb", "heartbeat_0.json")))
check("heartbeat liveness file on disk", hb["rank"] == 0 and hb["pid"] > 0)
eng3.shutdown()
print(f"ALL CHECKS PASSED ({time.time() - t0:.1f}s tail) work={work}")
